"""Bench trajectory guard: diff the newest two BENCH_r*.json rounds.

The flat-MFU problem ROADMAP item 1 tracks (0.296 -> 0.301 -> 0.297
across re-anchors) was only visible at re-anchor time because nothing
diffed consecutive bench rounds.  This prints a one-line verdict per
tracked metric — MFU, images/sec/chip, and (when a round records them)
collective bytes and compile/retrace counts — plus an overall line
check.sh surfaces on every PR.  Rounds that record a per-program
``comms`` block (bench.py) additionally get per-program collective
bytes/step and overlap_score deltas, and a newest round that ran
``mode: single_step`` is flagged "campaign unproven" — the scanned
overlap path was never dispatched, so its numbers prove nothing about
latency hiding.  Rounds fed by different input paths
(``input_mode``: synthetic vs records) are flagged NOT COMPARABLE
instead of diffed — the records path does strictly more work per step.

Warn-only BY DESIGN: bench rounds run on whatever chip the round
happened to land on, so a regression here is a prompt to look, not a
gate.  Exit code is always 0 unless the repo has fewer than two rounds
to compare (also 0 — nothing to diff is not a failure).
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

#: parsed-blob keys worth trending, with the direction that counts as
#: an improvement.  Keys absent from a round (older emitters recorded
#: fewer fields; collective/compile counts only exist once a round runs
#: the audit sentinels) are reported as such, never a crash.
TRACKED: tuple[tuple[str, str, bool], ...] = (
    ("mfu", "MFU", True),
    ("value", "images/sec/chip", True),
    ("vs_baseline", "vs_baseline", True),
    ("collective_bytes", "collective bytes", False),
    ("compile_count", "compiles", False),
    ("retrace_count", "retraces", False),
)

#: relative change below this is noise, not a verdict
EPSILON = 0.005


#: per-program comms-block keys worth trending (bench.py comms_block),
#: with the direction that counts as an improvement.  overlap_score is
#: the DLC512-ratcheted schedule-slack number; bytes are per step so
#: single- and multi-step rounds compare directly.
COMMS_TRACKED: tuple[tuple[str, str, bool], ...] = (
    ("collective_bytes_per_step", "collective bytes/step", False),
    ("overlap_score", "overlap_score", True),
)


def comms_diff(old: dict, new: dict) -> tuple[list[str], list[str]]:
    """(regressed_labels, lines) diffing the per-program ``comms`` block
    between two rounds.  Programs are matched by name; a program or the
    whole block missing from one side is reported, never a crash (older
    emitters predate the block)."""
    a, b = old.get("comms"), new.get("comms")
    if not isinstance(a, dict) and not isinstance(b, dict):
        return [], []
    if not isinstance(a, dict) or not isinstance(b, dict):
        which = "the old round" if not isinstance(a, dict) else "the new round"
        return [], [f"  comms: not recorded in {which}"]
    regressed, lines = [], []
    for name in sorted(set(a) | set(b)):
        pa, pb = a.get(name), b.get(name)
        if not isinstance(pa, dict) or not isinstance(pb, dict):
            which = "the old round" if not isinstance(pa, dict) else "the new round"
            lines.append(f"  comms[{name}]: not recorded in {which}")
            continue
        for key, label, higher in COMMS_TRACKED:
            verdict, line = diff_line(
                key, label, higher, {key: pa.get(key)}, {key: pb.get(key)}
            )
            lines.append(f"  comms[{name}] {line.strip()}")
            if verdict == "regressed":
                regressed.append(f"comms[{name}].{label}")
    return regressed, lines


def campaign_unproven(new: dict) -> str | None:
    """A newest round that ran ``mode: single_step`` never exercised the
    scanned multi-step dispatch path the comms-overlap campaign targets,
    so its numbers prove nothing about latency hiding — flag it rather
    than letting a flat diff read as 'overlap still fine'."""
    if new.get("mode") == "single_step":
        return (
            "campaign unproven: newest round ran mode=single_step, the "
            "comms-overlap path was never dispatched"
        )
    return None


def mode_regression(old: dict, new: dict) -> str | None:
    """A round falling out of the scanned multi-step dispatch mode back
    to single_step is a qualitative regression no numeric diff shows
    (the headline throughput may barely move on a lucky draw, but the
    overlap architecture stopped winning).  Returns the verdict fragment
    to fold into the headline, or None."""
    a, b = old.get("mode"), new.get("mode")
    if not isinstance(a, str) or not isinstance(b, str):
        return None
    if a.startswith("multi_step") and b == "single_step":
        return f"mode regressed ({a} -> {b})"
    return None


def input_mode_mismatch(old: dict, new: dict) -> str | None:
    """Rounds fed by different input paths (synthetic in-memory batches
    vs the datastream records path) measure different things: records
    adds disk reads, shuffle-permutation gathers, and decode to every
    step, so a numeric diff between the two is meaningless rather than a
    regression.  Returns the NOT-COMPARABLE fragment, or None when the
    modes match (or either round predates the field)."""
    a, b = old.get("input_mode"), new.get("input_mode")
    if not isinstance(a, str) or not isinstance(b, str) or a == b:
        return None
    return f"input mode changed ({a} -> {b})"


def bench_rounds(root: Path) -> list[Path]:
    """BENCH_r*.json sorted by round number (the filename's integer,
    not mtime — re-checkouts touch everything)."""

    def round_no(path: Path) -> int:
        m = re.search(r"BENCH_r(\d+)\.json$", path.name)
        return int(m.group(1)) if m else -1

    return sorted(root.glob("BENCH_r*.json"), key=round_no)


def parsed_metrics(path: Path) -> dict:
    try:
        blob = json.loads(path.read_text())
    except (OSError, ValueError) as e:
        print(f"bench-compare: unreadable {path.name}: {e}")
        return {}
    parsed = blob.get("parsed")
    return dict(parsed) if isinstance(parsed, dict) else {}


def diff_line(key: str, label: str, higher_is_better: bool,
              old: dict, new: dict) -> tuple[str, str]:
    """(verdict, line) for one metric; verdict in improved/regressed/
    flat/missing."""
    a, b = old.get(key), new.get(key)
    if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
        which = (
            "either round" if a is None and b is None
            else ("the old round" if a is None else "the new round")
        )
        return "missing", f"  {label}: not recorded in {which}"
    if a == 0:
        rel = 0.0 if b == 0 else float("inf")
    else:
        rel = (b - a) / abs(a)
    if abs(rel) < EPSILON:
        return "flat", f"  {label}: {a} -> {b} (flat)"
    better = (rel > 0) == higher_is_better
    verdict = "improved" if better else "regressed"
    return verdict, f"  {label}: {a} -> {b} ({rel:+.1%}, {verdict})"


def main(argv: list[str] | None = None) -> int:
    root = Path(argv[0]) if argv else Path(__file__).resolve().parent.parent
    rounds = bench_rounds(root)
    if len(rounds) < 2:
        print(f"bench-compare: {len(rounds)} round(s) under {root}, nothing to diff")
        return 0
    old_path, new_path = rounds[-2], rounds[-1]
    old, new = parsed_metrics(old_path), parsed_metrics(new_path)
    lines, verdicts = [], []
    for key, label, higher in TRACKED:
        verdict, line = diff_line(key, label, higher, old, new)
        lines.append(line)
        if verdict in ("improved", "regressed", "flat"):
            verdicts.append((label, verdict))
    comms_regressed, comms_lines = comms_diff(old, new)
    lines.extend(comms_lines)
    if isinstance(old.get("mode"), str) or isinstance(new.get("mode"), str):
        lines.append(f"  mode: {old.get('mode')} -> {new.get('mode')}")
    if isinstance(old.get("input_mode"), str) or isinstance(
        new.get("input_mode"), str
    ):
        lines.append(
            f"  input mode: {old.get('input_mode')} -> {new.get('input_mode')}"
        )
    regressed = [label for label, v in verdicts if v == "regressed"]
    regressed += comms_regressed
    improved = [label for label, v in verdicts if v == "improved"]
    mode_note = mode_regression(old, new)
    input_note = input_mode_mismatch(old, new)
    unproven_note = campaign_unproven(new)
    if input_note:
        # Different input paths: the numeric verdicts below are apples
        # to oranges — say so instead of calling either direction a
        # regression or an improvement.
        headline = f"NOT COMPARABLE ({input_note})"
    elif mode_note:
        # Name the dispatch-mode fallback explicitly: losing multi_step is
        # a regression even when every numeric metric reads flat.
        extra = f", {', '.join(regressed)}" if regressed else ""
        headline = f"REGRESSED ({mode_note}{extra})"
    elif regressed:
        headline = f"REGRESSED ({', '.join(regressed)})"
    elif improved:
        headline = f"improved ({', '.join(improved)})"
    else:
        headline = "flat"
    if unproven_note:
        # Not a numeric verdict: the round's dispatch mode means the
        # overlap campaign's claim simply went untested this round.
        headline = f"{headline}; {unproven_note}"
    print(
        f"bench-compare: {old_path.name} -> {new_path.name}: {headline} [warn-only]"
    )
    for line in lines:
        print(line)
    return 0  # trajectory guard, not a gate — see module docstring


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
