#!/usr/bin/env bash
# One gate, two halves: the repo-native lint pass (dlcfn lint with every
# gated pass on — DLC0xx per-file rules, DLC1xx broker-contract checker,
# DLC2xx concurrency lockset rules, DLC3xx message-shape/lifecycle
# checkers, DLC4xx JAX/SPMD trace-safety rules, DLC5xx comms/memory
# rules, DLC6xx determinism rules — ratcheted against the committed
# suppression baseline) then the dynamic gates (chaos, perf-smoke,
# compile-audit, comms-audit, replay-audit) and the tier-1 test suite —
# exactly the commands ROADMAP.md designates, so CI and a developer's
# pre-push run cannot drift apart.
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== dlcfn lint (full: --concurrency --protocol --sharding --comms --determinism, baselined) =="
python -m deeplearning_cfn_tpu.cli lint --concurrency --protocol --sharding --comms \
  --determinism --baseline scripts/lint_baseline.json || exit 1

echo "== chaos scenarios (seeded, virtual-clock — docs/RESILIENCE.md) =="
# --all includes slice-loss-live, which drives a real 2-slice SPMD trainer
# and needs 8 virtual CPU devices before the JAX backend initializes, and
# serve-replica-loss, which kills a serving replica mid-traffic and
# asserts zero lost accepted requests plus the p99 latency SLO
# (docs/SERVING.md runbook).  broker-failover runs the 1k-agent
# warm-standby soak (zero lost INSTANCE_TERMINATE, exactly-once
# re-sends) and split-brain proves epoch fencing rejects every
# stale-primary write.  shard-failover runs the sharded fleet soak
# (one shard's failover stalls only that shard; every pair auto-heals)
# and degraded-pair-heal pins the re-provision ladder (fresh standby,
# lag drained to zero, un-fenced old-term replay).
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m deeplearning_cfn_tpu.cli chaos --all --seed 0 \
  > /tmp/_chaos.json || { cat /tmp/_chaos.json; exit 1; }
python - <<'EOF' || exit 1
# The gates this script newly depends on must actually have run: --all is
# dynamic, so pin the serving SLO scenario, the control-plane failover
# pair (broker-failover's 1k-agent soak, split-brain's epoch fencing),
# the telemetry/alerting gate (alert-storm: exactly-once alerts
# through silent deaths, stragglers, and a broker failover), the
# data-plane gate (data-reshard-live: live reshard mid-epoch over real
# record shards, every record exactly once, bit-identical resume from
# the v3 envelope), and the multi-tenancy gate (sched-flash-crowd:
# the fleet arbiter preempts/restores a train slice under a serve page
# with loss continuity, zero lost requests, and crash-safe ledger
# resume — docs/SCHEDULER.md).
import json
reports = json.load(open("/tmp/_chaos.json"))
names = {r["scenario"] for r in reports}
for required in ("serve-replica-loss", "broker-failover", "split-brain",
                 "shard-failover", "degraded-pair-heal",
                 "alert-storm", "data-reshard-live", "sched-flash-crowd",
                 "gauntlet"):
    assert required in names, f"{required} missing from {sorted(names)}"
EOF
echo "chaos: all scenarios held their invariants (report: /tmp/_chaos.json)"

echo "== chaos gauntlet (composed multi-fault incident + seeded sweep) =="
# The composed-incident gate no single-subsystem scenario can see: the
# pinned 3-fault schedule (slice loss mid-epoch, broker shard failover
# in the SAME reshard pause, writer crash mid-manifest) must hold every
# cross-subsystem invariant, then a small seeded sweep perturbs fault
# timing/ordering and shrinks any failure to a minimal reproducer
# (docs/RESILIENCE.md, "Composed incidents").  Wall-budgeted: the
# full 20-seed explorer lives in tests/test_gauntlet.py -m slow.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  timeout -k 10 420 python -m deeplearning_cfn_tpu.cli gauntlet --seed 0 \
  > /tmp/_gauntlet.json || { cat /tmp/_gauntlet.json; exit 1; }
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  timeout -k 10 420 python -m deeplearning_cfn_tpu.cli gauntlet --sweep 4 --seed 100 \
  > /tmp/_gauntlet_sweep.json || { cat /tmp/_gauntlet_sweep.json; exit 1; }
echo "gauntlet: pinned incident + 4-seed sweep held every cross-subsystem invariant (reports: /tmp/_gauntlet.json, /tmp/_gauntlet_sweep.json)"

echo "== SLO rule schema (obs/slo.py DEFAULT_RULES vs METRIC_REGISTRY) =="
# Every shipped alert rule must parse and reference a registered
# exporter family — a rule over a typo'd metric would silently never
# fire (docs/OBSERVABILITY.md, "Writing an SLO rule").
python - <<'EOF' || exit 1
from deeplearning_cfn_tpu.obs.slo import validate_rules
errors = validate_rules()
for e in errors:
    print(f"slo-schema: {e}")
assert not errors, f"{len(errors)} invalid SLO rule(s)"
EOF
echo "slo-schema: all default rules valid against the metric registry"

echo "== bench trajectory (newest two BENCH rounds, warn-only) =="
python scripts/bench_compare.py || true

echo "== perf-smoke (compact-dtype input path, structural asserts only) =="
# 8 virtual devices so the comms_budget stage can rebuild the audited
# fsdp step and hold its collective_bytes to the committed budget.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python scripts/perf_smoke.py || exit 1

echo "== compile-audit sentinel (steady-state zero-retrace + donation) =="
# Real Trainer.fit() + multi-step path on CPU: any function recompiling
# after warmup (DLC410) or a step donating zero bytes (DLC411) fails here
# unless baselined (docs/STATIC_ANALYSIS.md retrace runbook).
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python scripts/compile_audit.py --baseline scripts/lint_baseline.json \
  > /tmp/_compile_audit.json || { cat /tmp/_compile_audit.json; exit 1; }
echo "compile-audit: steady-state zero retrace, donation effective (report: /tmp/_compile_audit.json)"

echo "== comms-audit sentinel (HLO collective + HBM budget + overlap ratchet) =="
# Lowers the real fsdp train step, multi-step scan body, serve decode,
# and the dp comms-overlap pair on 8 virtual devices and reads the HLO:
# collective bytes/count over the committed budget (DLC510), an
# all-gather fsdp doesn't predict (DLC511), or a schedule overlap_score
# below the committed number / a *_overlap program not strictly beating
# its monolithic baseline (DLC512) fails here unless baselined
# (docs/STATIC_ANALYSIS.md comms runbook).
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python scripts/comms_audit.py --baseline scripts/lint_baseline.json \
  > /tmp/_comms_audit.json || { cat /tmp/_comms_audit.json; exit 1; }
echo "comms-audit: collective/HBM budgets within ratchet (report: /tmp/_comms_audit.json)"

echo "== replay-audit sentinel (double-run byte-determinism per seed) =="
# Every registered chaos scenario plus soak_failover/soak_fleet runs
# twice per seed in-process; canonical report bytes must match exactly.
# A divergence is DLC610 with the first-divergence path and fails here
# unless baselined (docs/STATIC_ANALYSIS.md replay runbook).
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python scripts/replay_audit.py --baseline scripts/lint_baseline.json \
  > /tmp/_replay_audit.json || { cat /tmp/_replay_audit.json; exit 1; }
echo "replay-audit: every scenario and soak byte-identical across double runs (report: /tmp/_replay_audit.json)"

echo "== tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
  -m 'not slow' --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
