#!/usr/bin/env bash
# first-network-session.sh — the one-command proof owed the moment ANY
# environment has a network (round-2 verdict Missing #3).
#
# The build environment has no egress, so the real-data acceptance
# numbers — the reference's published 92% CIFAR-10 train accuracy
# (README.md:141) and real-COCO detection (prepare-s3-bucket.sh:23-50) —
# cannot be produced in-env.  Every pipeline stage IS in place and
# format-exact under test; this script turns "pipeline in place" into
# "capability demonstrated" as a single command:
#
#   download (CIFAR-10, MNIST, COCO val2017 subset)
#     -> dlcfn convert (public layouts -> DLC1 records)
#     -> CIFAR-10 VGG-11 to --target_accuracy 0.92 with held-out eval
#        (cosine LR + pad-crop + flip: the convergence recipe; constant
#        LR + flip-only does not reliably reach the reference's number)
#     -> COCO-subset RetinaNet training + mAP@0.5 eval
#     -> ImageNet ResNet-50 to 76% top-1 (the north star) — only when
#        DLCFN_FNS_SRC holds an imagenet/ ImageFolder tree (ImageNet's
#        download is authenticated; it cannot be fetched here) and
#        "imagenet" is in DLCFN_FNS_DATASETS
#
# Usage:  scripts/first-network-session.sh [WORK_DIR]
#
# Knobs (all env, defaulted for the real run; the in-env smoke test
# shrinks them):
#   DLCFN_FNS_SRC       pre-populated source dir -> skip all downloads
#   DLCFN_FNS_DATASETS  subset of "cifar mnist coco imagenet"
#                       (default: "cifar mnist coco" — imagenet is
#                       opt-in because its source cannot be downloaded)
#   DLCFN_FNS_TARGET    CIFAR target accuracy   (default 0.92)
#   DLCFN_FNS_STEPS     max CIFAR train steps   (default 40000)
#   DLCFN_FNS_DET_STEPS COCO train steps        (default 2000)
#   DLCFN_FNS_COCO_N    COCO subset image count (default 256)
#   DLCFN_FNS_SIZE      COCO record image size  (default 512)
#   DLCFN_FNS_IN_TARGET ImageNet top-1 target   (default 0.76)
#   DLCFN_FNS_IN_STEPS  max ImageNet steps      (default 450000 = 90
#                       epochs of 1.28M images at global batch 256)
#   DLCFN_FNS_IN_BATCH  ImageNet global batch   (default 256)
#   DLCFN_FNS_IN_MARGIN train-record crop margin px (default 32:
#                       256px stored, 224px random-crop windows)
set -euo pipefail

WORK="${1:-${DLCFN_FNS_WORK:-/tmp/dlcfn-first-network}}"
SRC="${DLCFN_FNS_SRC:-$WORK/src}"
DATASETS="${DLCFN_FNS_DATASETS:-cifar mnist coco}"
TARGET="${DLCFN_FNS_TARGET:-0.92}"
STEPS="${DLCFN_FNS_STEPS:-40000}"
DET_STEPS="${DLCFN_FNS_DET_STEPS:-2000}"
COCO_N="${DLCFN_FNS_COCO_N:-256}"
SIZE="${DLCFN_FNS_SIZE:-512}"
IN_TARGET="${DLCFN_FNS_IN_TARGET:-0.76}"
IN_STEPS="${DLCFN_FNS_IN_STEPS:-450000}"
IN_BATCH="${DLCFN_FNS_IN_BATCH:-256}"
IN_MARGIN="${DLCFN_FNS_IN_MARGIN:-32}"
IN_SIZE="${DLCFN_FNS_IN_SIZE:-224}"
PY="${PYTHON:-python3}"
DLCFN="$PY -m deeplearning_cfn_tpu.cli"
mkdir -p "$WORK" "$SRC" "$WORK/data" "$WORK/metrics"
SUMMARY="$WORK/summary.json"
echo "{}" > "$SUMMARY"

note() { echo ">>> $*" >&2; }
record() {  # record KEY JSON-FILE: merge a result into the summary
  $PY - "$SUMMARY" "$1" "$2" <<'EOF'
import json, sys
summary_path, key, result_path = sys.argv[1:4]
s = json.load(open(summary_path))
s[key] = json.load(open(result_path))
json.dump(s, open(summary_path, "w"), indent=2)
EOF
}

has() { case " $DATASETS " in *" $1 "*) return 0;; *) return 1;; esac; }

# ---------------------------------------------------------------- download
if [ -z "${DLCFN_FNS_SRC:-}" ]; then
  note "stage 1/3: download into $SRC"
  if has cifar && [ ! -d "$SRC/cifar/cifar-10-batches-py" ]; then
    mkdir -p "$SRC/cifar"
    curl -fL --retry 3 https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz \
      | tar xz -C "$SRC/cifar"
  fi
  if has mnist && [ ! -f "$SRC/mnist/train-images-idx3-ubyte.gz" ]; then
    mkdir -p "$SRC/mnist"
    for f in train-images-idx3-ubyte train-labels-idx1-ubyte \
             t10k-images-idx3-ubyte t10k-labels-idx1-ubyte; do
      curl -fL --retry 3 -o "$SRC/mnist/$f.gz" \
        "https://storage.googleapis.com/cvdf-datasets/mnist/$f.gz"
    done
  fi
  # Completion marker, not the annotations file: a run interrupted mid
  # image download must re-enter this block on rerun.
  if has coco && [ ! -f "$SRC/coco/.download-complete" ]; then
    mkdir -p "$SRC/coco/train" "$SRC/coco/val"
    curl -fL --retry 3 -o "$WORK/ann.zip" \
      "http://images.cocodataset.org/annotations/annotations_trainval2017.zip"
    $PY - "$WORK/ann.zip" "$SRC/coco" <<'EOF'
import sys, zipfile
zf, out = sys.argv[1:3]
with zipfile.ZipFile(zf) as z:
    with z.open("annotations/instances_val2017.json") as f, \
         open(f"{out}/instances_val2017.json", "wb") as g:
        g.write(f.read())
EOF
    # Subset: first COCO_N annotated images, 80/20 train/val dirs.
    $PY - "$SRC/coco" "$COCO_N" <<'EOF' > "$WORK/coco-files.txt"
import json, sys
root, n = sys.argv[1], int(sys.argv[2])
ann = json.load(open(f"{root}/instances_val2017.json"))
with_anns = {a["image_id"] for a in ann["annotations"]}
names = [i["file_name"] for i in ann["images"] if i["id"] in with_anns][:n]
split = max(1, int(len(names) * 0.8))
for i, name in enumerate(names):
    print(("train" if i < split else "val") + " " + name)
EOF
    while read -r split name; do
      [ -s "$SRC/coco/$split/$name" ] && continue  # resume partial runs
      curl -fL --retry 3 -o "$SRC/coco/$split/$name" \
        "http://images.cocodataset.org/val2017/$name"
    done < "$WORK/coco-files.txt"
    touch "$SRC/coco/.download-complete"
  fi
else
  note "stage 1/3: using pre-populated sources in $SRC (no downloads)"
fi

# ----------------------------------------------------------------- convert
note "stage 2/3: convert public layouts -> DLC1 records"
if has cifar; then
  $DLCFN convert --format cifar10 --src "$SRC/cifar" --out "$WORK/data/cifar" \
    > "$WORK/convert-cifar.json"
  record convert_cifar "$WORK/convert-cifar.json"
fi
if has mnist; then
  $DLCFN convert --format mnist --src "$SRC/mnist" --out "$WORK/data/mnist" \
    > "$WORK/convert-mnist.json"
  record convert_mnist "$WORK/convert-mnist.json"
fi
if has imagenet; then
  # ImageNet arrives via DLCFN_FNS_SRC only (authenticated download):
  # $SRC/imagenet/{train,val}/<class>/*.JPEG, torchvision layout.
  [ -d "$SRC/imagenet/train" ] || {
    note "imagenet requested but $SRC/imagenet/train missing"; exit 1; }
  # Train records carry a crop margin (stored 224+IN_MARGIN px) so every
  # epoch sees fresh random 224px windows; val records are exact-size
  # (the standard center-crop eval transform, baked at ingest).
  $DLCFN convert --format imagefolder --src "$SRC/imagenet/train" \
    --out "$WORK/data/imagenet" --size "$IN_SIZE" --margin "$IN_MARGIN" \
    --split train > "$WORK/convert-imagenet-train.json"
  if [ -d "$SRC/imagenet/val" ]; then
    # Same dir as train: the examples' eval reads --data_dir's val split
    # (the pipeline resolves each split's record shape independently).
    $DLCFN convert --format imagefolder --src "$SRC/imagenet/val" \
      --out "$WORK/data/imagenet" --size "$IN_SIZE" --split val \
      > "$WORK/convert-imagenet-val.json"
    record convert_imagenet_val "$WORK/convert-imagenet-val.json"
  fi
  record convert_imagenet_train "$WORK/convert-imagenet-train.json"
fi
if has coco; then
  # --masks: instance bitmaps go into the records too (the flagship is
  # MODE_MASK=True, run.sh:86).
  $DLCFN convert --format coco --src "$SRC/coco/train" \
    --annotations "$SRC/coco/instances_val2017.json" \
    --out "$WORK/data/coco" --size "$SIZE" --split train --masks \
    > "$WORK/convert-coco-train.json"
  # Val masks at stride 2: COCO mask mAP is scored at image resolution,
  # so the GT rasters backing the claimed number are high-fidelity
  # (train stays at stride 8, the prototype-loss resolution).
  $DLCFN convert --format coco --src "$SRC/coco/val" \
    --annotations "$SRC/coco/instances_val2017.json" \
    --out "$WORK/data/coco" --size "$SIZE" --split val --masks \
    --mask-stride 2 \
    > "$WORK/convert-coco-val.json"
  record convert_coco_train "$WORK/convert-coco-train.json"
  record convert_coco_val "$WORK/convert-coco-val.json"
fi

# ------------------------------------------------------------------- train
note "stage 3/3: train + evaluate"
if has cifar; then
  # The reference's published number: 92% CIFAR-10 accuracy
  # (README.md:141), here with a held-out eval as well.  The recipe is
  # the full one — cosine LR decay + pad-4 random crop + flip; constant
  # LR with flip alone does not reliably converge to 92%.
  $PY -m deeplearning_cfn_tpu.examples.cifar10_train --model vgg11 \
    --data_dir "$WORK/data/cifar" --augment_flip --augment_crop \
    --lr_schedule cosine --warmup_steps 500 --weight_decay 5e-4 \
    --target_accuracy "$TARGET" --steps "$STEPS" --eval_steps 20 \
    --metrics_dir "$WORK/metrics" \
    ${DLCFN_FNS_BATCH:+--global_batch_size "$DLCFN_FNS_BATCH"} \
    > "$WORK/train-cifar.out"
  tail -n1 "$WORK/train-cifar.out" | $PY -c \
    'import json,sys,ast; json.dump(ast.literal_eval(sys.stdin.read()), sys.stdout)' \
    > "$WORK/train-cifar.json"
  record cifar "$WORK/train-cifar.json"
fi

if has imagenet; then
  # The north star: ResNet-50 -> 76% top-1.  The exact recipe: stepped
  # LR decay at 50/75/90% of the run (the run.sh:93 shape at the classic
  # 30/60/80-of-90-epoch milestones), 5-epoch warmup, weight decay 1e-4
  # on kernels only (norm scales/biases mask-excluded — the canonical
  # recipe does not reach 76% without it), random-crop from margin
  # records + flip, label smoothing 0.1 (in the example), batch 256 at
  # base LR 0.1.  Held-out top-1 runs every ~epoch on a fast subsample;
  # the TARGET GATE and the final claimed number eval the FULL val split.
  EPOCH_STEPS=$((1281167 / IN_BATCH))
  $PY -m deeplearning_cfn_tpu.examples.resnet_imagenet --depth 50 \
    --data_dir "$WORK/data/imagenet" --image_size "$IN_SIZE" \
    --augment_crop --augment_flip \
    --lr_schedule step --warmup_steps $((EPOCH_STEPS * 5)) \
    --weight_decay 1e-4 \
    --learning_rate 0.1 --global_batch_size "$IN_BATCH" \
    --target_accuracy "$IN_TARGET" --steps "$IN_STEPS" \
    --eval_every "$EPOCH_STEPS" --eval_steps 64 \
    --metrics_dir "$WORK/metrics" \
    --checkpoint_dir "$WORK/ckpt/imagenet" \
    > "$WORK/train-imagenet.out"
  tail -n1 "$WORK/train-imagenet.out" | $PY -c \
    'import json,sys,ast; json.dump(ast.literal_eval(sys.stdin.read()), sys.stdout)' \
    > "$WORK/train-imagenet.json"
  record imagenet "$WORK/train-imagenet.json"
fi

if has coco; then
  # Pretrained-backbone transfer (run.sh:94 BACKBONE.WEIGHTS analog):
  # when the imagenet stage trained a ResNet-50 classifier, the detector
  # starts from its backbone instead of from scratch.
  BACKBONE_ARGS=""
  if [ -d "$WORK/ckpt/imagenet" ] && \
     [ "${DLCFN_FNS_DET_BACKBONE:-resnet50}" = "resnet50" ]; then
    BACKBONE_ARGS="--backbone_ckpt $WORK/ckpt/imagenet"
  fi
  $PY -m deeplearning_cfn_tpu.examples.detection_train \
    --data_dir "$WORK/data/coco" --image_size "$SIZE" \
    --steps "$DET_STEPS" --eval_steps 10 --max_boxes 50 --masks \
    --metrics_dir "$WORK/metrics" $BACKBONE_ARGS \
    ${DLCFN_FNS_DET_BATCH:+--global_batch_size "$DLCFN_FNS_DET_BATCH"} \
    ${DLCFN_FNS_DET_BACKBONE:+--backbone "$DLCFN_FNS_DET_BACKBONE"} \
    > "$WORK/train-coco.out"
  tail -n1 "$WORK/train-coco.out" | $PY -c \
    'import json,sys,ast; json.dump(ast.literal_eval(sys.stdin.read()), sys.stdout)' \
    > "$WORK/train-coco.json"
  record coco "$WORK/train-coco.json"
fi

note "done; summary:"
cat "$SUMMARY"
