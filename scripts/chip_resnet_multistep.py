"""Measure cross-iteration fusion at the ResNet-50 bench shape.

The last named lever against the documented HBM ceiling
(docs/BENCH_NOTES.md): put k consecutive training iterations inside ONE
compiled program (Trainer.multi_step_fn — the only form of
cross-iteration fusion XLA can express; separate dispatches are separate
executables) and compare per-step wallclock and cost-model bytes against
the single-step program.  Any cross-iteration reuse XLA can schedule
(param re-reads, optimizer-state traffic) shows up as fewer
bytes-per-step and/or faster steps; if bytes/step are identical the
lever is structurally dead for this workload.

Run on the real chip: PYTHONPATH=.:$PYTHONPATH python scripts/chip_resnet_multistep.py
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning_cfn_tpu.examples.common import enable_compile_cache
from deeplearning_cfn_tpu.models.resnet import ResNet50
from deeplearning_cfn_tpu.parallel.mesh import MeshSpec, build_mesh
from deeplearning_cfn_tpu.train.trainer import Trainer, TrainerConfig
from deeplearning_cfn_tpu.utils.compat import set_mesh

enable_compile_cache()

BATCH = 128
SIZE = 224
WARM, MEAS = 3, 10


def make_trainer():
    mesh = build_mesh(MeshSpec.data_parallel(len(jax.devices())))
    return Trainer(
        ResNet50(dtype=jnp.bfloat16),
        mesh,
        TrainerConfig(
            strategy="dp", learning_rate=0.1, has_train_arg=True,
            label_smoothing=0.1,
        ),
    )


def measure(k: int) -> dict:
    trainer = make_trainer()
    rng = np.random.default_rng(0)
    x1 = jnp.asarray(
        rng.standard_normal((BATCH, SIZE, SIZE, 3)), jnp.bfloat16
    )
    y1 = jnp.asarray(rng.integers(0, 1000, size=BATCH), jnp.int32)
    state = trainer.init(jax.random.key(0), x1)
    with set_mesh(trainer.mesh):
        if k == 1:
            fn = trainer.step_fn
            args = (
                jax.device_put(x1, trainer.batch_sharding),
                jax.device_put(y1, trainer.batch_sharding),
            )
        else:
            fn = trainer.multi_step_fn(k)
            # Distinct data per scan slice: identical slices could in
            # principle be exploited (aliased broadcast buffers), which
            # would flatter the measurement.
            xs = jnp.asarray(
                rng.standard_normal((k, BATCH, SIZE, SIZE, 3)), jnp.bfloat16
            )
            ys = jnp.asarray(
                rng.integers(0, 1000, size=(k, BATCH)), jnp.int32
            )
            args = (xs, ys)
        lowered = fn.lower(state, *args)
        compiled = lowered.compile()
        cost = compiled.cost_analysis() or {}
        for _ in range(WARM):
            state, out = fn(state, *args)
        # float() forces the readback; relay block_until_ready lies.
        float(np.asarray(jax.device_get(out))[-1] if k > 1 else out["loss"])
        t0 = time.perf_counter()
        for _ in range(MEAS):
            state, out = fn(state, *args)
        float(np.asarray(jax.device_get(out))[-1] if k > 1 else out["loss"])
        dt = time.perf_counter() - t0
    steps = MEAS * k
    return {
        "k": k,
        "ms_per_step": round(1000 * dt / steps, 2),
        "images_per_sec": round(BATCH * steps / dt, 1),
        # cost_analysis counts a scan BODY once regardless of trip count,
        # so for k>1 this is (approximately) the per-iteration traffic
        # directly — equal numbers across k mean XLA found no
        # cross-iteration byte reuse.
        "cost_bytes_per_iter": (
            round(cost["bytes accessed"] / 1e9, 2)
            if "bytes accessed" in cost
            else None
        ),
        "cost_flops_per_iter": (
            round(cost["flops"] / 1e12, 3) if "flops" in cost else None
        ),
    }


if __name__ == "__main__":
    for k in (1, 2, 4):
        print(json.dumps(measure(k), allow_nan=False))
