"""One-shot on-chip measurement: python chip_measure.py <mode> [args]

Modes:
  throughput <size> <batch> <seq> [fused|adafactor]  — warmup+timed train steps
  fit <size> <batch> <seq> [adafactor]               — init + 2 steps; FITS/OOM

The optional trailing token selects the qkv-fusion variant or the
adafactor optimizer (the memory-lean rung that admits --size 3b on the
16 GiB chip; adamw cannot hold its moment state at that scale).
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning_cfn_tpu.examples.common import enable_compile_cache
from deeplearning_cfn_tpu.train.metrics import peak_flops_per_chip
from deeplearning_cfn_tpu.models import llama
from deeplearning_cfn_tpu.parallel.mesh import MeshSpec, build_mesh
from deeplearning_cfn_tpu.train.trainer import TrainerConfig

enable_compile_cache()

mode, size, batch, seq = sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4])
fused = "fused" in sys.argv[5:]
optimizer = "adafactor" if "adafactor" in sys.argv[5:] else "adamw"

cfg = {"435m": llama.LlamaConfig.m435, "1b": llama.LlamaConfig.b1,
       "3b": llama.LlamaConfig.b3}[size](seq_len=seq)
if fused:
    import dataclasses
    cfg = dataclasses.replace(cfg, fused_qkv=True)

mesh = build_mesh(MeshSpec.fsdp_parallel(len(jax.devices())))
trainer = llama.make_trainer(
    cfg, mesh, TrainerConfig(strategy="fsdp", optimizer=optimizer, learning_rate=1e-4)
)
rng = np.random.default_rng(0)
tok = jax.device_put(
    jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32),
    trainer.batch_sharding,
)
tgt = jax.device_put(jnp.roll(tok, -1, axis=1), trainer.batch_sharding)

try:
    state = trainer.init(jax.random.key(0), tok[:1])
    if mode == "fit":
        for _ in range(2):
            state, metrics = trainer.train_step(state, tok, tgt)
        loss = float(metrics["loss"])
        print(json.dumps({"mode": "fit", "size": size, "batch": batch,
                          "seq": seq, "result": "FITS", "loss": round(loss, 3)}))
        sys.exit(0)
    WARM, MEAS = 3, 10
    for _ in range(WARM):
        state, metrics = trainer.train_step(state, tok, tgt)
    float(metrics["loss"])  # forced readback: relay block_until_ready lies
    t0 = time.perf_counter()
    for _ in range(MEAS):
        state, metrics = trainer.train_step(state, tok, tgt)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    toks = batch * seq * MEAS / dt
    flops_tok = llama.train_flops_per_token(cfg, seq)
    # Device-kind dispatch, not a hardcoded v5e constant: the same
    # harness must report honest MFU on v4/v5p chips too.
    peak = peak_flops_per_chip(jax.devices()[0]) or float("nan")
    mfu = flops_tok * batch * seq * MEAS / dt / peak
    print(json.dumps({
        "mode": "throughput", "size": size, "batch": batch, "seq": seq,
        "fused": fused, "optimizer": optimizer, "tokens_per_sec": round(toks, 1),
        "ms_per_step": round(1000 * dt / MEAS, 1), "mfu": round(mfu, 4),
        "loss": round(loss, 3),
    }))
except Exception as e:
    msg = str(e)
    oom = "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg or "exceeds" in msg
    print(json.dumps({"mode": mode, "size": size, "batch": batch, "seq": seq,
                      "result": "OOM" if oom else "ERROR",
                      "detail": msg[:300]}))
    sys.exit(2)
