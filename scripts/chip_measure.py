"""One-shot on-chip measurement: python chip_measure.py <mode> [args]

Modes:
  throughput <size> <batch> <seq> [fused|adafactor]  — warmup+timed train steps
  fit <size> <batch> <seq> [adafactor]               — init + 2 steps; FITS/OOM
  decode <size> <batch> <prompt_len> [new_tokens]    — serving tokens/s + MBU

The optional trailing token selects the qkv-fusion variant or the
adafactor optimizer (the memory-lean rung that admits --size 3b on the
16 GiB chip; adamw cannot hold its moment state at that scale).

``decode`` measures the llama_decode.generate path (prefill + lax.scan
decode, KV cache, greedy): tokens/s and MBU — model-bandwidth
utilization, param-bytes-only numerator — because each decode step must
stream the weights from HBM once, bandwidth (not the MXU) is the
ceiling that matters for serving.
"""
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning_cfn_tpu.examples.common import enable_compile_cache
from deeplearning_cfn_tpu.train.metrics import (
    json_safe,
    peak_flops_per_chip,
    utilization,
)
from deeplearning_cfn_tpu.models import llama
from deeplearning_cfn_tpu.parallel.mesh import MeshSpec, build_mesh
from deeplearning_cfn_tpu.train.trainer import TrainerConfig

enable_compile_cache()

mode, size, batch, seq = sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4])
fused = "fused" in sys.argv[5:]
optimizer = "adafactor" if "adafactor" in sys.argv[5:] else "adamw"

new_tokens = int(sys.argv[5]) if mode == "decode" and len(sys.argv) > 5 else 128
cfg = {"435m": llama.LlamaConfig.m435, "1b": llama.LlamaConfig.b1,
       "3b": llama.LlamaConfig.b3}[size](
    # decode: seq is the PROMPT length; the cache needs prompt + new room.
    seq_len=seq + new_tokens if mode == "decode" else seq
)
if fused:
    import dataclasses
    cfg = dataclasses.replace(cfg, fused_qkv=True)

if mode == "decode":
    from deeplearning_cfn_tpu.models.llama_decode import generate
    from deeplearning_cfn_tpu.train.metrics import peak_hbm_bytes_per_chip

    batch_, prompt_len = batch, seq  # positional reuse: <batch> <prompt_len>
    params = llama.init_params(cfg, jax.random.key(0))
    param_bytes = sum(p.nbytes for p in jax.tree_util.tree_leaves(params))
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(1, cfg.vocab_size, (batch_, prompt_len)), jnp.int32
    )
    assert new_tokens > 1, "decode mode needs >= 2 new tokens"
    out = generate(cfg, params, prompt, jax.random.key(1),
                   max_new_tokens=new_tokens)  # compile + warm
    np.asarray(out)
    # Prefill probe: same prompt, ONE new token.  Subtracting its time
    # isolates the decode steps — otherwise every rep charges a full
    # prefill to the per-step and MBU numbers, understating both (the
    # more the longer the prompt).
    pre = generate(cfg, params, prompt, jax.random.key(1), max_new_tokens=1)
    np.asarray(pre)
    REPS = 5
    t0 = time.perf_counter()
    for i in range(REPS):
        out = generate(cfg, params, prompt, jax.random.key(2 + i),
                       max_new_tokens=new_tokens)
    np.asarray(out)  # forced readback: relay block_until_ready lies
    dt_full = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(REPS):
        pre = generate(cfg, params, prompt, jax.random.key(2 + i),
                       max_new_tokens=1)
    np.asarray(pre)
    dt_pre = time.perf_counter() - t0
    # Relay wall-time variance can make the subtraction go negative on
    # short-prompt shapes; floor at 10% of the naive step time.
    naive = dt_full / (REPS * new_tokens)
    step_s = max((dt_full - dt_pre) / (REPS * (new_tokens - 1)), 0.1 * naive)
    toks = batch_ * new_tokens * REPS / dt_full  # end-to-end incl. prefill
    print(json.dumps(json_safe({
        "mode": "decode", "size": size, "batch": batch_,
        "prompt_len": prompt_len, "new_tokens": new_tokens,
        "param_bytes": param_bytes,
        "tokens_per_sec": round(toks, 1),
        "prefill_ms": round(1000 * dt_pre / REPS, 2),
        # Per decode STEP (= per token per stream), prefill-subtracted;
        # at B>1 each step serves B tokens, which is what
        # tokens_per_sec aggregates.
        "ms_per_step": round(1000 * step_s, 2),
        # null (not NaN) when the chip's HBM peak is unknown — the JSON
        # stays strictly parseable on CPU/GPU test backends.
        "mbu": utilization(param_bytes / step_s, peak_hbm_bytes_per_chip()),
    }), allow_nan=False))
    sys.exit(0)

mesh = build_mesh(MeshSpec.fsdp_parallel(len(jax.devices())))
trainer = llama.make_trainer(
    cfg, mesh, TrainerConfig(strategy="fsdp", optimizer=optimizer, learning_rate=1e-4)
)
rng = np.random.default_rng(0)
tok = jax.device_put(
    jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32),
    trainer.batch_sharding,
)
tgt = jax.device_put(jnp.roll(tok, -1, axis=1), trainer.batch_sharding)

try:
    state = trainer.init(jax.random.key(0), tok[:1])
    if mode == "fit":
        for _ in range(2):
            state, metrics = trainer.train_step(state, tok, tgt)
        loss = float(metrics["loss"])
        print(json.dumps(json_safe(
            {"mode": "fit", "size": size, "batch": batch,
             "seq": seq, "result": "FITS", "loss": round(loss, 3)}
        ), allow_nan=False))
        sys.exit(0)
    WARM, MEAS = 3, 10
    for _ in range(WARM):
        state, metrics = trainer.train_step(state, tok, tgt)
    float(metrics["loss"])  # forced readback: relay block_until_ready lies
    t0 = time.perf_counter()
    for _ in range(MEAS):
        state, metrics = trainer.train_step(state, tok, tgt)
    loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    toks = batch * seq * MEAS / dt
    flops_tok = llama.train_flops_per_token(cfg, seq)
    # Device-kind dispatch, not a hardcoded v5e constant: the same
    # harness must report honest MFU on v4/v5p chips too — and null (not
    # NaN) when the kind is unknown.
    mfu = utilization(
        flops_tok * batch * seq * MEAS / dt,
        peak_flops_per_chip(jax.devices()[0]),
    )
    print(json.dumps(json_safe({
        "mode": "throughput", "size": size, "batch": batch, "seq": seq,
        "fused": fused, "optimizer": optimizer, "tokens_per_sec": round(toks, 1),
        "ms_per_step": round(1000 * dt / MEAS, 1), "mfu": mfu,
        "loss": round(loss, 3),
    }), allow_nan=False))
except Exception as e:
    msg = str(e)
    oom = "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg or "exceeds" in msg
    print(json.dumps({"mode": mode, "size": size, "batch": batch, "seq": seq,
                      "result": "OOM" if oom else "ERROR",
                      "detail": msg[:300]}, allow_nan=False))
    sys.exit(2)
