"""Replay-audit CI stage: per-seed byte-determinism, proven by running twice.

Runs every registered chaos scenario plus both fleet soaks
(``soak_failover``, ``soak_fleet``) twice per seed in-process under
:mod:`analysis.replay_audit`, canonicalizes the reports to sorted-key
compact JSON, and diffs the bytes, applying the same
suppression-baseline ratchet as ``dlcfn lint``
(scripts/lint_baseline.json, DLC610 namespace only):

- a case whose two same-seed runs produce different bytes -> DLC610
  (carrying the first-divergence path) -> exit 1 (unless baselined)
- a baseline entry whose DLC610 finding no longer fires -> stale nag

Exit 0 and one JSON report line on success.  docs/STATIC_ANALYSIS.md
has the "reading a replay divergence" runbook for when this stage goes
red.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# slice-loss-live and data-reshard-live drive a real 2-slice SPMD
# trainer and need 8 virtual CPU devices before the JAX backend
# initializes — same preamble as `dlcfn chaos --all` in check.sh.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--seed",
        type=int,
        action="append",
        default=None,
        metavar="N",
        help="seed(s) to double-run at (repeatable; default: 0)",
    )
    parser.add_argument(
        "--scenario",
        action="append",
        default=None,
        metavar="NAME",
        help="replay only these scenarios (repeatable; default: all "
        "registered)",
    )
    parser.add_argument(
        "--skip-soaks",
        action="store_true",
        help="skip soak_failover/soak_fleet (scenario-only dev loop)",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help="suppression baseline (default scripts/lint_baseline.json)",
    )
    args = parser.parse_args(argv)

    import logging

    # The scenarios log their injected faults at WARNING; two full
    # passes of that firehose would drown the one JSON line this stage
    # is contracted to print.
    logging.disable(logging.WARNING)

    from deeplearning_cfn_tpu.analysis.determinism import AUDIT_RULE_IDS
    from deeplearning_cfn_tpu.analysis.replay_audit import (
        default_cases,
        run_replay_audit,
    )
    from deeplearning_cfn_tpu.analysis.runner import apply_audit_baseline

    cases = default_cases(
        scenarios=args.scenario, soaks=not args.skip_soaks
    )
    seeds = tuple(args.seed) if args.seed else (0,)
    report = run_replay_audit(cases=cases, seeds=seeds)

    # This stage owns only the dynamic DLC610 namespace; lint owns the rest.
    fresh, stale = apply_audit_baseline(
        report.violations, args.baseline, AUDIT_RULE_IDS
    )

    for rule, rel, message in stale:
        print(
            f"replay-audit: stale baseline entry: {rule} {rel}: {message}",
            file=sys.stderr,
        )
    for v in fresh:
        print(f"replay-audit: {v.format()}", file=sys.stderr)

    print(json.dumps(report.to_dict(), allow_nan=False))
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
