"""Compile-audit CI stage: steady-state zero-retrace, proven by running.

Runs the real ``Trainer.fit()`` single-step path and the bench
multi-step path for a few CPU steps under a
:class:`analysis.compile_audit.CompileWatcher`, then applies the same
suppression-baseline ratchet as ``dlcfn lint`` (scripts/lint_baseline.json):

- a function that recompiles after warmup -> DLC410 finding -> exit 1
- a step whose state donation deleted zero bytes -> DLC411 -> exit 1
- a baseline entry whose DLC41x finding no longer fires -> stale nag

Exit 0 and one JSON report line on success.  docs/STATIC_ANALYSIS.md has
the "reading a retrace report" runbook for when this stage goes red.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# The audit's question is dispatch-layer, not numerics: CPU answers it.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Honest compile counts need a cold persistent cache.
os.environ.setdefault("DLCFN_COMPILE_CACHE", "off")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--steps", type=int, default=4, help="steady-state steps")
    parser.add_argument("--warmup", type=int, default=2)
    parser.add_argument("--k", type=int, default=2, help="multi-step span")
    parser.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help="suppression baseline (default scripts/lint_baseline.json)",
    )
    args = parser.parse_args(argv)

    from deeplearning_cfn_tpu.analysis.compile_audit import (
        run_compile_audit,
        run_serve_audit,
    )
    from deeplearning_cfn_tpu.analysis.runner import apply_audit_baseline
    from deeplearning_cfn_tpu.analysis.sharding import AUDIT_RULE_IDS

    report = run_compile_audit(
        steady_steps=args.steps, warmup_steps=args.warmup, k=args.k
    )
    # The serving plane rides the same ratchet: its continuous-batching
    # decode must stay on one compiled step across mixed-length traffic.
    serve_report = run_serve_audit()
    report.paths.extend(serve_report.paths)
    report.violations.extend(serve_report.violations)
    for key in ("compile_count", "retrace_count", "backend_compiles"):
        report.watcher[key] = report.watcher.get(key, 0) + serve_report.watcher.get(
            key, 0
        )

    # This stage owns only the dynamic DLC41x namespace; lint owns the rest.
    fresh, stale = apply_audit_baseline(
        report.violations, args.baseline, AUDIT_RULE_IDS
    )

    for rule, rel, message in stale:
        print(
            f"compile-audit: stale baseline entry: {rule} {rel}: {message}",
            file=sys.stderr,
        )
    for v in fresh:
        print(f"compile-audit: {v.format()}", file=sys.stderr)

    print(json.dumps(report.to_dict(), allow_nan=False))
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
