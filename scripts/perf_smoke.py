"""Perf-smoke gate: assert the compact-dtype input path is actually taken.

Runs a tiny CPU pipeline microbench — the same uint8 synthetic stream a
real bench uses, through ``DevicePrefetcher(workers=2)`` with counters —
against a float32 baseline of identical shape, and asserts structural
properties only (byte counts, batch counts, dtype preservation).  No
wall-clock assertions: CI machines are noisy and this gate must never
flake on a slow runner; docs/PERFORMANCE.md covers how to read the
timing counters it prints.

Exit 0 and one JSON line on success; exit 1 with a message on violation.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np


BATCH = 8
IMAGE = 32
STEPS = 6
WORKERS = 2


def run_pipeline(dtype: str) -> tuple[dict, object]:
    from deeplearning_cfn_tpu.train.data import DevicePrefetcher, SyntheticDataset
    from deeplearning_cfn_tpu.train.pipeline import PipelineStats

    ds = SyntheticDataset(
        shape=(IMAGE, IMAGE, 3),
        num_classes=10,
        batch_size=BATCH,
        dtype=dtype,
        pool_batches=3,
    )
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    stats = PipelineStats(name=f"smoke-{dtype}")
    prefetcher = DevicePrefetcher(
        ds.batches(STEPS), sharding, size=2, workers=WORKERS, stats=stats
    )
    last_x = None
    n = 0
    try:
        for batch in prefetcher:
            last_x = batch.x
            n += 1
    finally:
        prefetcher.close()
    assert n == STEPS, f"{dtype}: consumed {n} batches, expected {STEPS}"
    return stats.snapshot(), last_x


def main() -> int:
    u8_snap, u8_x = run_pipeline("uint8")
    f32_snap, f32_x = run_pipeline("float32")

    failures = []
    if u8_x.dtype != jnp.uint8:
        failures.append(f"uint8 pipeline delivered {u8_x.dtype} to the device")
    if f32_x.dtype != jnp.float32:
        failures.append(f"float32 baseline delivered {f32_x.dtype}")
    if u8_snap["batches"] != STEPS or f32_snap["batches"] != STEPS:
        failures.append(
            f"batch counters diverged: u8={u8_snap['batches']} "
            f"f32={f32_snap['batches']} expected={STEPS}"
        )
    # THE gate: the compact path must move strictly fewer bytes than the
    # float32 baseline at identical shapes.  Labels (int32) are shared
    # payload, so the ratio is < 1/4 + epsilon rather than exactly 1/4.
    if not u8_snap["bytes_transferred"] < f32_snap["bytes_transferred"]:
        failures.append(
            f"compact-dtype path not taken: uint8 moved "
            f"{u8_snap['bytes_transferred']} bytes vs float32 "
            f"{f32_snap['bytes_transferred']}"
        )
    image_bytes_u8 = STEPS * BATCH * IMAGE * IMAGE * 3
    label_bytes = STEPS * BATCH * 4
    if u8_snap["bytes_transferred"] != image_bytes_u8 + label_bytes:
        failures.append(
            f"uint8 byte counter {u8_snap['bytes_transferred']} != expected "
            f"{image_bytes_u8 + label_bytes} (images + int32 labels)"
        )
    # The in-step dequantize must invert the quantization: mean of the
    # dequantized uint8 stream tracks the float stream's mean.
    from deeplearning_cfn_tpu.train.pipeline import dequantize_normalize
    from deeplearning_cfn_tpu.train.data import SyntheticDataset

    ds = SyntheticDataset(
        shape=(IMAGE, IMAGE, 3), num_classes=10, batch_size=BATCH, dtype="uint8"
    )
    mean, std = ds.input_stats
    dq = np.asarray(dequantize_normalize(jnp.asarray(u8_x), mean, std))
    if not np.isfinite(dq).all() or abs(float(dq.mean())) > 1.0:
        failures.append(f"dequantized stream off-distribution (mean {dq.mean():.3f})")

    if failures:
        for f in failures:
            print(f"perf-smoke: {f}", file=sys.stderr)
        return 1
    print(
        json.dumps(
            {
                "uint8": u8_snap,
                "float32": f32_snap,
                "bytes_ratio": round(
                    u8_snap["bytes_transferred"] / f32_snap["bytes_transferred"], 4
                ),
                "workers": WORKERS,
            },
            allow_nan=False,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
