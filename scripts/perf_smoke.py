"""Perf-smoke gate: compact-dtype input path + profiler overhead/sentinel.

Runs a tiny CPU pipeline microbench — the same uint8 synthetic stream a
real bench uses, through ``DevicePrefetcher(workers=2)`` with counters —
against a float32 baseline of identical shape, and asserts structural
properties (byte counts, batch counts, dtype preservation).  Wall-clock
is asserted only as RATIOS with wide margins (never absolute CI-machine
speed): the StepProfiler overhead guard compares an instrumented loop
against a bare one around a step big enough (~ms) that the <2% budget
is ~30x the profiler's actual per-step cost, median-of-3 to shrug off
scheduler noise; the step-time regression sentinel asserts ordering
(p99 >= p50) and a deliberately loose absolute ceiling.
docs/PERFORMANCE.md covers how to read the timing counters it prints.
A serving-plane scheduler stage, a 1k-agent broker-failover soak (both
on virtual clocks, structural asserts only), a fleet-telemetry payload
cost check (TELEM snapshots stay O(entries) with summaries truncated at
the wire cap), an input-overlap stage (double-buffered stacked batches
stay >= 2 deep on device, consumed stacks are freed by donate_buffers,
and the consumer holds its single post-warmup compile), a datastream
stage (per-host shard assignment is an exact partition, one epoch reads
every record exactly once, and the async sharded checkpointer's save()
provably never blocks a step — its writer is parked on a gate while the
step path keeps enqueuing), a fleet-scheduler stage (placement is a
deterministic pure function under permuted submission, quota invariants
hold, and the sched package never reads the wall clock), and an
exact-match check of the audited train step's collective bytes against
the committed comms budget (8-virtual-device runs only) ride along,
plus a comms-overlap stage (the bucketed gradient-sync program's
audited overlap_score strictly beats the monolithic baseline's, bucket
byte accounting sums exactly to the grad tree, and the overlap step
holds zero steady-state retraces).

Exit 0 and one JSON line on success; exit 1 with a message on violation.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np


BATCH = 8
IMAGE = 32
STEPS = 6
WORKERS = 2


def run_pipeline(dtype: str) -> tuple[dict, object]:
    from deeplearning_cfn_tpu.train.data import DevicePrefetcher, SyntheticDataset
    from deeplearning_cfn_tpu.train.pipeline import PipelineStats

    ds = SyntheticDataset(
        shape=(IMAGE, IMAGE, 3),
        num_classes=10,
        batch_size=BATCH,
        dtype=dtype,
        pool_batches=3,
    )
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    stats = PipelineStats(name=f"smoke-{dtype}")
    prefetcher = DevicePrefetcher(
        ds.batches(STEPS), sharding, size=2, workers=WORKERS, stats=stats
    )
    last_x = None
    n = 0
    try:
        for batch in prefetcher:
            last_x = batch.x
            n += 1
    finally:
        prefetcher.close()
    assert n == STEPS, f"{dtype}: consumed {n} batches, expected {STEPS}"
    return stats.snapshot(), last_x


PROFILE_STEPS = 30
PROFILE_REPEATS = 3
OVERHEAD_BUDGET = 0.02  # enabling the profiler may cost <2% of step time


def profiler_overhead() -> dict:
    """Measure StepProfiler cost against a bare loop over a jitted step.

    The step (1024x1024 matmul) runs ~1 ms on CPU, so the 2% budget is
    tens of microseconds against the profiler's ~1-2 us of bookkeeping —
    a wide structural margin, not a tight wall-clock bet.  Median of
    three interleaved repeats absorbs scheduler noise.  Also returns the
    profiler's snapshot for the step-time regression sentinel.
    """
    import time

    from deeplearning_cfn_tpu.obs.profiler import StepProfiler

    @jax.jit
    def step(a):
        return a @ a

    a = jnp.ones((1024, 1024), jnp.float32)
    step(a).block_until_ready()  # compile outside every timed window

    def bare_loop() -> float:
        t0 = time.perf_counter()
        out = a
        for _ in range(PROFILE_STEPS):
            out = step(out)
        out.block_until_ready()
        return time.perf_counter() - t0

    def profiled_loop(prof: StepProfiler) -> float:
        t0 = time.perf_counter()
        out = a
        prof.start()
        for i in range(PROFILE_STEPS):
            with prof.phase("dispatch"):
                out = step(out)
            prof.step_done(step=i)
        with prof.sync_boundary(PROFILE_STEPS):
            out.block_until_ready()
        return time.perf_counter() - t0

    bare, profiled = [], []
    prof = StepProfiler(name="perf_smoke")
    for _ in range(PROFILE_REPEATS):
        bare.append(bare_loop())
        profiled.append(profiled_loop(prof))
    bare_s = sorted(bare)[len(bare) // 2]
    profiled_s = sorted(profiled)[len(profiled) // 2]
    return {
        "bare_s": round(bare_s, 6),
        "profiled_s": round(profiled_s, 6),
        "overhead_fraction": round(profiled_s / bare_s - 1.0, 6),
        "snapshot": prof.snapshot(),
    }


SERVE_REQUESTS = 40
SERVE_STARVATION_BOUND = 80  # scheduler steps a queued request may wait


def serve_scheduler() -> tuple[dict, list[str]]:
    """Serving-plane scheduler stage: structural asserts only, no
    wall-clock.  Drives seeded mixed-length traffic through one
    continuous-batching engine on a virtual clock and checks the
    scheduler's contracts: occupancy never exceeds the slot count, FIFO
    admission never starves a request beyond a generous step bound, every
    accepted request completes, and the decode path stays on its single
    post-warmup compile (the DLC410 property, observed live)."""
    import dataclasses

    from deeplearning_cfn_tpu.analysis.compile_audit import CompileWatcher
    from deeplearning_cfn_tpu.analysis.schedules import VirtualClock
    from deeplearning_cfn_tpu.models.llama import LlamaConfig, init_params
    from deeplearning_cfn_tpu.serve import (
        ContinuousBatchingEngine,
        ServeConfig,
        ServeRequest,
        TrafficConfig,
        run_load,
    )

    failures: list[str] = []
    cfg = dataclasses.replace(
        LlamaConfig.tiny(vocab_size=64, seq_len=64), dtype=jnp.float32
    )
    params = init_params(cfg, jax.random.key(0))
    scfg = ServeConfig(
        num_slots=4, block_size=4, blocks_per_slot=8, prefill_len=16
    )
    clock = VirtualClock()
    engine = ContinuousBatchingEngine(
        cfg, params, scfg, clock=clock, journal=False
    )
    # Warmup: one request compiles the prefill and decode executables.
    engine.submit(ServeRequest("warm", np.array([1, 2, 3], np.int32), 4))
    while engine.pending():
        engine.step()

    occupancy_ok = True

    def watch_occupancy(_step: int) -> None:
        nonlocal occupancy_ok
        occupancy_ok = occupancy_ok and engine.active_slots <= scfg.num_slots

    with CompileWatcher() as watcher:
        watcher.mark_steady()
        report = run_load(
            engine,
            TrafficConfig(requests=SERVE_REQUESTS, seed=0),
            clock,
            on_step=watch_occupancy,
        )
        retraces = watcher.new_compiles_since_mark()
    snap = engine.snapshot()
    if report.completed != SERVE_REQUESTS:
        failures.append(
            f"serve scheduler lost requests: {report.completed}/{SERVE_REQUESTS}"
        )
    if not occupancy_ok:
        failures.append(
            f"serve scheduler overfilled its {scfg.num_slots} slots"
        )
    if snap["max_wait_steps"] > SERVE_STARVATION_BOUND:
        failures.append(
            f"serve scheduler starved a request for {snap['max_wait_steps']} "
            f"steps (bound {SERVE_STARVATION_BOUND})"
        )
    if retraces:
        failures.append(
            f"serve decode retraced after warmup: {sorted(retraces)}"
        )
    return {
        "requests": SERVE_REQUESTS,
        "completed": report.completed,
        "steps": report.steps,
        "max_wait_steps": snap["max_wait_steps"],
        "recycled_blocks": snap["recycled_blocks"],
        "post_warmup_compiles": len(retraces),
    }, failures


def comms_budget() -> tuple[dict, list[str]]:
    """Comms-budget stage: the audited fsdp train step's collective
    bytes must match scripts/comms_budget.json EXACTLY — not a ceiling.

    The audit is pure lower+compile of a fixed program on a fixed mesh,
    so its HLO (and therefore its collective inventory) is
    deterministic; any drift in either direction means the partitioner
    output changed and the budget must be consciously re-measured
    (scripts/comms_audit.py --write-budget).  Needs the 8 virtual
    devices check.sh provides; skipped structurally elsewhere so a bare
    `python scripts/perf_smoke.py` still runs."""
    from deeplearning_cfn_tpu.analysis.comms_audit import (
        load_budget,
        run_comms_audit,
    )

    failures: list[str] = []
    budget = load_budget()
    if budget is None:
        return {"skipped": "no committed budget"}, failures
    if jax.device_count() != int(budget.get("device_count", -1)):
        return {
            "skipped": f"device_count {jax.device_count()} != "
            f"budget's {budget.get('device_count')}"
        }, failures
    report = run_comms_audit(journal=False, budget_path=None, serve=False)
    committed = budget.get("programs", {}).get("train_step", {})
    measured = next(
        (p for p in report.programs if p.name == "train_step"), None
    )
    if measured is None:
        failures.append("comms audit produced no train_step program")
        return {}, failures
    if measured.collective_bytes != int(committed.get("collective_bytes", -1)):
        failures.append(
            f"train_step collective_bytes {measured.collective_bytes} != "
            f"committed {committed.get('collective_bytes')} "
            "(scripts/comms_budget.json; re-measure deliberately with "
            "scripts/comms_audit.py --write-budget)"
        )
    return {
        "train_step": measured.budget,
        "committed": committed,
    }, failures


OVERLAP_BUCKET_BYTES = 32 * 1024


def comms_overlap() -> tuple[dict, list[str]]:
    """Comms-overlap stage: the bucketed gradient-sync engine
    (parallel/overlap.py) must actually buy what it promises, proven
    structurally on the 8-device virtual mesh:

    (1) the bucketed dp program's audited ``overlap_score`` is STRICTLY
        greater than the monolithic program's on the same model, mesh,
        and batch — the schedule genuinely interleaves sync with
        compute (the DLC512 pair invariant, checked here without the
        committed budget in the loop);
    (2) the bucket plan's byte accounting sums exactly to the gradient
        tree — every leaf lands in exactly one bucket, nothing double-
        synced or dropped;
    (3) the overlap step compiles once and never again across
        steady-state steps (zero retraces under ``CompileWatcher`` —
        the trace-time bucket planning must be compile-stable)."""
    from deeplearning_cfn_tpu.analysis.comms_audit import (
        AUDIT_BATCH_SIZE,
        AUDIT_CLASSES,
        AUDIT_INPUT_SHAPE,
        _audit_model,
        program_comms,
    )
    from deeplearning_cfn_tpu.analysis.compile_audit import CompileWatcher
    from deeplearning_cfn_tpu.parallel.mesh import MeshSpec, build_mesh
    from deeplearning_cfn_tpu.parallel.overlap import plan_buckets
    from deeplearning_cfn_tpu.train.data import SyntheticDataset
    from deeplearning_cfn_tpu.train.trainer import Trainer, TrainerConfig
    from deeplearning_cfn_tpu.utils import compat

    failures: list[str] = []
    if jax.device_count() < 8:
        return {
            "skipped": f"needs 8 virtual devices, have {jax.device_count()}"
        }, failures
    mesh = build_mesh(MeshSpec.data_parallel(8), jax.devices()[:8])
    ds = SyntheticDataset(
        shape=AUDIT_INPUT_SHAPE,
        num_classes=AUDIT_CLASSES,
        batch_size=AUDIT_BATCH_SIZE,
        seed=0,
    )
    sample = next(iter(ds.batches(1)))
    kwargs = dict(learning_rate=0.05, optimizer="sgd", strategy="dp")
    mono = Trainer(_audit_model(), mesh, TrainerConfig(**kwargs))
    bucketed = Trainer(
        _audit_model(),
        mesh,
        TrainerConfig(
            comms_overlap=True,
            overlap_bucket_bytes=OVERLAP_BUCKET_BYTES,
            **kwargs,
        ),
    )
    with compat.set_mesh(mesh):
        mono_state = mono.init(jax.random.PRNGKey(0), sample.x)
        mono_score = program_comms(
            mono.step_fn.lower(mono_state, sample.x, sample.y).compile()
        )["overlap_score"]
        with CompileWatcher() as watcher:
            state = bucketed.init(jax.random.PRNGKey(0), sample.x)
            bucket_score = program_comms(
                bucketed.step_fn.lower(state, sample.x, sample.y).compile()
            )["overlap_score"]
            state, metrics = bucketed.train_step(state, sample.x, sample.y)
            jax.block_until_ready(metrics["loss"])
            watcher.mark_steady()
            for _ in range(3):
                state, metrics = bucketed.train_step(
                    state, sample.x, sample.y
                )
            jax.block_until_ready(metrics["loss"])
            retraces = watcher.new_compiles_since_mark()
    if bucket_score <= mono_score:
        failures.append(
            f"bucketed overlap_score {bucket_score} does not strictly "
            f"exceed the monolithic baseline's {mono_score} — the "
            "bucket schedule is buying no latency hiding"
        )
    specs = jax.tree_util.tree_map(
        lambda s: s.spec, bucketed.state_shardings.params
    )
    plan = plan_buckets(state.params, specs, OVERLAP_BUCKET_BYTES)
    leaves = jax.tree_util.tree_leaves(state.params)
    tree_bytes = sum(l.size * l.dtype.itemsize for l in leaves)
    if plan.total_bytes != tree_bytes:
        failures.append(
            f"bucket byte accounting {plan.total_bytes} != grad tree "
            f"{tree_bytes} — a leaf was dropped or double-bucketed"
        )
    bucketed_leaves = sum(len(b.indices) for b in plan.buckets)
    if bucketed_leaves != len(leaves):
        failures.append(
            f"bucket plan covers {bucketed_leaves} leaves of {len(leaves)}"
        )
    if retraces:
        failures.append(
            f"overlap step recompiled after warmup: {sorted(retraces)}"
        )
    return {
        "monolithic_overlap_score": mono_score,
        "bucketed_overlap_score": bucket_score,
        "buckets": len(plan.buckets),
        "bucket_bytes": plan.total_bytes,
        "post_warmup_compiles": len(retraces),
    }, failures


TELEM_GAUGES = 12
TELEM_OVERSIZE_SAMPLES = 4096


def telemetry_overhead() -> tuple[dict, list[str]]:
    """Fleet-telemetry stage: structural asserts only, no wall-clock.
    The TELEM payload rides the heartbeat path, so its cost model must
    hold by construction: the encoded snapshot carries exactly the
    gauges handed in (no hidden amplification), summary samples are
    truncated to MAX_SUMMARY_SAMPLES regardless of how many the caller
    accumulated, non-finite values serialize as null (never a parse
    error at the controller), and payload size is O(entries) — bounded
    by a per-entry budget, not proportional to run length."""
    from deeplearning_cfn_tpu.obs.aggregator import (
        MAX_SUMMARY_SAMPLES,
        FleetAggregator,
        agent_snapshot,
        decode_snapshot,
        encode_snapshot,
    )

    failures: list[str] = []
    gauges = {f"dlcfn_fleet_gauge_probe_{i}": float(i) for i in range(TELEM_GAUGES)}
    gauges["dlcfn_serve_tokens_per_s"] = float("nan")
    payload = encode_snapshot(
        agent_snapshot(
            gauges=gauges,
            summaries={"dlcfn_step_ms": [float(i) for i in range(TELEM_OVERSIZE_SAMPLES)]},
        )
    )
    body = decode_snapshot(payload)
    if body is None:
        failures.append("telemetry snapshot failed to round-trip")
        return {}, failures
    if len(body["gauges"]) != len(gauges):
        failures.append(
            f"telemetry gauge count amplified: {len(body['gauges'])} != {len(gauges)}"
        )
    if body["gauges"]["dlcfn_serve_tokens_per_s"] is not None:
        failures.append("non-finite gauge escaped json_safe onto the wire")
    shipped = len(body["summaries"]["dlcfn_step_ms"])
    if shipped != MAX_SUMMARY_SAMPLES:
        failures.append(
            f"summary samples not truncated: shipped {shipped}, "
            f"cap {MAX_SUMMARY_SAMPLES}"
        )
    # O(entries) bound: generous per-entry byte budget (name + float +
    # JSON punctuation), independent of the 4096 samples accumulated.
    entries = len(gauges) + MAX_SUMMARY_SAMPLES
    budget = 64 * entries + 256
    if len(payload) > budget:
        failures.append(
            f"telemetry payload {len(payload)}B over the structural "
            f"budget {budget}B for {entries} entries"
        )
    # The controller-side merge stays a pure fold of its input table.
    agg = FleetAggregator().merge({"g/0": (1.0, 1, payload), "g/1": (1.0, 1, payload)})
    if agg["hosts"] != 2 or agg["summaries"]["dlcfn_step_ms"]["count"] != 2 * MAX_SUMMARY_SAMPLES:
        failures.append("fleet merge dropped or duplicated snapshot samples")
    return {
        "gauges": len(gauges),
        "samples_shipped": shipped,
        "samples_accumulated": TELEM_OVERSIZE_SAMPLES,
        "payload_bytes": len(payload),
        "payload_budget_bytes": budget,
    }, failures


OVERLAP_K = 2        # batches per stacked multi-step call
OVERLAP_CALLS = 5    # stacks consumed by the stage
OVERLAP_BUFFER = 2   # DevicePrefetcher depth — the double buffer


def input_overlap() -> tuple[dict, list[str]]:
    """Overlap-architecture stage: structural asserts only, no wall-clock.

    Drives stacked uint8 batches through ``DevicePrefetcher`` exactly the
    way ``Trainer._fit_multi`` and the bench multi-step phase do, and
    checks the three properties docs/PERFORMANCE.md's overlap section
    promises: (1) the prefetcher keeps >= 2 batches device-resident
    while one is being consumed (double buffering, observed via
    ``buffered()``); (2) every consumed stack's leaves are actually
    freed by ``donate_buffers`` (``is_deleted``) — the explicit-delete
    stand-in for donation on input stacks; (3) the consuming program
    compiles once and never again across the remaining same-shape calls
    (zero post-warmup compiles)."""
    import time

    from deeplearning_cfn_tpu.analysis.compile_audit import CompileWatcher
    from deeplearning_cfn_tpu.train.data import (
        DevicePrefetcher,
        SyntheticDataset,
        donate_buffers,
        stack_batches,
    )

    failures: list[str] = []
    ds = SyntheticDataset(
        shape=(IMAGE, IMAGE, 3), num_classes=10, batch_size=BATCH, dtype="uint8"
    )
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])

    @jax.jit
    def consume(xs, ys):
        return jnp.sum(xs.astype(jnp.float32)) + jnp.sum(ys)

    stacks = stack_batches(ds.batches(OVERLAP_CALLS * OVERLAP_K), OVERLAP_K)
    prefetcher = DevicePrefetcher(
        stacks, sharding, size=OVERLAP_BUFFER, workers=WORKERS
    )
    peak_resident = 0
    donated_bytes = 0
    calls = 0
    out = None
    try:
        with CompileWatcher() as watcher:
            for i, stack in enumerate(prefetcher):
                if i == 0:
                    # Let the producer refill behind the in-hand stack so
                    # the double buffer is observable, then freeze the
                    # compile ledger: everything past this call is steady
                    # state.
                    deadline = time.monotonic() + 10.0
                    while (
                        len(prefetcher.buffered()) < OVERLAP_BUFFER
                        and time.monotonic() < deadline
                    ):
                        time.sleep(0.001)
                peak_resident = max(peak_resident, 1 + len(prefetcher.buffered()))
                out = consume(stack.x, stack.y)
                if i == 0:
                    out.block_until_ready()
                    watcher.mark_steady()
                # Explicit free of the consumed stack — deletion after
                # dispatch is safe (the running program holds its own
                # reference) and is what keeps k-deep stacks from
                # accumulating in HBM.
                donated_bytes += donate_buffers((stack.x, stack.y))
                if not (stack.x.is_deleted() and stack.y.is_deleted()):
                    failures.append(
                        "consumed stack leaves survive donate_buffers "
                        "(is_deleted False) — stacks would accumulate in HBM"
                    )
                calls += 1
            out.block_until_ready()
            retraces = watcher.new_compiles_since_mark()
    finally:
        prefetcher.close()
    if calls != OVERLAP_CALLS:
        failures.append(
            f"overlap stage consumed {calls} stacks, expected {OVERLAP_CALLS}"
        )
    if peak_resident < 2:
        failures.append(
            f"prefetcher never held 2 device-resident stacks "
            f"(peak {peak_resident}) — no overlap to hide transfers behind"
        )
    if retraces:
        failures.append(
            f"overlap consumer recompiled after warmup: {sorted(retraces)}"
        )
    expected_stack_bytes = OVERLAP_CALLS * OVERLAP_K * BATCH * (
        IMAGE * IMAGE * 3 + 4
    )
    if donated_bytes != expected_stack_bytes:
        failures.append(
            f"donated bytes {donated_bytes} != expected {expected_stack_bytes} "
            "(uint8 images + int32 labels across every consumed stack)"
        )
    return {
        "steps_per_call": OVERLAP_K,
        "calls": calls,
        "device_resident_stacks_peak": peak_resident,
        "donated_bytes": donated_bytes,
        "post_warmup_compiles": len(retraces),
    }, failures


DATASTREAM_SHARDS = 4
DATASTREAM_HOSTS = ("host-a", "host-b")


def datastream() -> tuple[dict, list[str]]:
    """Data-plane stage: structural asserts only, no wall-clock.

    Checks the three contracts docs/DATA.md promises: (1) the per-host
    shard assignment is an exact partition of the shard set for every
    epoch probed; (2) draining one epoch across all hosts reads every
    record exactly once (record ids are baked into the shards, so the
    claim is literally ``sorted(seen) == range(total)``); (3) the async
    sharded checkpointer never blocks a step — proven by construction,
    not by timing: the writer is parked on a threading.Event while the
    step path keeps enqueuing, so zero bytes can land while the gate is
    closed, latest-wins supersedes the middle save, and releasing the
    gate commits exactly the first-picked and last-enqueued steps."""
    import shutil
    import tempfile
    import threading

    from deeplearning_cfn_tpu.train.datastream import (
        AsyncShardedCheckpointer,
        HostShardStream,
        assign_shards,
    )
    from deeplearning_cfn_tpu.train.records import (
        Field,
        RecordSpec,
        write_records,
    )

    failures: list[str] = []
    for epoch in range(3):
        assigned = assign_shards(
            DATASTREAM_HOSTS, DATASTREAM_SHARDS, seed=7, epoch=epoch
        )
        flat = sorted(s for w in assigned.values() for s in w)
        if flat != list(range(DATASTREAM_SHARDS)):
            failures.append(
                f"epoch {epoch}: shard assignment is not an exact "
                f"partition: {assigned}"
            )

    spec = RecordSpec((Field("x", "uint8", (1,)), Field("y", "int32", ())))
    root = Path(tempfile.mkdtemp(prefix="dlcfn-perf-datastream-"))
    try:
        gid = 0
        paths = []
        for sid in range(DATASTREAM_SHARDS):
            recs = []
            for _ in range(11 + sid):  # uneven on purpose
                recs.append(
                    spec.encode(
                        x=np.array([gid % 251], np.uint8), y=np.int32(gid)
                    )
                )
                gid += 1
            p = root / f"shard-{sid}.dlc"
            write_records(p, spec, recs)
            paths.append(p)
        seen: list[int] = []
        for host in DATASTREAM_HOSTS:
            stream = HostShardStream(
                paths,
                spec,
                batch_size=4,
                host=host,
                hosts=DATASTREAM_HOSTS,
                seed=7,
                loop=False,
            )
            for b in stream.batches():
                seen.extend(int(v) for v in b.y)
        if sorted(seen) != list(range(gid)):
            failures.append(
                f"epoch drain not exactly-once: {len(seen)} reads of "
                f"{gid} records"
            )

        class _GatedDisk:
            """CheckpointIO-compatible; every write parks on a gate, so
            the step path demonstrably runs ahead of the writer."""

            def __init__(self):
                self.entered = threading.Event()
                self.release = threading.Event()

            def write_bytes(self, path, data):
                self.entered.set()
                if not self.release.wait(timeout=30):
                    raise OSError("gate never released")
                Path(path).write_bytes(data)

            def replace(self, src, dst):
                import os

                os.replace(src, dst)

            def read_bytes(self, path):
                return Path(path).read_bytes()

        disk = _GatedDisk()
        state = {"w": np.arange(8, dtype=np.float32)}
        ck = AsyncShardedCheckpointer(
            root / "ckpt", every_steps=1, n_shards=2, io=disk
        )
        ck.save(1, state, stream_state={"host": "host-a", "cursor": 1})
        if not disk.entered.wait(timeout=30):
            failures.append("async writer never started after save()")
        # The step path is HERE, running, while the writer is parked on
        # the gate: save() returned with zero bytes on disk.
        if list((root / "ckpt").glob("ckpt-*.manifest.json")):
            failures.append(
                "a manifest landed while the writer was gated — "
                "save() blocked on IO"
            )
        ck.save(2, {"w": state["w"] + 1})
        ck.save(3, {"w": state["w"] + 2})
        if ck.superseded_total != 1:
            failures.append(
                f"latest-wins supersede count {ck.superseded_total} != 1 "
                "(step 2 should yield to step 3)"
            )
        disk.release.set()
        ck.wait(timeout_s=60)
        steps = ck.steps()
        if steps != [1, 3]:
            failures.append(
                f"committed steps {steps} != [1, 3] "
                "(first-picked + last-enqueued)"
            )
        restored = ck.restore_latest()
        if restored is None or restored[1] != 3:
            failures.append(
                "restore_latest did not return the last committed step"
            )
        ck.close()
        return {
            "shards": DATASTREAM_SHARDS,
            "hosts": len(DATASTREAM_HOSTS),
            "records": gid,
            "epoch_reads": len(seen),
            "superseded": ck.superseded_total,
            "committed_steps": steps,
        }, failures
    finally:
        shutil.rmtree(root, ignore_errors=True)


BROKER_SOAK_AGENTS = 1000
BROKER_SOAK_SENDERS = 100


def broker_soak() -> tuple[dict, list[str]]:
    """Control-plane failover stage: structural asserts only, no
    wall-clock.  Runs the 1k-agent warm-standby soak on a virtual clock
    (primary killed mid-term, standby promoted, clients blind-re-send)
    and checks the control plane's contracts: every killed agent's
    INSTANCE_TERMINATE fires exactly once across the failover, the
    idempotent re-send storm lands exactly-once, the promoted standby
    replays every shipped journal entry, and no write was fenced in a
    clean (single-partition) failover."""
    from deeplearning_cfn_tpu.analysis.schedules import soak_failover

    failures: list[str] = []
    soak = soak_failover(agents=BROKER_SOAK_AGENTS, seed=0)
    if soak["lost_terminates"]:
        failures.append(
            f"broker failover lost {soak['lost_terminates']} "
            f"INSTANCE_TERMINATE events"
        )
    for kind in ("spurious", "duplicate", "premature"):
        if soak[f"{kind}_terminates"]:
            failures.append(
                f"broker failover produced {soak[f'{kind}_terminates']} "
                f"{kind} terminates"
            )
    if soak["duplicate_sends"] or soak["work_depth"] != BROKER_SOAK_SENDERS:
        failures.append(
            f"idempotent re-send not exactly-once: depth "
            f"{soak['work_depth']}/{BROKER_SOAK_SENDERS}, "
            f"{soak['duplicate_sends']} duplicates"
        )
    # Bounded replay lag: the promoted standby holds every entry the
    # primary shipped before dying — journaled minus replayed is exactly
    # the tail the kill left unshipped, never more.
    if soak["replayed_seq"] != soak["journaled_seq"] - soak["unshipped_at_kill"]:
        failures.append(
            f"standby replay lag unbounded: replayed {soak['replayed_seq']} "
            f"of {soak['journaled_seq']} journaled "
            f"({soak['unshipped_at_kill']} unshipped at kill)"
        )
    if soak["fenced_writes"]:
        failures.append(
            f"clean failover fenced {soak['fenced_writes']} writes"
        )
    if soak["client_failovers"] != BROKER_SOAK_SENDERS:
        failures.append(
            f"client failover count {soak['client_failovers']} != "
            f"{BROKER_SOAK_SENDERS} senders"
        )
    return soak, failures


FLEET_SIM_AGENTS = 10_000
FLEET_SIM_SHARDS = 8


def fleet_sim() -> tuple[dict, list[str]]:
    """Sharded-fleet stage: the 10k-agent deterministic soak, wall-clock
    bounded.  Runs :func:`soak_fleet` twice at the same seed on a
    VirtualClock — concurrent multi-shard failovers, a split brain,
    auto-re-provision races — and checks (1) exactly-once delivery and
    zero lost/spurious INSTANCE_TERMINATE at 10k agents, (2) no shard
    pair left degraded, (3) byte-determinism: both runs serialize to
    identical JSON, and (4) the hot loop never touches ``time.sleep`` —
    all waiting is virtual, so the stage's cost is CPU, not wall
    clock."""
    import time as _time

    from deeplearning_cfn_tpu.analysis.schedules import soak_fleet

    failures: list[str] = []
    sleep_calls = 0
    real_sleep = _time.sleep

    def counting_sleep(seconds: float) -> None:
        nonlocal sleep_calls
        sleep_calls += 1
        real_sleep(seconds)

    _time.sleep = counting_sleep
    try:
        first = soak_fleet(agents=FLEET_SIM_AGENTS, shards=FLEET_SIM_SHARDS, seed=0)
        second = soak_fleet(agents=FLEET_SIM_AGENTS, shards=FLEET_SIM_SHARDS, seed=0)
    finally:
        _time.sleep = real_sleep
    if sleep_calls:
        failures.append(
            f"fleet sim hot loop slept {sleep_calls} time(s) — the soak "
            f"must wait on the VirtualClock only"
        )
    serialized = json.dumps(first, sort_keys=True, allow_nan=False)
    if serialized != json.dumps(second, sort_keys=True, allow_nan=False):
        diff = {
            k for k in set(first) | set(second) if first.get(k) != second.get(k)
        }
        failures.append(
            f"fleet sim not byte-deterministic at seed 0: fields {sorted(diff)}"
        )
    if first["lost_terminates"] or first["terminated"] != first["killed"]:
        failures.append(
            f"fleet sim lost terminates: {first['terminated']} of "
            f"{first['killed']} killed agents terminated "
            f"({first['lost_terminates']} lost)"
        )
    for kind in ("spurious", "duplicate", "premature"):
        if first[f"{kind}_terminates"]:
            failures.append(
                f"fleet sim produced {first[f'{kind}_terminates']} "
                f"{kind} terminates"
            )
    expected = first["senders"] + first["stale_writes"]
    if first["duplicate_sends"] or first["delivered"] != expected:
        failures.append(
            f"fleet sim delivery not exactly-once: {first['delivered']} "
            f"delivered of {expected} sent, "
            f"{first['duplicate_sends']} duplicates"
        )
    if first["degraded_pairs"]:
        failures.append(
            f"fleet sim left {first['degraded_pairs']} shard pair(s) "
            f"degraded after auto-heal"
        )
    if first["diverged_entries"]:
        failures.append(
            f"split-brain shard diverged by {first['diverged_entries']} "
            f"entries past the fence"
        )
    if first["unaffected_shard_failovers"]:
        failures.append(
            f"failovers leaked across shards: {first['unaffected_shard_failovers']} "
            f"client failovers on healthy shards"
        )
    return first, failures


DETERMINISM_SCENARIO = "silent-death"
DETERMINISM_SOAK_AGENTS = 200
DETERMINISM_WALL_BUDGET_S = 120.0


def determinism() -> tuple[dict, list[str]]:
    """Replay-determinism stage: the DLC610 sentinel's mechanics, smoke-
    sized.  Double-runs one chaos scenario plus a scaled-down
    ``soak_failover`` through :mod:`analysis.replay_audit` and checks
    (1) both double-runs are byte-identical, (2) the double run never
    touches ``time.sleep`` — scenarios and soaks wait on virtual clocks
    only, so replaying them twice costs CPU, not wall clock — and
    (3) wall time stays inside DETERMINISM_WALL_BUDGET_S.  The full
    sweep over every scenario and both soaks is scripts/replay_audit.py;
    this stage pins the sentinel's cost model."""
    import time as _time

    from deeplearning_cfn_tpu.analysis.replay_audit import (
        ReplayCase,
        default_cases,
        run_replay_audit,
    )
    from deeplearning_cfn_tpu.analysis.schedules import soak_failover

    failures: list[str] = []
    sleep_calls = 0
    real_sleep = _time.sleep

    def counting_sleep(seconds: float) -> None:
        nonlocal sleep_calls
        sleep_calls += 1
        real_sleep(seconds)

    cases = default_cases(scenarios=[DETERMINISM_SCENARIO], soaks=False)
    cases.append(
        ReplayCase(
            name="soak_failover_smoke",
            kind="soak",
            run=lambda seed: soak_failover(
                agents=DETERMINISM_SOAK_AGENTS,
                seed=seed,
                kill_count=10,
                senders=20,
                unshipped_tail=5,
            ),
            audited_file="scripts/perf_smoke.py",
        )
    )
    start = _time.monotonic()
    _time.sleep = counting_sleep
    try:
        report = run_replay_audit(cases=cases, journal=False)
    finally:
        _time.sleep = real_sleep
    wall_s = round(_time.monotonic() - start, 3)
    for replay in report.replays:
        if not replay.identical:
            failures.append(
                f"determinism stage: {replay.kind} '{replay.name}' diverged "
                f"across a same-seed double run (first divergence at "
                f"{replay.divergence})"
            )
    if sleep_calls:
        failures.append(
            f"determinism stage slept {sleep_calls} time(s) — the double "
            f"run must wait on virtual clocks only"
        )
    if wall_s > DETERMINISM_WALL_BUDGET_S:
        failures.append(
            f"determinism stage took {wall_s}s, over the "
            f"{DETERMINISM_WALL_BUDGET_S}s wall budget"
        )
    snapshot = {
        "replays": [r.to_dict() for r in report.replays],
        "sleep_calls": sleep_calls,
        "wall_s": wall_s,
    }
    return snapshot, failures


SCHED_JOBS = 6
SCHED_SLICES = 5


def sched_placer() -> tuple[dict, list[str]]:
    """Fleet-scheduler stage: structural asserts only, no wall-clock.

    Checks the placer's contracts (docs/SCHEDULER.md): (1) placement is
    a deterministic pure function — repeated calls AND permuted
    submission orders produce byte-identical placements; (2) quota
    invariants hold by verify_placement (each slice assigned at most
    once, every placed job within [min_slices, max_slices], every job
    placed or carrying a reason); (3) the sched package never touches
    the wall clock — all of its timing flows through the injected
    broker/journal seams, so decisions replay deterministically."""
    import itertools

    from deeplearning_cfn_tpu.sched import JobSpec, place, verify_placement

    failures: list[str] = []
    inventory = {f"s{i}": 4 for i in range(SCHED_SLICES)}
    jobs = [
        JobSpec(name="chat", kind="serve", priority="prod-serve"),
        JobSpec(name="train-a", kind="train", priority="prod-train",
                min_slices=1, max_slices=2),
        JobSpec(name="train-b", kind="train", priority="prod-train",
                min_slices=2, max_slices=2),
        JobSpec(name="nightly", kind="train", priority="batch",
                min_slices=1, max_slices=3),
        JobSpec(name="eval", kind="serve", priority="batch"),
        JobSpec(name="hopeless", kind="train", priority="batch",
                min_slices=SCHED_SLICES + 1, max_slices=SCHED_SLICES + 1),
    ]
    assert len(jobs) == SCHED_JOBS
    baseline = place(jobs, inventory)
    for trial, ordering in enumerate(itertools.permutations(jobs, len(jobs))):
        if trial >= 24:  # two dozen permutations is plenty of shuffle
            break
        if place(list(ordering), inventory).to_dict() != baseline.to_dict():
            failures.append(
                f"placement depends on submission order (permutation {trial})"
            )
            break
    quota_errors = verify_placement(baseline, jobs, inventory)
    failures.extend(f"quota invariant: {e}" for e in quota_errors)
    if "hopeless" not in baseline.unplaced:
        failures.append(
            "over-quota job was placed instead of explained in unplaced"
        )
    if baseline.assignments.get("chat") != ("s0",):
        failures.append(
            f"prod-serve did not get the first slice: {baseline.assignments}"
        )
    # No wall clock anywhere in the package: a sched decision must be a
    # pure function of (ledger, intents), or crash-resume cannot replay.
    sched_dir = Path(__file__).resolve().parent.parent / (
        "deeplearning_cfn_tpu/sched"
    )
    clocked = [
        p.name
        for p in sorted(sched_dir.glob("*.py"))
        if any(
            probe in p.read_text()
            for probe in ("time.time(", "time.monotonic(", "time.sleep(")
        )
    ]
    if clocked:
        failures.append(
            f"sched package touches the wall clock in {clocked} — "
            "decisions must be replayable from the ledger alone"
        )
    return {
        "jobs": SCHED_JOBS,
        "slices": SCHED_SLICES,
        "assignments": {j: list(s) for j, s in sorted(baseline.assignments.items())},
        "unplaced": dict(sorted(baseline.unplaced.items())),
        "permutations_checked": 24,
        "quota_errors": len(quota_errors),
    }, failures


def main() -> int:
    u8_snap, u8_x = run_pipeline("uint8")
    f32_snap, f32_x = run_pipeline("float32")

    failures = []
    if u8_x.dtype != jnp.uint8:
        failures.append(f"uint8 pipeline delivered {u8_x.dtype} to the device")
    if f32_x.dtype != jnp.float32:
        failures.append(f"float32 baseline delivered {f32_x.dtype}")
    if u8_snap["batches"] != STEPS or f32_snap["batches"] != STEPS:
        failures.append(
            f"batch counters diverged: u8={u8_snap['batches']} "
            f"f32={f32_snap['batches']} expected={STEPS}"
        )
    # THE gate: the compact path must move strictly fewer bytes than the
    # float32 baseline at identical shapes.  Labels (int32) are shared
    # payload, so the ratio is < 1/4 + epsilon rather than exactly 1/4.
    if not u8_snap["bytes_transferred"] < f32_snap["bytes_transferred"]:
        failures.append(
            f"compact-dtype path not taken: uint8 moved "
            f"{u8_snap['bytes_transferred']} bytes vs float32 "
            f"{f32_snap['bytes_transferred']}"
        )
    image_bytes_u8 = STEPS * BATCH * IMAGE * IMAGE * 3
    label_bytes = STEPS * BATCH * 4
    if u8_snap["bytes_transferred"] != image_bytes_u8 + label_bytes:
        failures.append(
            f"uint8 byte counter {u8_snap['bytes_transferred']} != expected "
            f"{image_bytes_u8 + label_bytes} (images + int32 labels)"
        )
    # The in-step dequantize must invert the quantization: mean of the
    # dequantized uint8 stream tracks the float stream's mean.
    from deeplearning_cfn_tpu.train.pipeline import dequantize_normalize
    from deeplearning_cfn_tpu.train.data import SyntheticDataset

    ds = SyntheticDataset(
        shape=(IMAGE, IMAGE, 3), num_classes=10, batch_size=BATCH, dtype="uint8"
    )
    mean, std = ds.input_stats
    dq = np.asarray(dequantize_normalize(jnp.asarray(u8_x), mean, std))
    if not np.isfinite(dq).all() or abs(float(dq.mean())) > 1.0:
        failures.append(f"dequantized stream off-distribution (mean {dq.mean():.3f})")

    # Profiling must be OFF by default outside bench/status paths: fit's
    # default is None (-> NULL_PROFILER), and a disabled profiler's
    # wrap_source is the identity (zero iterator indirection).
    import inspect

    from deeplearning_cfn_tpu.obs.profiler import NULL_PROFILER
    from deeplearning_cfn_tpu.train.trainer import Trainer

    if inspect.signature(Trainer.fit).parameters["profiler"].default is not None:
        failures.append("Trainer.fit profiles by default (profiler default != None)")
    probe = iter(())
    if NULL_PROFILER.wrap_source(probe) is not probe:
        failures.append("disabled profiler wraps the batch source (overhead when off)")

    # Overhead guard: enabling the profiler may cost <2% of step time.
    overhead = profiler_overhead()
    if overhead["overhead_fraction"] >= OVERHEAD_BUDGET:
        failures.append(
            f"StepProfiler overhead {overhead['overhead_fraction']:.2%} "
            f">= {OVERHEAD_BUDGET:.0%} budget "
            f"(bare {overhead['bare_s']}s vs profiled {overhead['profiled_s']}s)"
        )
    # Step-time regression sentinel: distribution shape, not raw speed —
    # quantile ordering must hold and p99 of a ~1 ms matmul step must
    # stay under a deliberately loose ceiling even on a slow runner.
    snap = overhead["snapshot"]
    p50, p99 = snap["step_ms"].get("p50"), snap["step_ms"].get("p99")
    if p50 is None or p99 is None or not (0 < p50 <= p99):
        failures.append(f"step-time quantiles malformed: p50={p50} p99={p99}")
    elif p99 > 2000.0:
        failures.append(f"step-time p99 {p99}ms blew the 2000ms sentinel bound")
    for phase in ("dispatch", "compute", "host"):
        if phase not in snap["phases"]:
            failures.append(f"profiler snapshot missing phase {phase!r}")

    overlap_snap, overlap_failures = input_overlap()
    failures.extend(overlap_failures)

    serve_snap, serve_failures = serve_scheduler()
    failures.extend(serve_failures)

    broker_snap, broker_failures = broker_soak()
    failures.extend(broker_failures)

    fleet_snap, fleet_failures = fleet_sim()
    failures.extend(fleet_failures)

    telem_snap, telem_failures = telemetry_overhead()
    failures.extend(telem_failures)

    datastream_snap, datastream_failures = datastream()
    failures.extend(datastream_failures)

    sched_snap, sched_failures = sched_placer()
    failures.extend(sched_failures)

    comms_snap, comms_failures = comms_budget()
    failures.extend(comms_failures)

    comms_overlap_snap, comms_overlap_failures = comms_overlap()
    failures.extend(comms_overlap_failures)

    det_snap, det_failures = determinism()
    failures.extend(det_failures)

    if failures:
        for f in failures:
            print(f"perf-smoke: {f}", file=sys.stderr)
        return 1
    print(
        json.dumps(
            {
                "uint8": u8_snap,
                "float32": f32_snap,
                "bytes_ratio": round(
                    u8_snap["bytes_transferred"] / f32_snap["bytes_transferred"], 4
                ),
                "workers": WORKERS,
                "profiler_overhead": {
                    k: overhead[k]
                    for k in ("bare_s", "profiled_s", "overhead_fraction")
                },
                "step_ms": snap["step_ms"],
                "overlap": overlap_snap,
                "serve": serve_snap,
                "broker_failover": broker_snap,
                "fleet_sim": fleet_snap,
                "telemetry": telem_snap,
                "datastream": datastream_snap,
                "sched": sched_snap,
                "comms": comms_snap,
                "comms_overlap": comms_overlap_snap,
                "determinism": det_snap,
            },
            allow_nan=False,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
