"""Comms-audit CI stage: communication and HBM budgets, proven from HLO.

Lowers and compiles the real fsdp train step, multi-step scan body,
serve decode step, and the dp comms-overlap pair (monolithic
``train_step_dp`` vs bucketed ``train_step_dp_overlap`` /
``multi_step_dp_overlap`` — parallel/overlap.py) on 8 virtual CPU
devices under a :class:`analysis.comms_audit.CommsWatcher`,
machine-reads each executable's HLO for collectives, schedule slack,
and cost/memory analysis, and applies the same suppression-baseline
ratchet as ``dlcfn lint`` (scripts/lint_baseline.json, DLC51x
namespace only):

- a program whose collective op count or bytes regress over the
  committed budget (scripts/comms_budget.json) -> DLC510 -> exit 1
- an fsdp step containing an all-gather the strategy doesn't predict
  -> DLC511 -> exit 1 (unless baselined)
- a program whose schedule overlap_score falls below the committed
  number, or a ``*_overlap`` program that fails to strictly beat its
  monolithic baseline -> DLC512 -> exit 1 (unless baselined)
- a baseline entry whose DLC51x finding no longer fires -> stale nag

``--write-budget`` re-measures and rewrites scripts/comms_budget.json —
the deliberate act that moves the ratchet.  Exit 0 and one JSON report
line on success.  docs/STATIC_ANALYSIS.md has the "reading a comms
report" runbook for when this stage goes red.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# The audit's question is partitioner-layer, not numerics: CPU answers
# it, but only with a real mesh to partition over.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DLCFN_COMPILE_CACHE", "off")
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--k", type=int, default=2, help="multi-step span")
    parser.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help="suppression baseline (default scripts/lint_baseline.json)",
    )
    parser.add_argument(
        "--budget",
        type=Path,
        default=None,
        help="committed comms budget (default scripts/comms_budget.json)",
    )
    parser.add_argument(
        "--write-budget",
        action="store_true",
        help="re-measure and rewrite the committed budget, then exit 0",
    )
    args = parser.parse_args(argv)

    from deeplearning_cfn_tpu.analysis.collectives import AUDIT_RULE_IDS
    from deeplearning_cfn_tpu.analysis.comms_audit import (
        DEFAULT_BUDGET_PATH,
        run_comms_audit,
        write_budget,
    )
    from deeplearning_cfn_tpu.analysis.runner import apply_audit_baseline

    budget_path = args.budget if args.budget is not None else DEFAULT_BUDGET_PATH
    report = run_comms_audit(k=args.k, budget_path=budget_path)

    if args.write_budget:
        payload = write_budget(
            report.programs, budget_path, device_count=report.device_count
        )
        print(json.dumps({"written": str(budget_path), **payload}, allow_nan=False))
        return 0

    # This stage owns only the dynamic DLC51x namespace; lint owns the rest.
    fresh, stale = apply_audit_baseline(
        report.violations, args.baseline, AUDIT_RULE_IDS
    )

    for rule, rel, message in stale:
        print(
            f"comms-audit: stale baseline entry: {rule} {rel}: {message}",
            file=sys.stderr,
        )
    for v in fresh:
        print(f"comms-audit: {v.format()}", file=sys.stderr)

    print(json.dumps(report.to_dict(), allow_nan=False))
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
