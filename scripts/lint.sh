#!/usr/bin/env bash
# dlcfn-lint CI entry: the repo-native static-analysis pass
# (docs/STATIC_ANALYSIS.md).  Lints the package, scripts/, and bench.py;
# exit 1 on any finding, including broker-contract drift (DLC100/101).
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m deeplearning_cfn_tpu.cli lint "$@"
