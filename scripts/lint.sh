#!/usr/bin/env bash
# dlcfn-lint CI entry: the repo-native static-analysis pass
# (docs/STATIC_ANALYSIS.md).  Lints the package, scripts/, and bench.py;
# exit 1 on any finding, including broker-contract drift (DLC100/101).
# Opt-in passes: --concurrency (DLC2xx), --protocol (DLC3xx),
# --sharding (DLC4xx JAX/SPMD trace safety), --baseline.
# --json is shorthand for --format json (machine-readable findings).
set -euo pipefail
cd "$(dirname "$0")/.."
args=()
for a in "$@"; do
  if [[ "$a" == "--json" ]]; then
    args+=(--format json)
  else
    args+=("$a")
  fi
done
exec python -m deeplearning_cfn_tpu.cli lint "${args[@]+"${args[@]}"}"
