"""Build the real-text LM corpus behind BENCH_NOTES "held-out perplexity
at scale": this repository's own source + docs, byte-level tokenizer,
fixed windows, DISJOINT FILE SPLIT (val files never contribute a train
window, so held-out perplexity is genuinely held out).

Usage: python scripts/build_repo_corpus.py --out /tmp/repo_corpus [--seq_len 1024]

Output: <out>/train.dlc + val.dlc (+ layout/tokenizer sidecars) ready for
``llama_train --data_dir <out>``.  Versioned so the corpus each round's
perplexity rows train on is rebuildable bit-for-bit from the tree.
"""

import argparse
import shutil
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from deeplearning_cfn_tpu.train.datasets import convert_text  # noqa: E402

# Source + docs, no binaries, no goldens (JSON is near-random bytes at the
# byte level and pads perplexity down), no test fixtures.
GLOBS = ("deeplearning_cfn_tpu/**/*.py", "native/**/*.cpp", "native/**/*.h",
         "docs/*.md", "*.md", "scripts/*.py", "tests/*.py")
VAL_EVERY = 10  # every 10th file (sorted order) is val: ~9% of files


def collect_files() -> tuple[list[Path], list[Path]]:
    files = sorted({p for g in GLOBS for p in REPO.glob(g) if p.is_file()})
    train = [p for i, p in enumerate(files) if i % VAL_EVERY != VAL_EVERY - 1]
    val = [p for i, p in enumerate(files) if i % VAL_EVERY == VAL_EVERY - 1]
    return train, val


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True)
    ap.add_argument("--seq_len", type=int, default=1024)
    args = ap.parse_args(argv)

    train, val = collect_files()
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    stats = {}
    with tempfile.TemporaryDirectory() as td:
        for split, paths in (("train", train), ("val", val)):
            sdir = Path(td) / split
            sdir.mkdir()
            for p in paths:
                # Flat .txt copies: convert_text globs *.txt one level deep.
                shutil.copyfile(p, sdir / (str(p.relative_to(REPO)).replace("/", "__") + ".txt"))
            info = convert_text(sdir, out, seq_len=args.seq_len, split=split)
            stats[split] = {"files": len(paths), **info}
    print(stats)
    return stats


if __name__ == "__main__":
    main()
