"""Headline benchmark: ResNet-50 synthetic ImageNet throughput per chip.

BASELINE.json's driver metric is "ResNet-50 ImageNet images/sec/chip".  The
reference's corresponding workload is the Horovod synthetic ResNet-50
benchmark (README.md:149-163), for which it publishes **no number**
(BASELINE.md).  ``vs_baseline`` is therefore computed against the era's
publicly documented tensorpack+Horovod ResNet-50 throughput on the
reference's own hardware class (~350 images/sec per V100 on p3.16xlarge,
fp16, batch 64/GPU) — the workload the reference stack existed to run.

Input regime (the PR 13 overlap architecture, docs/PERFORMANCE.md): batches
cross the host->device link as uint8 (4x fewer bytes than f32) and
dequantize+normalize INSIDE the compiled step (TrainerConfig.input_stats) —
the scanned multi-step program therefore carries its own input stage, and
the multi-step phase consumes DISTINCT pre-staged [k, B, ...] stacks kept
double-buffered on device by DevicePrefetcher, each freed (donated) right
after its dispatch.  An int8-WEIGHTS forward variant is reported alongside
(ops/quant.py), riding the same compact-transfer idea one level up.

Runs on whatever accelerator JAX exposes (the driver provides one real TPU
chip).  Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning_cfn_tpu.utils.compat import set_mesh

# Per-GPU throughput of the reference's flagship stack on its own hardware
# (tensorpack ResNet-50 + Horovod on V100, the workload of README.md:149-163).
REFERENCE_IMAGES_PER_SEC_PER_DEVICE = 350.0

BATCH_PER_CHIP = 128
IMAGE_SIZE = 224
WARMUP_STEPS = 5
MEASURE_STEPS = 20
# Iterations per compiled program (Trainer.multi_step_fn).  Round-5
# measurement (docs/BENCH_NOTES.md): putting k consecutive iterations in
# ONE module leaves cost-model bytes/iteration unchanged (no
# cross-iteration data reuse exists — activations are batch-unique) but
# measures ~9-14% faster per step: XLA pipelines the iteration boundary
# and the per-dispatch overhead amortizes.  k=4 is the measured knee.
STEPS_PER_CALL = 4

# Forward-only window for the int8-weights variant: cheaper per step than
# training, so fewer steps still average out dispatch jitter.
QUANT_WARMUP_STEPS = 2
QUANT_MEASURE_STEPS = 10

PIPELINE_WORKERS = 2
PIPELINE_POOL_BATCHES = 4

# Device-resident stacks the multi-step phase keeps ahead of compute: 2 =
# double buffering (one consumed by the in-flight program, one staged).
STACK_BUFFER = 2


def measure_input_pipeline(
    trainer, state, batch: int, n_chips: int
) -> tuple[dict, dict]:
    """End-to-end device-resident input pipeline measurement: pooled
    uint8 synthetic batches (4x smaller PCIe payload than float32)
    through ``DevicePrefetcher(workers=2)`` straight into the ALREADY-
    compiled train step — with ``TrainerConfig.input_stats`` set the
    step program itself dequantizes, so the uint8 batch IS the step's
    input signature and this phase adds zero compiles.  Returns the
    per-chip throughput plus the PipelineStats counters, and the
    StepProfiler snapshot (data_wait here includes consumer waits on
    the prefetch buffer; h2d is producer-side and overlapped)."""
    from deeplearning_cfn_tpu.obs.profiler import StepProfiler
    from deeplearning_cfn_tpu.train.data import DevicePrefetcher, SyntheticDataset
    from deeplearning_cfn_tpu.train.pipeline import PipelineStats

    ds = SyntheticDataset.imagenet_like(
        batch_size=batch,
        image_size=IMAGE_SIZE,
        dtype="uint8",
        pool_batches=PIPELINE_POOL_BATCHES,
    )

    steps = WARMUP_STEPS + MEASURE_STEPS
    stats = PipelineStats(name="bench")
    profiler = StepProfiler(name="input_pipeline")
    prefetcher = DevicePrefetcher(
        ds.batches(steps),
        trainer.batch_sharding,
        size=2,
        workers=PIPELINE_WORKERS,
        stats=stats,
        profiler=profiler,
    )
    step = trainer.step_fn
    t0 = None
    metrics = None
    try:
        with set_mesh(trainer.mesh):
            profiler.start()
            for i, b in enumerate(profiler.wrap_source(prefetcher)):
                with profiler.phase("dispatch"):
                    state, metrics = step(state, b.x, b.y)
                if i == WARMUP_STEPS - 1:
                    # Sync before opening the timed window.
                    with profiler.sync_boundary(WARMUP_STEPS):
                        float(metrics["loss"])
                    t0 = time.perf_counter()
                profiler.step_done(step=i)
        with profiler.sync_boundary(MEASURE_STEPS):
            final_loss = float(metrics["loss"])
        dt = time.perf_counter() - t0
    finally:
        prefetcher.close()
    assert np.isfinite(final_loss)
    snap = stats.snapshot()
    per_chip = batch * MEASURE_STEPS / dt / n_chips
    return {
        "images_per_sec_per_chip": round(per_chip, 2),
        "transfer_dtype": "uint8",
        "workers": PIPELINE_WORKERS,
        "bytes_transferred": snap["bytes_transferred"],
        "bytes_per_image": round(snap["bytes_transferred"] / (batch * steps), 1),
        "host_input_seconds": snap["host_input_seconds"],
        "producer_stall_seconds": snap["producer_stall_seconds"],
        "consumer_wait_seconds": snap["consumer_wait_seconds"],
        "overlap_fraction": snap["overlap_fraction"],
    }, profiler.journal()


def measure_quantized(trainer, model, state, x, batch: int, n_chips: int) -> dict:
    """int8-WEIGHTS forward variant (ops/quant.py): conv/dense kernels
    cross HBM as int8 + per-channel scales and upcast inside the jitted
    apply, next to their consumers.  Measured as eval-mode forward
    throughput against the same program with float weights, plus the
    worst-case logit deviation on one batch — the compact-weights
    counterpart of the uint8 input plumbing, reported alongside the bf16
    training numbers rather than replacing them."""
    from deeplearning_cfn_tpu.ops.quant import (
        dequantize_tree,
        quantize_tree,
        quantized_nbytes,
        tree_nbytes,
    )

    params, model_state = state.params, state.model_state
    # One jitted program for the whole-tree quantization: eager per-kernel
    # jnp ops would compile a tiny program per layer shape and read as
    # dozens of retraces in the compile watcher.
    qparams, passthrough = jax.jit(quantize_tree)(params)

    @jax.jit
    def fwd_float(p, ms, xb):
        return model.apply({"params": p, **ms}, trainer._normalize_input(xb), train=False)

    @jax.jit
    def fwd_int8(q, pth, ms, xb):
        p = dequantize_tree(q, pth)
        return model.apply({"params": p, **ms}, trainer._normalize_input(xb), train=False)

    def timed(fn, *args) -> tuple[float, jax.Array]:
        out = None
        for _ in range(QUANT_WARMUP_STEPS):
            out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(QUANT_MEASURE_STEPS):
            out = fn(*args)
        jax.block_until_ready(out)
        return time.perf_counter() - t0, out

    with set_mesh(trainer.mesh):
        dt_float, logits_float = timed(fwd_float, params, model_state, x)
        dt_int8, logits_int8 = timed(fwd_int8, qparams, passthrough, model_state, x)
    # Host-side diff (numpy after device_get): eager jnp here would add
    # spurious tiny-program compiles to the watcher's tally.
    lf = np.asarray(jax.device_get(logits_float), np.float32)
    li = np.asarray(jax.device_get(logits_int8), np.float32)
    diff = float(np.max(np.abs(lf - li)))
    per_chip = lambda dt: round(batch * QUANT_MEASURE_STEPS / dt / n_chips, 2)
    float_bytes = tree_nbytes(params)
    int8_bytes = quantized_nbytes(qparams) + tree_nbytes(passthrough)
    return {
        "weights_dtype": "int8",
        "param_bytes_float": float_bytes,
        "param_bytes_int8": int8_bytes,
        "param_bytes_ratio": round(int8_bytes / float_bytes, 3) if float_bytes else None,
        "forward_images_per_sec_per_chip_float": per_chip(dt_float),
        "forward_images_per_sec_per_chip_int8": per_chip(dt_int8),
        "max_abs_logit_diff": round(diff, 4),
    }


def main() -> None:
    from deeplearning_cfn_tpu.analysis.compile_audit import (
        CompileWatcher,
        measure_donation,
    )
    from deeplearning_cfn_tpu.obs.profiler import (
        StepProfiler,
        program_attribution,
        program_cost,
    )
    from deeplearning_cfn_tpu.examples.common import enable_compile_cache
    from deeplearning_cfn_tpu.models.resnet import ResNet50
    from deeplearning_cfn_tpu.parallel.mesh import MeshSpec, build_mesh
    from deeplearning_cfn_tpu.train.data import (
        DevicePrefetcher,
        SyntheticDataset,
        device_put_tree,
        donate_buffers,
        stack_batches,
    )
    from deeplearning_cfn_tpu.train.pipeline import PipelineStats
    from deeplearning_cfn_tpu.train.trainer import Trainer, TrainerConfig

    enable_compile_cache()

    devices = jax.devices()
    n_chips = len(devices)
    batch = BATCH_PER_CHIP * n_chips

    mesh = build_mesh(MeshSpec.data_parallel(n_chips), devices)
    model = ResNet50(dtype=jnp.bfloat16)
    ds = SyntheticDataset.imagenet_like(
        batch_size=batch,
        image_size=IMAGE_SIZE,
        dtype="uint8",
        pool_batches=PIPELINE_POOL_BATCHES,
    )
    trainer = Trainer(
        model,
        mesh,
        TrainerConfig(
            strategy="dp",
            learning_rate=0.1,
            has_train_arg=True,
            label_smoothing=0.1,
            # uint8 inputs dequantize+normalize INSIDE the compiled step
            # (and inside the multi-step scan body) — the host never
            # touches a float image and every program owns its input stage.
            input_stats=ds.input_stats,
        ),
    )

    # One resident uint8 batch for the dispatch-bound phases (single-step
    # loop, donation probe, quantized forward): placed once, reused.
    b0 = next(iter(ds.batches(1)))
    x = jax.device_put(b0.x, trainer.batch_sharding)
    y = jax.device_put(b0.y, trainer.batch_sharding)

    # The watcher turns the whole bench into its own compile audit:
    # per-function compile counts from the jax_log_compiles stream, so a
    # retrace silently eating the timed window shows up as
    # retrace_count > 0 in the JSON instead of as an unexplained MFU dip
    # (docs/STATIC_ANALYSIS.md retrace runbook).
    with CompileWatcher() as watcher:
        state = trainer.init(jax.random.key(0), x)
        # Cost analysis before any donated execution: flops per compiled
        # step is the MFU numerator.  Keep the executable: its HLO is the
        # comms block's source (collectives + peak HBM).
        stats, step_exe = trainer.compile_stats(state, x, y, return_compiled=True)
        flops_per_step = stats.get("flops_per_step")

        step = trainer.step_fn
        # The ambient mesh is part of the jit cache key: compile_stats
        # AOT-compiles under set_mesh, so dispatching bare here would
        # miss that cache entry and pay the full ResNet-50 compile a
        # second time (this run's own compile audit caught exactly that:
        # step_fn compiled twice until the phase moved under set_mesh).
        with set_mesh(trainer.mesh):
            for _ in range(WARMUP_STEPS):
                state, metrics = step(state, x, y)
            # float() forces a device->host readback through the whole
            # step chain — block_until_ready alone proved unreliable on
            # relayed PJRT backends.
            float(metrics["loss"])
            # One extra untimed step proving the state buffers actually
            # get donated (is_deleted after dispatch): donated_bytes == 0
            # means the step holds two state copies live.
            (state, metrics), donation = measure_donation(step, state, x, y)

            # Phase attribution for the timed window: dispatch is the
            # per-call enqueue cost, compute surfaces at the final
            # readback (amortized over the window), host is the loop
            # residual.  The profiler's overhead budget is enforced by
            # scripts/perf_smoke.py (<2% of step time).
            prof_single = StepProfiler(name="single_step")
            t0 = time.perf_counter()
            prof_single.start()
            for _ in range(MEASURE_STEPS):
                with prof_single.phase("dispatch"):
                    state, metrics = step(state, x, y)
                prof_single.step_done()
            with prof_single.sync_boundary(MEASURE_STEPS):
                final_loss = float(metrics["loss"])
            dt_single = dt = time.perf_counter() - t0
        assert np.isfinite(final_loss)
        single_step_per_chip = batch * MEASURE_STEPS / dt / n_chips

        # Headline mode: k iterations per compiled program (STEPS_PER_CALL)
        # fed DISTINCT pre-staged batch stacks.  The prefetcher keeps
        # STACK_BUFFER [k, B, ...] uint8 stacks device-resident (producer
        # H2D overlaps the in-flight program's compute) and each consumed
        # stack is freed right after its dispatch — deletion is safe
        # in-flight, and it caps input HBM at ~STACK_BUFFER+1 stacks
        # (docs/PERFORMANCE.md, "the overlap architecture").
        k = STEPS_PER_CALL
        warmup_calls = max(1, WARMUP_STEPS // k)
        outer = max(1, MEASURE_STEPS // k)
        stacked_sharding = NamedSharding(mesh, P(None, *trainer.batch_sharding.spec))
        prof_multi = StepProfiler(name=f"multi_step_k{k}")
        stack_stats = PipelineStats(name="bench_stacks")
        stacked = stack_batches(ds.batches((warmup_calls + outer) * k), k)
        prefetcher = DevicePrefetcher(
            stacked,
            stacked_sharding,
            size=STACK_BUFFER,
            workers=PIPELINE_WORKERS,
            stats=stack_stats,
            profiler=prof_multi,
        )
        kfn = trainer.multi_step_fn(k)
        kexe = kcost = None
        stack_donated = 0
        resident_stacks_peak = 0
        t0 = None
        try:
            with set_mesh(trainer.mesh):
                prof_multi.start()
                for i, stack in enumerate(prof_multi.wrap_source(prefetcher)):
                    with prof_multi.phase("h2d"):
                        # Prefetched stacks are already resident with the
                        # stacked sharding — an identity check per leaf.
                        xs = device_put_tree(stack.x, stacked_sharding)
                        ys = device_put_tree(stack.y, stacked_sharding)
                    if kexe is None:
                        # AOT compile BEFORE the first dispatch: the
                        # per-program cost model for the k-step program
                        # (its flops cover all k iterations), and — like
                        # compile_stats for the single step — it populates
                        # the jit dispatch cache under this mesh, so the
                        # dispatch below hits the cache instead of
                        # compiling a second time (compile_count unchanged).
                        kexe = kfn.lower(state, xs, ys).compile()
                        kcost = program_cost(kexe)
                    resident_stacks_peak = max(
                        resident_stacks_peak, len(prefetcher.buffered())
                    )
                    with prof_multi.phase("dispatch"):
                        state, losses = kfn(state, xs, ys)
                    # The stack is this loop's own placement; XLA cannot
                    # donate it (no same-shaped output to alias into), so
                    # free it explicitly (train/data.donate_buffers).
                    stack_donated += donate_buffers((xs, ys))
                    if i == warmup_calls - 1:
                        with prof_multi.sync_boundary(warmup_calls * k):
                            float(np.asarray(jax.device_get(losses))[-1])
                        t0 = time.perf_counter()
                    prof_multi.step_done(steps=k)
                with prof_multi.sync_boundary(outer * k):
                    final_loss = float(np.asarray(jax.device_get(losses))[-1])
            dt_multi = dt = time.perf_counter() - t0
        finally:
            prefetcher.close()
        assert np.isfinite(final_loss)
        multi_step_per_chip = batch * outer * k / dt / n_chips

        # Quantized-forward first: the pipeline phase dispatches the
        # DONATING step, after which this scope's `state` buffers are gone.
        quantized = measure_quantized(trainer, model, state, x, batch, n_chips)
        pipeline, pipeline_profile = measure_input_pipeline(
            trainer, state, batch, n_chips
        )
    # Both modes are honest measurements and BOTH are reported (the old
    # harness silently dropped the loser); the headline is the better one,
    # since relay variance can invert the expected ordering on a bad draw.
    if multi_step_per_chip >= single_step_per_chip:
        per_chip, mode = multi_step_per_chip, f"multi_step_k{k}"
        mode_reason = (
            f"multi_step_k{k} ({multi_step_per_chip:.0f}) >= "
            f"single_step ({single_step_per_chip:.0f})"
        )
    else:
        per_chip, mode = single_step_per_chip, "single_step"
        mode_reason = (
            f"single_step ({single_step_per_chip:.0f}) beat "
            f"multi_step_k{k} ({multi_step_per_chip:.0f}) on this draw"
        )
    # Tag each phase profiler with ITS OWN dispatch mode (not the
    # winner — that's parsed.mode) so journaled step_profile events and
    # the step_time block attribute timings to the loop that produced
    # them.
    prof_single.set_label("mode", "single_step")
    prof_multi.set_label("mode", f"multi_step_k{k}")

    from deeplearning_cfn_tpu.train.metrics import peak_flops_per_chip

    peak = peak_flops_per_chip(devices[0])
    mfu = None
    if peak and flops_per_step:
        # cost_analysis flops are PER-DEVICE for an SPMD-partitioned
        # module (verified empirically on an 8-device mesh), so per-device
        # flop rate over per-chip peak is the per-chip MFU at any scale.
        steps_per_sec = per_chip * n_chips / batch
        mfu = flops_per_step * steps_per_sec / peak

    # Per-phase step-time breakdown (the MFU-plateau attribution): the
    # single-vs-multi-step gap must be explained by the phases — the
    # delta in per-step dispatch + host overhead is the mechanism the
    # k-step mode exists to amortize (docs/BENCH_NOTES.md); compute is
    # the same program body in both.
    snap_single = prof_single.journal()
    snap_multi = prof_multi.journal()
    gap_ms = (dt_single / MEASURE_STEPS - dt_multi / (outer * k)) * 1e3
    overhead_delta_ms = (
        snap_single["dispatch_ms"]
        + snap_single["host_ms"]
        - snap_multi["dispatch_ms"]
        - snap_multi["host_ms"]
    )
    step_time = {
        "single_step": snap_single,
        f"multi_step_k{k}": snap_multi,
        "input_pipeline": pipeline_profile,
        "gap": {
            "single_minus_multi_ms_per_step": round(gap_ms, 3),
            "dispatch_host_delta_ms_per_step": round(overhead_delta_ms, 3),
            "explained_fraction": round(overhead_delta_ms / gap_ms, 3)
            if abs(gap_ms) > 1e-6
            else None,
        },
    }
    # The overlap block is the acceptance surface for the double-buffered
    # input path: >= 2 stacks were device-resident during the timed
    # window, consumed stacks were actually freed, and the consumer's
    # data_wait stayed ~0 (the prefetcher ran ahead of compute).
    stack_snap = stack_stats.snapshot()
    overlap = {
        "steps_per_call": k,
        "stack_buffer": STACK_BUFFER,
        "device_resident_stacks_peak": resident_stacks_peak,
        "input_stack_donated_bytes": stack_donated,
        "stack_bytes_transferred": stack_snap["bytes_transferred"],
        "stack_overlap_fraction": stack_snap["overlap_fraction"],
        "data_wait_p50_ms": snap_multi.get("phases", {})
        .get("data_wait", {})
        .get("p50_ms", 0.0),
    }
    # Communication + HBM pressure per compiled program, read straight
    # off the executables' HLO/memory analysis (the other two MFU
    # killers the step-time blocks can't see — docs/STATIC_ANALYSIS.md
    # comms runbook).  Bytes are normalized per STEP so single- and
    # multi-step modes compare directly.
    from deeplearning_cfn_tpu.analysis.comms_audit import program_comms

    def comms_block(exe, steps_per_call: int) -> dict:
        c = program_comms(exe)
        return {
            "collective_count": c["collective_count"],
            "collective_bytes_per_step": c["collective_bytes"] // steps_per_call,
            "peak_hbm_bytes": c["peak_hbm_bytes"],
            # Schedule slack per collective (comms_audit.schedule_overlap)
            # — how much compute the scheduler has to hide each
            # collective behind; the DLC512-ratcheted number.
            "overlap_score": c["overlap_score"],
        }

    comms = {
        "train_step": comms_block(step_exe, 1),
        f"multi_step_k{k}": comms_block(kexe, k),
    }
    # Per-compiled-program MFU/MBU from each program's own cost model
    # and measured call time — attribution finer than whole-bench MFU.
    # "headline" marks the program the top-level value came from.
    programs = {
        "train_step": program_attribution(
            flops=stats.get("cost_flops_per_step"),
            bytes_accessed=stats.get("bytes_accessed"),
            seconds_per_call=dt_single / MEASURE_STEPS,
            steps_per_call=1,
            peak_flops=peak,
        ),
        f"multi_step_k{k}": program_attribution(
            flops=kcost["flops"],
            bytes_accessed=kcost["bytes_accessed"],
            seconds_per_call=dt_multi / outer,
            steps_per_call=k,
            peak_flops=peak,
        ),
    }
    programs["train_step"]["headline"] = mode == "single_step"
    programs[f"multi_step_k{k}"]["headline"] = mode == f"multi_step_k{k}"
    print(
        json.dumps(
            {
                "metric": "resnet50_synthetic_images_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / REFERENCE_IMAGES_PER_SEC_PER_DEVICE, 3),
                "mfu": round(mfu, 4) if mfu is not None else None,
                "mode": mode,
                "mode_reason": mode_reason,
                # What fed the step loop: "synthetic" (in-memory generated
                # batches) vs "records" (the train/datastream DLC1 shard
                # path).  Throughput numbers are only comparable within
                # one input mode — bench_compare refuses to diff across
                # them.
                "input_mode": "synthetic",
                "transfer_dtype": "uint8",
                "single_step_images_per_sec_per_chip": round(
                    single_step_per_chip, 2
                ),
                "multi_step_images_per_sec_per_chip": round(
                    multi_step_per_chip, 2
                ),
                "input_pipeline": pipeline,
                "overlap": overlap,
                "quantized": quantized,
                "step_time": step_time,
                "programs": programs,
                # Compile-behavior correlates for the MFU trajectory
                # (ISSUE 7): total XLA compiles this run, compiles beyond
                # the first per function (0 = steady-state zero-retrace),
                # and state bytes the step actually donated.
                "compile_count": watcher.compile_count,
                "retrace_count": watcher.retrace_count,
                "donated_bytes": donation.donated_bytes,
                "comms": comms,
                "flops_per_step": flops_per_step,
                "device_kind": str(getattr(devices[0], "device_kind", "unknown")),
                "n_chips": n_chips,
            },
            allow_nan=False,
        )
    )


if __name__ == "__main__":
    main()
