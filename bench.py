"""Headline benchmark: ResNet-50 synthetic ImageNet throughput per chip.

BASELINE.json's driver metric is "ResNet-50 ImageNet images/sec/chip".  The
reference's corresponding workload is the Horovod synthetic ResNet-50
benchmark (README.md:149-163), for which it publishes **no number**
(BASELINE.md).  ``vs_baseline`` is therefore computed against the era's
publicly documented tensorpack+Horovod ResNet-50 throughput on the
reference's own hardware class (~350 images/sec per V100 on p3.16xlarge,
fp16, batch 64/GPU) — the workload the reference stack existed to run.

Runs on whatever accelerator JAX exposes (the driver provides one real TPU
chip).  Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning_cfn_tpu.utils.compat import set_mesh

# Per-GPU throughput of the reference's flagship stack on its own hardware
# (tensorpack ResNet-50 + Horovod on V100, the workload of README.md:149-163).
REFERENCE_IMAGES_PER_SEC_PER_DEVICE = 350.0

BATCH_PER_CHIP = 128
IMAGE_SIZE = 224
WARMUP_STEPS = 5
MEASURE_STEPS = 20
# Iterations per compiled program (Trainer.multi_step_fn).  Round-5
# measurement (docs/BENCH_NOTES.md): putting k consecutive iterations in
# ONE module leaves cost-model bytes/iteration unchanged (no
# cross-iteration data reuse exists — activations are batch-unique) but
# measures ~9-14% faster per step: XLA pipelines the iteration boundary
# and the per-dispatch overhead amortizes.  k=4 is the measured knee.
STEPS_PER_CALL = 4


PIPELINE_WORKERS = 2
PIPELINE_POOL_BATCHES = 4


def measure_input_pipeline(trainer, state, batch: int, n_chips: int) -> dict:
    """End-to-end device-resident input pipeline measurement: pooled
    uint8 synthetic batches (4x smaller PCIe payload than float32)
    through ``DevicePrefetcher(workers=2)`` into the ALREADY-compiled
    bf16 train step, with dequantize+normalize as a small jitted stage in
    front (recompiling the full step for uint8 inputs would double the
    bench's compile bill for no measurement value).  Returns the
    per-chip throughput plus the PipelineStats counters."""
    from deeplearning_cfn_tpu.train.data import DevicePrefetcher, SyntheticDataset
    from deeplearning_cfn_tpu.train.pipeline import (
        PipelineStats,
        dequantize_normalize,
    )

    ds = SyntheticDataset.imagenet_like(
        batch_size=batch,
        image_size=IMAGE_SIZE,
        dtype="uint8",
        pool_batches=PIPELINE_POOL_BATCHES,
    )
    mean, std = ds.input_stats

    @jax.jit
    def dequant(x):
        return dequantize_normalize(x, mean, std, compute_dtype=jnp.bfloat16)

    steps = WARMUP_STEPS + MEASURE_STEPS
    stats = PipelineStats(name="bench")
    prefetcher = DevicePrefetcher(
        ds.batches(steps),
        trainer.batch_sharding,
        size=2,
        workers=PIPELINE_WORKERS,
        stats=stats,
    )
    step = trainer.step_fn
    t0 = None
    metrics = None
    try:
        with set_mesh(trainer.mesh):
            for i, b in enumerate(prefetcher):
                state, metrics = step(state, dequant(b.x), b.y)
                if i == WARMUP_STEPS - 1:
                    # Sync before opening the timed window.
                    float(metrics["loss"])
                    t0 = time.perf_counter()
        final_loss = float(metrics["loss"])
        dt = time.perf_counter() - t0
    finally:
        prefetcher.close()
    assert np.isfinite(final_loss)
    snap = stats.snapshot()
    per_chip = batch * MEASURE_STEPS / dt / n_chips
    return {
        "images_per_sec_per_chip": round(per_chip, 2),
        "transfer_dtype": "uint8",
        "workers": PIPELINE_WORKERS,
        "bytes_transferred": snap["bytes_transferred"],
        "bytes_per_image": round(snap["bytes_transferred"] / (batch * steps), 1),
        "host_input_seconds": snap["host_input_seconds"],
        "producer_stall_seconds": snap["producer_stall_seconds"],
        "consumer_wait_seconds": snap["consumer_wait_seconds"],
        "overlap_fraction": snap["overlap_fraction"],
    }


def main() -> None:
    from deeplearning_cfn_tpu.analysis.compile_audit import (
        CompileWatcher,
        measure_donation,
    )
    from deeplearning_cfn_tpu.examples.common import enable_compile_cache
    from deeplearning_cfn_tpu.models.resnet import ResNet50
    from deeplearning_cfn_tpu.parallel.mesh import MeshSpec, build_mesh
    from deeplearning_cfn_tpu.train.trainer import Trainer, TrainerConfig

    enable_compile_cache()

    devices = jax.devices()
    n_chips = len(devices)
    batch = BATCH_PER_CHIP * n_chips

    mesh = build_mesh(MeshSpec.data_parallel(n_chips), devices)
    model = ResNet50(dtype=jnp.bfloat16)
    trainer = Trainer(
        model,
        mesh,
        TrainerConfig(
            strategy="dp",
            learning_rate=0.1,
            has_train_arg=True,
            label_smoothing=0.1,
        ),
    )

    rng = np.random.default_rng(0)
    x = rng.standard_normal((batch, IMAGE_SIZE, IMAGE_SIZE, 3))
    y = rng.integers(0, 1000, size=batch).astype(np.int32)
    # bf16 inputs: halves the host->device bytes and matches compute dtype.
    x = jax.device_put(jnp.asarray(x, jnp.bfloat16), trainer.batch_sharding)
    y = jax.device_put(jnp.asarray(y), trainer.batch_sharding)

    # The watcher turns the whole bench into its own compile audit:
    # per-function compile counts from the jax_log_compiles stream, so a
    # retrace silently eating the timed window shows up as
    # retrace_count > 0 in the JSON instead of as an unexplained MFU dip
    # (docs/STATIC_ANALYSIS.md retrace runbook).
    with CompileWatcher() as watcher:
        state = trainer.init(jax.random.key(0), x)
        # Cost analysis before any donated execution: flops per compiled
        # step is the MFU numerator.
        stats = trainer.compile_stats(state, x, y)
        flops_per_step = stats.get("flops_per_step")

        step = trainer.step_fn
        # The ambient mesh is part of the jit cache key: compile_stats
        # AOT-compiles under set_mesh, so dispatching bare here would
        # miss that cache entry and pay the full ResNet-50 compile a
        # second time (this run's own compile audit caught exactly that:
        # step_fn compiled twice until the phase moved under set_mesh).
        with set_mesh(trainer.mesh):
            for _ in range(WARMUP_STEPS):
                state, metrics = step(state, x, y)
            # float() forces a device->host readback through the whole
            # step chain — block_until_ready alone proved unreliable on
            # relayed PJRT backends.
            float(metrics["loss"])
            # One extra untimed step proving the state buffers actually
            # get donated (is_deleted after dispatch): donated_bytes == 0
            # means the step holds two state copies live.
            (state, metrics), donation = measure_donation(step, state, x, y)

            t0 = time.perf_counter()
            for _ in range(MEASURE_STEPS):
                state, metrics = step(state, x, y)
            final_loss = float(metrics["loss"])
            dt = time.perf_counter() - t0
        assert np.isfinite(final_loss)
        single_step_per_chip = batch * MEASURE_STEPS / dt / n_chips

        # Headline mode: k iterations per compiled program (STEPS_PER_CALL).
        k = STEPS_PER_CALL
        with set_mesh(trainer.mesh):
            kfn = trainer.multi_step_fn(k)

            # One named jit for both broadcasts: done bare, each
            # jnp.broadcast_to dispatches its own anonymous
            # "broadcast_in_dim" program and the pair reads as a retrace
            # in the compile audit (same op name, two avals).
            @jax.jit
            def stack_k(a, b):
                return (
                    jnp.broadcast_to(a, (k, *a.shape)),
                    jnp.broadcast_to(b, (k, *b.shape)),
                )

            xs, ys = stack_k(x, y)
            for _ in range(max(1, WARMUP_STEPS // k)):
                state, losses = kfn(state, xs, ys)
            float(np.asarray(jax.device_get(losses))[-1])
            outer = max(1, MEASURE_STEPS // k)
            t0 = time.perf_counter()
            for _ in range(outer):
                state, losses = kfn(state, xs, ys)
            final_loss = float(np.asarray(jax.device_get(losses))[-1])
            dt = time.perf_counter() - t0
        assert np.isfinite(final_loss)
        multi_step_per_chip = batch * outer * k / dt / n_chips

        pipeline = measure_input_pipeline(trainer, state, batch, n_chips)
    # Both modes are honest measurements and BOTH are reported (the old
    # harness silently dropped the loser); the headline is the better one,
    # since relay variance can invert the expected ordering on a bad draw.
    if multi_step_per_chip >= single_step_per_chip:
        per_chip, mode = multi_step_per_chip, f"multi_step_k{k}"
        mode_reason = (
            f"multi_step_k{k} ({multi_step_per_chip:.0f}) >= "
            f"single_step ({single_step_per_chip:.0f})"
        )
    else:
        per_chip, mode = single_step_per_chip, "single_step"
        mode_reason = (
            f"single_step ({single_step_per_chip:.0f}) beat "
            f"multi_step_k{k} ({multi_step_per_chip:.0f}) on this draw"
        )

    from deeplearning_cfn_tpu.train.metrics import peak_flops_per_chip

    peak = peak_flops_per_chip(devices[0])
    mfu = None
    if peak and flops_per_step:
        # cost_analysis flops are PER-DEVICE for an SPMD-partitioned
        # module (verified empirically on an 8-device mesh), so per-device
        # flop rate over per-chip peak is the per-chip MFU at any scale.
        steps_per_sec = per_chip * n_chips / batch
        mfu = flops_per_step * steps_per_sec / peak
    print(
        json.dumps(
            {
                "metric": "resnet50_synthetic_images_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(per_chip / REFERENCE_IMAGES_PER_SEC_PER_DEVICE, 3),
                "mfu": round(mfu, 4) if mfu is not None else None,
                "mode": mode,
                "mode_reason": mode_reason,
                "single_step_images_per_sec_per_chip": round(
                    single_step_per_chip, 2
                ),
                "multi_step_images_per_sec_per_chip": round(
                    multi_step_per_chip, 2
                ),
                "input_pipeline": pipeline,
                # Compile-behavior correlates for the MFU trajectory
                # (ISSUE 7): total XLA compiles this run, compiles beyond
                # the first per function (0 = steady-state zero-retrace),
                # and state bytes the step actually donated.
                "compile_count": watcher.compile_count,
                "retrace_count": watcher.retrace_count,
                "donated_bytes": donation.donated_bytes,
                "flops_per_step": flops_per_step,
                "device_kind": str(getattr(devices[0], "device_kind", "unknown")),
                "n_chips": n_chips,
            },
            allow_nan=False,
        )
    )


if __name__ == "__main__":
    main()
