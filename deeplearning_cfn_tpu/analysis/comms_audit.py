"""The dynamic comms-audit sentinel: machine-read the HLO a step ships.

The DLC50x static rules (analysis/collectives.py) catch the *source
patterns* that tend to produce accidental collectives; this module
measures the collectives that actually end up in the compiled program.
It lowers and compiles the real ``Trainer`` train step, the multi-step
scan body, and the serve decode step on the virtual CPU mesh, then reads
three machine signals off each executable:

- the optimized HLO text (``compiled.as_text()``), scanned for
  ``all-gather`` / ``all-reduce`` / ``reduce-scatter`` /
  ``collective-permute`` / ``all-to-all`` ops (async ``-start`` variants
  count once; their ``-done`` halves are skipped) with per-op result
  shapes and byte sizes;
- ``cost_analysis()`` flops and bytes-accessed, normalized the same way
  as ``obs.profiler.program_cost``;
- ``memory_analysis()`` — argument/output/temp/alias sizes folded into a
  peak-HBM estimate, the number that decides whether a sharding change
  fits on a 16 GiB chip.

Each audited program yields a **comms budget**
``{collective_count, collective_bytes, peak_hbm_bytes, overlap_score}``.
The budget is committed (scripts/comms_budget.json) and ratcheted:
DLC510 fires when a program's collective op count or bytes regress over
the committed numbers, DLC511 when an fsdp-strategy step contains an
all-gather the strategy doesn't predict — fsdp shards *parameters*, so
the only gathers it earns are parameter/optimizer-state shaped; a gather
matching no train-state leaf means a batch or activation got
materialized replicated (the classic missing
``with_sharding_constraint``).

``overlap_score`` machine-reads the optimized *schedule*, not just the
op set: per computation, every collective issue point is charged the
number of non-collective ops between it and the next collective
boundary — the compute the scheduler has available to hide that
collective behind (for an async pair, the ops between ``-start`` and
``-done`` fall out of the same walk).  The score is mean slack per
collective; a bucketed program that issues sync early scores strictly
higher than the monolithic end-of-backward bundle.  DLC512 ratchets it:
a score falling below the committed number — or a ``*_overlap``
program failing to strictly beat its monolithic baseline — is a
serialized collective that a bucket boundary could hide
(parallel/overlap.py; docs/PERFORMANCE.md "Hiding the collectives").

Findings are ordinary :class:`Violation`\\ s flowing through the same
suppression-baseline ratchet as the DLC41x compile audit
(scripts/lint_baseline.json, namespace-scoped via
``runner.apply_audit_baseline``), and results are journaled to the
flight recorder as ``comms_audit`` events so communication history rides
the same JSONL stream as retraces and step times.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import jax

from deeplearning_cfn_tpu.analysis.collectives import (
    AUDIT_RULE_BUDGET,
    AUDIT_RULE_OVERLAP,
    AUDIT_RULE_UNPREDICTED,
)
from deeplearning_cfn_tpu.analysis.core import Violation
from deeplearning_cfn_tpu.obs.profiler import program_cost

REPO_ROOT = Path(__file__).resolve().parents[2]
# Findings anchor on the file that owns the audited step (baseline key
# is (rule, repo-relative path, message) — same contract as DLC41x).
AUDITED_FILE = REPO_ROOT / "deeplearning_cfn_tpu" / "train" / "trainer.py"
SERVE_AUDITED_FILE = REPO_ROOT / "deeplearning_cfn_tpu" / "serve" / "engine.py"
DEFAULT_BUDGET_PATH = REPO_ROOT / "scripts" / "comms_budget.json"

COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# An HLO collective instruction looks like
#   %all-gather.1 = f32[16,64]{1,0} all-gather(f32[2,64]{1,0} %p), ...
# or, async, `... all-gather-start(...)` paired with a `-done` op that
# carries the same bytes (count the start, skip the done).  The result
# shape is either one `dtype[dims]{layout}` token or a tuple
# `(f32[..]{..}, u32[], ...)` which may contain spaces.
_COLLECTIVE_RE = re.compile(
    r"=\s+((?:\([^)]*\))|\S+)\s+"
    r"(all-gather|all-reduce|reduce-scatter|collective-permute|all-to-all)"
    r"(-start)?\("
)
_SHAPE_RE = re.compile(r"(pred|[a-z]\d*[a-z]*\d*)\[([0-9,]*)\]")
_DTYPE_BYTES = {
    "pred": 1,
    "s8": 1,
    "u8": 1,
    "f8e4m3fn": 1,
    "f8e5m2": 1,
    "s16": 2,
    "u16": 2,
    "f16": 2,
    "bf16": 2,
    "s32": 4,
    "u32": 4,
    "f32": 4,
    "s64": 8,
    "u64": 8,
    "f64": 8,
    "c64": 8,
    "c128": 16,
}


@dataclass(frozen=True)
class CollectiveOp:
    """One collective instruction read out of optimized HLO."""

    op: str
    result_shapes: tuple[tuple[int, ...], ...]
    nbytes: int

    def to_dict(self) -> dict:
        return {
            "op": self.op,
            "result_shapes": [list(s) for s in self.result_shapes],
            "nbytes": self.nbytes,
        }


def _parse_shapes(shape_text: str) -> tuple[list[tuple[int, ...]], int]:
    """All ``dtype[dims]`` members of an HLO shape string -> (shapes, bytes)."""
    shapes: list[tuple[int, ...]] = []
    nbytes = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        shape = tuple(int(d) for d in dims.split(",") if d)
        shapes.append(shape)
        elems = 1
        for d in shape:
            elems *= d
        nbytes += elems * _DTYPE_BYTES.get(dtype, 4)
    return shapes, nbytes


def hlo_collectives(hlo_text: str) -> list[CollectiveOp]:
    """Scan optimized HLO text for collective ops with result sizes."""
    out: list[CollectiveOp] = []
    for match in _COLLECTIVE_RE.finditer(hlo_text):
        shapes, nbytes = _parse_shapes(match.group(1))
        out.append(
            CollectiveOp(
                op=match.group(2), result_shapes=tuple(shapes), nbytes=nbytes
            )
        )
    return out


# --- the schedule reader (overlap_score) -------------------------------------

# An instruction line is indented and assigns a %-named value; the op
# name follows the result shape (a single `dtype[..]{..}` token or a
# parenthesized tuple, which may contain spaces and `/*index=k*/`
# comments).
_INSTR_RE = re.compile(r"^\s+(ROOT\s+)?%?[\w.\-]+\s+=\s+")
_OP_RE = re.compile(r"=\s+(?:\([^)]*\)|\S+)\s+([\w\-]+)\(")
_COLLECTIVE_NAMES = frozenset(
    name + suffix
    for name in COLLECTIVE_OPS
    for suffix in ("", "-start", "-done")
)


def hlo_computation_ops(hlo_text: str) -> dict[str, list[str]]:
    """Optimized HLO text -> ordered op names per computation.

    HLO prints instructions in SCHEDULE order inside each computation
    (`ENTRY`/`%fused`/`%while_body` headers start at column zero and end
    with ``{``), which is what makes positional slack a faithful read of
    what the backend will execute between two collectives.
    """
    comps: dict[str, list[str]] = {}
    cur: list[str] | None = None
    for line in hlo_text.splitlines():
        if (
            line.rstrip().endswith("{")
            and not line.startswith((" ", "\t"))
            and ("%" in line or line.startswith("ENTRY"))
        ):
            cur = comps[line.split("(")[0].strip()] = []
        elif line.strip() == "}":
            cur = None
        elif cur is not None and _INSTR_RE.match(line):
            m = _OP_RE.search(line)
            if m:
                cur.append(m.group(1))
    return comps


def schedule_overlap(hlo_text: str) -> dict:
    """Mean compute slack per collective across the whole module.

    For every collective ISSUE point (plain or ``-start``; ``-done``
    halves are not issue points but do act as boundaries), slack is the
    count of non-collective ops strictly between it and the next
    collective boundary — or the end of its computation for the last
    one.  Async pairs need no special case: the ops between ``-start``
    and ``-done`` are exactly the start's slack.  A slack-0 issue point
    is a SERIALIZED collective — nothing is scheduled for the backend
    to hide it behind.

    Returns ``{"overlap_score": float, "serialized_collectives": int,
    "scheduled_collectives": int}``; score is 0.0 for collective-free
    programs.
    """
    total_slack = 0
    n_issue = 0
    n_serialized = 0
    for ops in hlo_computation_ops(hlo_text).values():
        idxs = [i for i, op in enumerate(ops) if op in _COLLECTIVE_NAMES]
        for j, i in enumerate(idxs):
            if ops[i].endswith("-done"):
                continue
            boundary = idxs[j + 1] if j + 1 < len(idxs) else len(ops)
            slack = boundary - i - 1
            total_slack += slack
            n_issue += 1
            if slack == 0:
                n_serialized += 1
    return {
        "overlap_score": round(total_slack / max(n_issue, 1), 4),
        "serialized_collectives": n_serialized,
        "scheduled_collectives": n_issue,
    }


def _peak_hbm_bytes(compiled: Any) -> int:
    """Fold ``memory_analysis()`` into one peak-HBM estimate.

    arguments + outputs + temporaries, minus aliased (donated) bytes —
    the resident set the program needs at its widest point, which is the
    number a sharding mistake inflates.
    """
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return 0
    if mem is None:
        return 0
    total = 0
    for name, sign in (
        ("argument_size_in_bytes", 1),
        ("output_size_in_bytes", 1),
        ("temp_size_in_bytes", 1),
        ("alias_size_in_bytes", -1),
    ):
        total += sign * int(getattr(mem, name, 0) or 0)
    return max(total, 0)


def program_comms(compiled: Any) -> dict:
    """The full comms/memory readout for one AOT-compiled program."""
    try:
        text = compiled.as_text()
    except Exception:
        text = ""
    ops = hlo_collectives(text)
    by_op = {name: 0 for name in COLLECTIVE_OPS}
    bytes_by_op = {name: 0 for name in COLLECTIVE_OPS}
    for op in ops:
        by_op[op.op] += 1
        bytes_by_op[op.op] += op.nbytes
    cost = program_cost(compiled)
    overlap = schedule_overlap(text)
    return {
        "collective_count": len(ops),
        "collective_bytes": sum(op.nbytes for op in ops),
        "peak_hbm_bytes": _peak_hbm_bytes(compiled),
        "overlap_score": overlap["overlap_score"],
        "serialized_collectives": overlap["serialized_collectives"],
        "by_op": {k: v for k, v in by_op.items() if v},
        "bytes_by_op": {k: v for k, v in bytes_by_op.items() if v},
        "flops": cost["flops"],
        "bytes_accessed": cost["bytes_accessed"],
        "ops": ops,
    }


# --- strategy prediction (DLC511) -------------------------------------------


@dataclass(frozen=True)
class StrategyPrediction:
    """The all-gathers an fsdp step is entitled to emit.

    fsdp shards parameters and optimizer state across the ``fsdp`` axis
    and gathers them around use — so every legitimate all-gather result
    is shaped like a train-state leaf.  Anything else (a batch array, a
    hidden activation) means the partitioner materialized data
    replicated that the strategy meant to keep sharded.
    """

    leaf_shapes: frozenset[tuple[int, ...]]

    @classmethod
    def from_state(cls, state: Any) -> "StrategyPrediction":
        shapes = {
            tuple(getattr(leaf, "shape", ()))
            for leaf in jax.tree_util.tree_leaves(state)
        }
        return cls(leaf_shapes=frozenset(shapes))

    def predicts(self, shape: tuple[int, ...]) -> bool:
        return tuple(shape) in self.leaf_shapes


def _dims(shape: tuple[int, ...]) -> str:
    return "x".join(str(d) for d in shape) if shape else "scalar"


# --- the watcher ------------------------------------------------------------


@dataclass
class ProgramComms:
    """One audited program's comms budget + DLC511 evidence."""

    name: str
    collective_count: int
    collective_bytes: int
    peak_hbm_bytes: int
    by_op: dict[str, int]
    bytes_by_op: dict[str, int]
    flops: float | None
    bytes_accessed: float | None
    # Mean compute slack per collective in the optimized schedule
    # (schedule_overlap) — the ratcheted latency-hiding signal — and the
    # count of slack-0 (fully serialized) collectives behind it.
    overlap_score: float = 0.0
    serialized_collectives: int = 0
    # Distinct all-gather result shapes the strategy does not predict
    # (empty when no prediction applies, e.g. the serve decode path).
    unpredicted_gathers: tuple[tuple[int, ...], ...] = ()
    audited_file: str | None = None

    @property
    def budget(self) -> dict:
        return {
            "collective_count": self.collective_count,
            "collective_bytes": self.collective_bytes,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "overlap_score": self.overlap_score,
        }

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            **self.budget,
            "serialized_collectives": self.serialized_collectives,
            "by_op": dict(sorted(self.by_op.items())),
            "bytes_by_op": dict(sorted(self.bytes_by_op.items())),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "unpredicted_gathers": [list(s) for s in self.unpredicted_gathers],
        }


class CommsWatcher:
    """Accumulates per-program comms budgets from AOT-compiled modules.

    Unlike :class:`~.compile_audit.CompileWatcher` (which listens to the
    dispatch layer while code *runs*), this watcher is fed explicitly:
    ``watch()`` takes an already-compiled executable, reads its HLO, and
    records the budget — compilation is the measurement, no execution
    happens.
    """

    def __init__(self) -> None:
        self.programs: list[ProgramComms] = []

    def watch(
        self,
        name: str,
        compiled: Any,
        prediction: StrategyPrediction | None = None,
        audited_file: str | None = None,
    ) -> ProgramComms:
        comms = program_comms(compiled)
        unpredicted: list[tuple[int, ...]] = []
        if prediction is not None:
            seen: set[tuple[int, ...]] = set()
            for op in comms["ops"]:
                if op.op != "all-gather":
                    continue
                for shape in op.result_shapes:
                    # Async gathers carry u32[] control members; only
                    # real payload shapes can be "unpredicted".
                    if len(shape) == 0:
                        continue
                    if not prediction.predicts(shape) and shape not in seen:
                        seen.add(shape)
                        unpredicted.append(shape)
        program = ProgramComms(
            name=name,
            collective_count=comms["collective_count"],
            collective_bytes=comms["collective_bytes"],
            peak_hbm_bytes=comms["peak_hbm_bytes"],
            by_op=comms["by_op"],
            bytes_by_op=comms["bytes_by_op"],
            flops=comms["flops"],
            bytes_accessed=comms["bytes_accessed"],
            overlap_score=comms["overlap_score"],
            serialized_collectives=comms["serialized_collectives"],
            unpredicted_gathers=tuple(sorted(unpredicted)),
            audited_file=audited_file,
        )
        self.programs.append(program)
        return program

    def budgets(self) -> dict[str, dict]:
        return {p.name: p.budget for p in self.programs}


# --- committed budget (the ratchet's numbers) -------------------------------


def load_budget(path: Path | str = DEFAULT_BUDGET_PATH) -> dict | None:
    """The committed per-program budget, or None when not yet written."""
    p = Path(path)
    if not p.exists():
        return None
    data = json.loads(p.read_text())
    if not isinstance(data, dict) or "programs" not in data:
        return None
    return data


def write_budget(
    programs: list[ProgramComms],
    path: Path | str = DEFAULT_BUDGET_PATH,
    device_count: int | None = None,
) -> dict:
    payload = {
        "device_count": (
            device_count if device_count is not None else jax.device_count()
        ),
        "programs": {p.name: p.budget for p in programs},
    }
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")
    return payload


# --- findings ---------------------------------------------------------------


def violations_for(
    programs: list[ProgramComms],
    budget: dict | None,
    device_count: int,
) -> list[Violation]:
    """Fold audited programs into baseline-ratchet findings.

    Messages are count-free and shape-stable: the audit model, batch
    size, and mesh are fixed constants, so the same program compiles to
    the same collectives run over run — a changed message IS a changed
    program.  DLC511 emits one finding per distinct unpredicted gather
    shape so a future regression fails fresh instead of hiding behind an
    existing entry.
    """
    out: list[Violation] = []
    budget_programs = {}
    if budget is not None and int(budget.get("device_count", -1)) == device_count:
        budget_programs = budget.get("programs", {})
    by_name = {p.name: p for p in programs}
    for p in programs:
        # The overlap pair invariant needs no committed budget: a
        # `<name>_overlap` program exists to BEAT `<name>`, so a score
        # that fails to strictly exceed the monolithic baseline's means
        # the bucket schedule serialized a collective it was built to
        # hide.
        base = by_name.get(p.name[: -len("_overlap")]) if p.name.endswith(
            "_overlap"
        ) else None
        if base is not None and p.overlap_score <= base.overlap_score:
            out.append(
                Violation(
                    rule=AUDIT_RULE_OVERLAP,
                    path=p.audited_file or str(AUDITED_FILE),
                    line=1,
                    col=1,
                    message=(
                        f"serialized collective on the {p.name} path: the "
                        "bucketed program's overlap_score does not strictly "
                        f"exceed the monolithic {base.name} baseline's — the "
                        "explicit bucket schedule is buying no latency "
                        "hiding (parallel/overlap.py; comms-audit sentinel, "
                        "see docs/STATIC_ANALYSIS.md comms runbook)"
                    ),
                )
            )
    for p in programs:
        anchor = p.audited_file or str(AUDITED_FILE)
        for shape in p.unpredicted_gathers:
            out.append(
                Violation(
                    rule=AUDIT_RULE_UNPREDICTED,
                    path=anchor,
                    line=1,
                    col=1,
                    message=(
                        f"unpredicted all-gather on the {p.name} path: the "
                        f"compiled fsdp step gathers a {_dims(shape)} array "
                        "that matches no train-state leaf — fsdp predicts "
                        "parameter/optimizer gathers only, so a batch or "
                        "activation is being materialized replicated "
                        "(comms-audit sentinel; see docs/STATIC_ANALYSIS.md "
                        "comms runbook)"
                    ),
                )
            )
        committed = budget_programs.get(p.name)
        if committed is None:
            continue
        over_count = p.collective_count > int(committed["collective_count"])
        over_bytes = p.collective_bytes > int(committed["collective_bytes"])
        if over_count or over_bytes:
            grew = " and ".join(
                what
                for what, over in (
                    ("op count", over_count),
                    ("bytes", over_bytes),
                )
                if over
            )
            out.append(
                Violation(
                    rule=AUDIT_RULE_BUDGET,
                    path=anchor,
                    line=1,
                    col=1,
                    message=(
                        f"comms budget regression on the {p.name} path: "
                        f"collective {grew} exceed the committed budget "
                        "(scripts/comms_budget.json; re-measure with "
                        "scripts/comms_audit.py --write-budget if the "
                        "increase is intended — comms-audit sentinel, see "
                        "docs/STATIC_ANALYSIS.md comms runbook)"
                    ),
                )
            )
        committed_score = committed.get("overlap_score")
        if committed_score is not None and p.overlap_score < float(
            committed_score
        ):
            out.append(
                Violation(
                    rule=AUDIT_RULE_OVERLAP,
                    path=anchor,
                    line=1,
                    col=1,
                    message=(
                        f"overlap regression on the {p.name} path: the "
                        "compiled schedule's overlap_score fell below the "
                        "committed budget — a gradient-sync collective that "
                        "a bucket boundary could hide is now serialized "
                        "(scripts/comms_budget.json; re-measure with "
                        "scripts/comms_audit.py --write-budget if the drop "
                        "is intended — comms-audit sentinel, see "
                        "docs/STATIC_ANALYSIS.md comms runbook)"
                    ),
                )
            )
    return out


# --- the audit itself -------------------------------------------------------


@dataclass
class CommsAuditReport:
    programs: list[ProgramComms]
    violations: list[Violation]
    device_count: int
    budget_checked: bool
    measured: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "programs": [p.to_dict() for p in self.programs],
            "budgets": {p.name: p.budget for p in self.programs},
            "violations": [v.to_dict() for v in self.violations],
            "device_count": self.device_count,
            "budget_checked": self.budget_checked,
            "clean": not self.violations,
        }


# The audit model is a fixed constant: its train state must contain at
# least one leaf big enough for the fsdp heuristic to shard (Dense(256)
# kernel = 64*256 elements, exactly the min-shard threshold), and the
# global batch must divide the 8-way mesh.  Changing any of these
# numbers changes the committed budget — regenerate it deliberately.
AUDIT_BATCH_SIZE = 16
AUDIT_HIDDEN = 256
AUDIT_CLASSES = 4
AUDIT_INPUT_SHAPE = (8, 8, 1)


def _audit_model():
    import flax.linen as nn

    hidden, classes = AUDIT_HIDDEN, AUDIT_CLASSES

    class _CommsAuditMLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(hidden)(x)
            x = nn.relu(x)
            return nn.Dense(classes)(x)

    return _CommsAuditMLP()


def run_comms_audit(
    k: int = 2,
    journal: bool = True,
    budget_path: Path | str | None = DEFAULT_BUDGET_PATH,
    serve: bool = True,
) -> CommsAuditReport:
    """Audit the real fsdp train step, multi-step scan body, serve
    decode step, and the dp comms-overlap pair for communication and
    HBM pressure.

    The dp pair is the overlap ratchet's proof surface: the SAME model,
    batch, and mesh lowered monolithically (``train_step_dp``) and
    through the bucketed engine (``train_step_dp_overlap``,
    ``multi_step_dp_overlap`` with grad accumulation pipelining sync
    into the scan body) — DLC512 requires the bucketed schedule's
    overlap_score to strictly exceed the monolithic baseline's.

    Pure lower+compile — no step executes, so the audit is fast and
    deterministic: the same source compiles to the same HLO, which is
    what makes an exact-match budget ratchet possible.
    """
    import numpy as np

    from deeplearning_cfn_tpu.parallel.mesh import MeshSpec, build_mesh
    from deeplearning_cfn_tpu.train.data import SyntheticDataset
    from deeplearning_cfn_tpu.train.trainer import Trainer, TrainerConfig
    from deeplearning_cfn_tpu.utils import compat

    devices = jax.devices()
    n = 8 if len(devices) >= 8 else len(devices)
    mesh = build_mesh(MeshSpec.fsdp_parallel(n), devices[:n])
    ds = SyntheticDataset(
        shape=AUDIT_INPUT_SHAPE,
        num_classes=AUDIT_CLASSES,
        batch_size=AUDIT_BATCH_SIZE,
        seed=0,
    )
    trainer = Trainer(
        _audit_model(),
        mesh,
        TrainerConfig(learning_rate=0.05, optimizer="sgd", strategy="fsdp"),
    )
    sample = next(iter(ds.batches(1)))
    watcher = CommsWatcher()
    with compat.set_mesh(mesh):
        state = trainer.init(jax.random.PRNGKey(0), sample.x)
        prediction = StrategyPrediction.from_state(state)

        compiled_step = trainer.step_fn.lower(state, sample.x, sample.y).compile()
        watcher.watch("train_step", compiled_step, prediction=prediction)

        kfn = trainer.multi_step_fn(k)
        stack = list(ds.batches(k))
        xs = np.stack([b.x for b in stack])
        ys = np.stack([b.y for b in stack])
        compiled_multi = kfn.lower(state, xs, ys).compile()
        watcher.watch("multi_step", compiled_multi, prediction=prediction)

    # The dp overlap pair: monolithic vs bucketed sync on an identical
    # dp mesh/model/batch.  The small bucket target (32 KiB against the
    # ~270 KiB audit param tree) forces several fused buckets so the
    # schedule genuinely interleaves sync with compute; grad accumulation
    # on the multi-step variant exercises the pipelined scan body.
    dp_mesh = build_mesh(MeshSpec.data_parallel(n), devices[:n])
    dp_kwargs = dict(learning_rate=0.05, optimizer="sgd", strategy="dp")
    mono_dp = Trainer(_audit_model(), dp_mesh, TrainerConfig(**dp_kwargs))
    overlap_dp = Trainer(
        _audit_model(),
        dp_mesh,
        TrainerConfig(
            comms_overlap=True, overlap_bucket_bytes=32 * 1024, **dp_kwargs
        ),
    )
    overlap_accum_dp = Trainer(
        _audit_model(),
        dp_mesh,
        TrainerConfig(
            comms_overlap=True,
            overlap_bucket_bytes=32 * 1024,
            grad_accum_steps=2,
            **dp_kwargs,
        ),
    )
    with compat.set_mesh(dp_mesh):
        dp_state = mono_dp.init(jax.random.PRNGKey(0), sample.x)
        dp_prediction = StrategyPrediction.from_state(dp_state)
        watcher.watch(
            "train_step_dp",
            mono_dp.step_fn.lower(dp_state, sample.x, sample.y).compile(),
            prediction=dp_prediction,
        )
        ov_state = overlap_dp.init(jax.random.PRNGKey(0), sample.x)
        watcher.watch(
            "train_step_dp_overlap",
            overlap_dp.step_fn.lower(ov_state, sample.x, sample.y).compile(),
            prediction=dp_prediction,
        )
        acc_state = overlap_accum_dp.init(jax.random.PRNGKey(0), sample.x)
        kfn_ov = overlap_accum_dp.multi_step_fn(k)
        watcher.watch(
            "multi_step_dp_overlap",
            kfn_ov.lower(acc_state, xs, ys).compile(),
            prediction=dp_prediction,
        )

    if serve:
        watcher.programs.append(_audit_serve_decode())

    budget = load_budget(budget_path) if budget_path is not None else None
    violations = violations_for(watcher.programs, budget, device_count=n)
    report = CommsAuditReport(
        programs=watcher.programs,
        violations=violations,
        device_count=n,
        budget_checked=bool(
            budget is not None
            and int(budget.get("device_count", -1)) == n
        ),
    )
    if journal:
        from deeplearning_cfn_tpu.obs.recorder import get_recorder

        get_recorder().record(
            "comms_audit",
            clean=not violations,
            device_count=n,
            programs={p.name: p.to_dict() for p in watcher.programs},
        )
    return report


def _audit_serve_decode() -> ProgramComms:
    """Lower+compile the real paged decode step on the default device.

    Single-device serving has no collectives by construction; the decode
    budget's load-bearing number is ``peak_hbm_bytes`` — the paged K/V
    pool must stay aliased (donated), not doubled.
    """
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    from deeplearning_cfn_tpu.models.llama import LlamaConfig, init_params
    from deeplearning_cfn_tpu.serve.engine import (
        ContinuousBatchingEngine,
        ServeConfig,
        paged_decode_step,
    )

    cfg = dataclasses.replace(
        LlamaConfig.tiny(vocab_size=64, seq_len=64), dtype=jnp.float32
    )
    params = init_params(cfg, jax.random.key(0))
    scfg = ServeConfig(num_slots=4, block_size=4, blocks_per_slot=8, prefill_len=16)
    engine = ContinuousBatchingEngine(
        cfg, params, scfg, clock=lambda: 0.0, journal=False
    )
    tokens = np.zeros(scfg.num_slots, np.int32)
    lengths = np.zeros(scfg.num_slots, np.int32)
    tables = np.zeros((scfg.num_slots, scfg.blocks_per_slot), np.int32)
    active = np.zeros(scfg.num_slots, bool)
    compiled = paged_decode_step.lower(
        cfg,
        engine.params,
        engine.cache,
        tokens,
        lengths,
        tables,
        active,
        engine._key,
        temperature=scfg.temperature,
    ).compile()
    watcher = CommsWatcher()
    return watcher.watch(
        "serve_decode", compiled, audited_file=str(SERVE_AUDITED_FILE)
    )
