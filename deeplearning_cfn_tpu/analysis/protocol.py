"""DLC3xx: the broker protocol state-machine / message-shape checker.

DLC100 proves every layer *names* the same verbs; these rules prove the
layers agree on each verb's *message shape* — the drift DLC100 cannot
see (client sends three request tokens, C++ extracts two; broker renames
a reply token; an HB frame loses a field).  Four homes are cross-checked:

1. the canonical per-verb spec comments on
   ``cluster/contract.py:BROKER_PROTOCOL_VERBS`` (``# SEND <queue>
   <nbytes>\\n<body> ...`` — machine-read, so the docs cannot rot);
2. the Python client's wire writes and reply parsing
   (``cluster/broker_client.py``, via AST);
3. the C++ handler chain (``native/broker/broker.cpp``, via the same
   tolerant segment scan DLC100 uses — no C++ parser);
4. the lifecycle-kind vocabulary: ``EventKind`` members, the kinds
   publishers construct, the kinds the elasticity controller dispatches,
   and the flight-journal ``kind`` strings consumers filter on.

DLC300 request-shape drift   per-verb argument count + payload presence:
                             canonical spec vs client template vs C++
                             ``>>`` extraction / read_exact
DLC301 reply-token drift     every reply token the client tests for
                             (``== "PONG"``, ``startswith("OK ")``) must
                             be one the C++ handler emits for that verb
DLC302 frame-shape drift     multi-line frames (MSG/HB): tag + token
                             arity the client unpacks vs what the C++
                             response concatenation emits
DLC303 lifecycle-kind drift  ``EventKind.X`` references must be defined
                             members; every published kind must be
                             dispatched by the elasticity controller;
                             every journal ``kind=`` a reader filters on
                             must be one some ``record()`` call produces

Like contract_check, every extractor takes its source path as an
argument so tests can run the checker against mutated fixture copies.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterable

from deeplearning_cfn_tpu.analysis.core import Violation, dotted_name
from deeplearning_cfn_tpu.analysis.contract_check import (
    BROKER_CPP,
    CLIENT_PY,
    CONTRACT_PY,
    REPO_ROOT,
    _CPP_HANDLER,
    _parse,
    canonical_verbs,
    client_verb_map,
)

RULE_REQUEST = "DLC300"
RULE_REPLY = "DLC301"
RULE_FRAME = "DLC302"
RULE_LIFECYCLE = "DLC303"

EVENTS_PY = REPO_ROOT / "deeplearning_cfn_tpu" / "provision" / "events.py"
ELASTICITY_PY = REPO_ROOT / "deeplearning_cfn_tpu" / "cluster" / "elasticity.py"
PACKAGE_DIR = REPO_ROOT / "deeplearning_cfn_tpu"

# One request shape: (argument token count, carries a length-prefixed payload).
Shape = tuple[int, bool]

_TOKEN = re.compile(r"^[A-Z]{1,16}$")
_SPEC_ARGS = re.compile(r"^(?:\s*<\w+>)*")


# --- layer 1: canonical shapes from the contract.py spec comments ----------
def canonical_shapes(contract_py: Path = CONTRACT_PY) -> dict[str, set[Shape]]:
    """verb -> request shapes, parsed from the ``# VERB <arg>...`` comment
    lines inside the BROKER_PROTOCOL_VERBS assignment.  A verb may carry
    several spec lines (HEARTBEAT's record and dump modes)."""
    verbs, _ = canonical_verbs(contract_py)
    source = contract_py.read_text()
    m = re.search(
        r"BROKER_PROTOCOL_VERBS\s*(?::[^=]+)?=\s*\(", source
    )
    if m is None:
        return {}
    depth = 0
    end = m.end()
    for i in range(m.end() - 1, len(source)):
        if source[i] == "(":
            depth += 1
        elif source[i] == ")":
            depth -= 1
            if depth == 0:
                end = i
                break
    segment = source[m.start():end]
    out: dict[str, set[Shape]] = {}
    for comment in re.findall(r"#\s*([A-Z]{2,16})\b([^\n]*)", segment):
        verb, rest = comment
        if verb not in verbs:
            continue
        args_m = _SPEC_ARGS.match(rest)
        head = args_m.group(0) if args_m else ""
        nargs = len(re.findall(r"<\w+>", head))
        # A payload spec is the literal two-character "\n" followed by a
        # <name> token, immediately after the argument list.
        payload = rest[len(head):].startswith("\\n<")
        out.setdefault(verb, set()).add((nargs, payload))
    return out


# --- layer 2: client request shapes, reply tokens, frames ------------------
def _header_template(expr: ast.AST) -> tuple[str | None, bool]:
    """(header text with {} placeholders, payload appended?) for a
    ``sendall`` argument.  Mirrors contract_check._leading_literal but
    keeps the whole first line, so token arity is recoverable."""
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        header, _ = _header_template(expr.left)
        return header, True
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "encode"
    ):
        return _header_template(expr.func.value)
    if isinstance(expr, ast.JoinedStr):
        parts: list[str] = []
        for value in expr.values:
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                parts.append(value.value)
            else:
                parts.append("{}")
        return "".join(parts), False
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, bytes):
            return expr.value.decode(errors="replace"), False
        if isinstance(expr.value, str):
            return expr.value, False
    return None, False


def client_request_shapes(client_py: Path = CLIENT_PY) -> dict[str, set[Shape]]:
    """verb -> (token count, payload?) shapes the client writes."""
    tree = _parse(client_py)
    out: dict[str, set[Shape]] = {}
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "sendall"
            and node.args
        ):
            continue
        header, payload = _header_template(node.args[0])
        if header is None:
            continue
        tokens = header.split("\n", 1)[0].split()
        if not tokens or not _TOKEN.fullmatch(tokens[0]):
            continue
        out.setdefault(tokens[0], set()).add((len(tokens) - 1, payload))
    return out


def _expected_tokens(fn: ast.AST) -> set[str]:
    """Reply tokens a client method tests for: ``== "PONG"`` /
    ``!= "OK"`` comparisons and ``.startswith("OK ")`` prefixes."""
    out: set[str] = set()

    def first_token(text: str) -> str | None:
        parts = text.split()
        if parts and _TOKEN.fullmatch(parts[0]):
            return parts[0]
        return None

    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            for side in [node.left, *node.comparators]:
                if isinstance(side, ast.Constant) and isinstance(side.value, str):
                    token = first_token(side.value)
                    if token:
                        out.add(token)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "startswith"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            token = first_token(node.args[0].value)
            if token:
                out.add(token)
    return out


def _client_frames(fn: ast.AST) -> dict[str, set[int]]:
    """frame tag -> token arities a client method unpacks.  Anchored on
    the ``v = self._read_line().split(" ")`` idiom: ``v[0] != "TAG"``
    names the tag; ``len(v) != N`` and tuple-unpacks of ``v`` fix arity."""
    frame_vars: set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr == "split"
        ):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    frame_vars.add(target.id)
    if not frame_vars:
        return {}
    tags: set[str] = set()
    arities: set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            left, comparators = node.left, node.comparators
            # v[0] != "TAG"
            if (
                isinstance(left, ast.Subscript)
                and isinstance(left.value, ast.Name)
                and left.value.id in frame_vars
            ):
                for comp in comparators:
                    if isinstance(comp, ast.Constant) and isinstance(comp.value, str):
                        if _TOKEN.fullmatch(comp.value):
                            tags.add(comp.value)
            # len(v) != N
            if (
                isinstance(left, ast.Call)
                and dotted_name(left.func) == "len"
                and left.args
                and isinstance(left.args[0], ast.Name)
                and left.args[0].id in frame_vars
            ):
                for comp in comparators:
                    if isinstance(comp, ast.Constant) and isinstance(comp.value, int):
                        arities.add(comp.value)
        elif isinstance(node, ast.Assign):
            # _, mid, receipt, count, length = v
            if isinstance(node.value, ast.Name) and node.value.id in frame_vars:
                for target in node.targets:
                    if isinstance(target, (ast.Tuple, ast.List)):
                        arities.add(len(target.elts))
    return {tag: set(arities) for tag in tags}


def client_reply_contract(
    client_py: Path = CLIENT_PY,
) -> tuple[dict[str, set[str]], dict[str, dict[str, set[int]]]]:
    """(verb -> expected reply tokens, verb -> frame tag -> arities),
    unioned across the client methods that send each verb."""
    tree = _parse(client_py)
    verb_map = client_verb_map(client_py)
    tokens: dict[str, set[str]] = {}
    frames: dict[str, dict[str, set[int]]] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            verbs = verb_map.get(fn.name)
            if not verbs:
                continue
            fn_tokens = _expected_tokens(fn)
            fn_frames = _client_frames(fn)
            for verb in verbs:
                tokens.setdefault(verb, set()).update(fn_tokens)
                per_verb = frames.setdefault(verb, {})
                for tag, arities in fn_frames.items():
                    per_verb.setdefault(tag, set()).update(arities)
    # Frame tags double as expected tokens only for frame parsing; keep
    # them out of the scalar reply-token set (they are checked by DLC302).
    for verb, per_verb in frames.items():
        tokens.get(verb, set()).difference_update(per_verb)
    return tokens, frames


# --- layer 3: the C++ handler chain ----------------------------------------
_CPP_LITERAL = re.compile(r'"((?:[^"\\]|\\.)*)"')


def _cpp_segments(broker_cpp: Path = BROKER_CPP) -> dict[str, str]:
    """verb -> handler segment text (from its ``cmd == "VERB"`` test to
    the next handler's)."""
    text = broker_cpp.read_text(errors="replace")
    matches = list(_CPP_HANDLER.finditer(text))
    out: dict[str, str] = {}
    for i, m in enumerate(matches):
        verb = m.group(1) or m.group(2)
        end = matches[i + 1].start() if i + 1 < len(matches) else len(text)
        out[verb] = text[m.start():end]
    return out


def cpp_request_shapes(broker_cpp: Path = BROKER_CPP) -> dict[str, Shape]:
    """verb -> (number of ``>>`` extractions, reads a payload?)."""
    return {
        verb: (segment.count(">>"), "read_exact(" in segment)
        for verb, segment in _cpp_segments(broker_cpp).items()
    }


def cpp_reply_contract(
    broker_cpp: Path = BROKER_CPP,
) -> tuple[dict[str, set[str]], dict[str, dict[str, int]]]:
    """(verb -> emitted reply tokens, verb -> frame tag -> token arity).

    Reply tokens come from the first token of every string literal in the
    handler segment (``write_all(fd, "OK " ...)``, ``resp = "N " ...``);
    frames from ``resp += "TAG " ...`` concatenations, whose arity is the
    tag + trailing fields joined by standalone ``" "`` separators."""
    tokens: dict[str, set[str]] = {}
    frames: dict[str, dict[str, int]] = {}
    for verb, segment in _cpp_segments(broker_cpp).items():
        verb_tokens: set[str] = set()
        verb_frames: dict[str, int] = {}
        for m in _CPP_LITERAL.finditer(segment):
            literal = m.group(1)
            first = literal.split("\\n")[0].split()
            if first and _TOKEN.fullmatch(first[0]):
                verb_tokens.add(first[0])
        for stmt_m in re.finditer(r"resp\s*\+=\s*([^;]*);", segment):
            stmt = stmt_m.group(1)
            literals = _CPP_LITERAL.findall(stmt)
            if not literals:
                continue
            head = literals[0].split("\\n")[0]
            lead = head.split()
            if not lead or not _TOKEN.fullmatch(lead[0]):
                continue
            # Tokens: those inside the lead literal, plus the field its
            # trailing space opens, plus one per standalone " " separator
            # ("MSG " + id + " " + receipt + ... -> 2 + separators).
            arity = (
                len(lead)
                + (1 if head.endswith(" ") else 0)
                + sum(1 for lit in literals[1:] if lit == " ")
            )
            verb_frames[lead[0]] = arity
        tokens[verb] = verb_tokens
        frames[verb] = verb_frames
    return tokens, frames


# --- the wire-shape check --------------------------------------------------
def check_protocol(
    contract_py: Path = CONTRACT_PY,
    client_py: Path = CLIENT_PY,
    broker_cpp: Path = BROKER_CPP,
) -> list[Violation]:
    out: list[Violation] = []

    def v(rule: str, path: Path, msg: str) -> None:
        out.append(Violation(rule=rule, path=str(path), line=1, col=1, message=msg))

    canon = canonical_shapes(contract_py)
    canon_verbs, _ = canonical_verbs(contract_py)
    client = client_request_shapes(client_py)
    cpp = cpp_request_shapes(broker_cpp)

    # DLC300: request shapes.  Verb *presence* drift is DLC100's job;
    # shapes are only compared where the layers share the verb.
    for verb in sorted(canon_verbs):
        specs = canon.get(verb)
        if not specs:
            v(
                RULE_REQUEST,
                contract_py,
                f"verb {verb!r} has no request-shape spec comment on "
                "BROKER_PROTOCOL_VERBS (`# VERB <arg>... ` is the "
                "machine-read source of truth)",
            )
            continue
        for shape in sorted(client.get(verb, set())):
            if shape not in specs:
                nargs, payload = shape
                v(
                    RULE_REQUEST,
                    client_py,
                    f"client sends {verb} with {nargs} argument token(s)"
                    f"{' + payload' if payload else ''}, but the canonical "
                    f"spec allows {sorted(specs)} (args, payload?)",
                )
        if verb in cpp:
            cpp_nargs, cpp_payload = cpp[verb]
            spec_max = max(n for n, _ in specs)
            if cpp_nargs != spec_max:
                v(
                    RULE_REQUEST,
                    broker_cpp,
                    f"broker.cpp extracts {cpp_nargs} argument token(s) for "
                    f"{verb} but the canonical spec's widest shape has "
                    f"{spec_max}",
                )
            if cpp_payload != any(p for _, p in specs):
                v(
                    RULE_REQUEST,
                    broker_cpp,
                    f"broker.cpp {'reads' if cpp_payload else 'does not read'} "
                    f"a payload for {verb}, disagreeing with the canonical "
                    "spec",
                )

    # DLC301/DLC302: replies and frames.
    client_tokens, client_frames = client_reply_contract(client_py)
    cpp_tokens, cpp_frames = cpp_reply_contract(broker_cpp)
    for verb in sorted(set(client_tokens) & set(cpp_tokens)):
        for token in sorted(client_tokens[verb] - cpp_tokens[verb]):
            v(
                RULE_REPLY,
                client_py,
                f"client expects reply token {token!r} for {verb} but "
                f"broker.cpp's handler only emits "
                f"{sorted(cpp_tokens[verb]) or 'nothing'}",
            )
    for verb in sorted(set(client_frames) | set(cpp_frames)):
        want = client_frames.get(verb, {})
        have = cpp_frames.get(verb, {})
        for tag in sorted(set(want) | set(have)):
            if tag not in have:
                v(
                    RULE_FRAME,
                    broker_cpp,
                    f"client parses {tag!r} frames for {verb} but "
                    "broker.cpp's handler never emits them",
                )
            elif tag not in want:
                v(
                    RULE_FRAME,
                    client_py,
                    f"broker.cpp emits {tag!r} frames for {verb} but the "
                    "client never parses them",
                )
            elif want[tag] and have[tag] not in want[tag]:
                v(
                    RULE_FRAME,
                    client_py,
                    f"{tag!r} frame arity drift for {verb}: client unpacks "
                    f"{sorted(want[tag])} token(s), broker.cpp emits "
                    f"{have[tag]}",
                )
    return out


# --- DLC303: lifecycle kinds ------------------------------------------------
def _event_kind_members(events_py: Path = EVENTS_PY) -> set[str]:
    tree = _parse(events_py)
    for cls in ast.walk(tree):
        if isinstance(cls, ast.ClassDef) and cls.name == "EventKind":
            return {
                t.id
                for node in cls.body
                if isinstance(node, ast.Assign)
                for t in node.targets
                if isinstance(t, ast.Name)
            }
    return set()


def _package_files(scan_root: Path = PACKAGE_DIR) -> list[Path]:
    return sorted(
        f for f in scan_root.rglob("*.py") if "__pycache__" not in f.parts
    )


def check_lifecycle(
    events_py: Path = EVENTS_PY,
    elasticity_py: Path = ELASTICITY_PY,
    files: Iterable[Path] | None = None,
) -> list[Violation]:
    out: list[Violation] = []
    defined = _event_kind_members(events_py)
    if not defined:
        out.append(
            Violation(
                rule=RULE_LIFECYCLE,
                path=str(events_py),
                line=1,
                col=1,
                message="EventKind enum not found: the lifecycle vocabulary "
                "must live in provision/events.py",
            )
        )
        return out

    handled: set[str] = set()
    elasticity_tree = _parse(elasticity_py)
    for node in ast.walk(elasticity_tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "EventKind"
        ):
            handled.add(node.attr)

    produced_kinds: set[str] = set()
    consumed_kinds: dict[str, tuple[Path, int]] = {}
    published: dict[str, tuple[Path, int]] = {}
    for path in files if files is not None else _package_files():
        tree = _parse(path)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "EventKind"
            ):
                if node.attr not in defined:
                    out.append(
                        Violation(
                            rule=RULE_LIFECYCLE,
                            path=str(path),
                            line=node.lineno,
                            col=node.col_offset + 1,
                            message=f"EventKind.{node.attr} is not a defined "
                            "lifecycle kind (provision/events.py)",
                        )
                    )
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # LifecycleEvent(kind=EventKind.X, ...) publishers
            if dotted_name(func) == "LifecycleEvent":
                for kw in node.keywords:
                    if (
                        kw.arg == "kind"
                        and isinstance(kw.value, ast.Attribute)
                        and isinstance(kw.value.value, ast.Name)
                        and kw.value.value.id == "EventKind"
                    ):
                        published.setdefault(
                            kw.value.attr, (path, node.lineno)
                        )
            # journal producers: <anything>.record("kind", ...)
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "record"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                produced_kinds.add(node.args[0].value)
            # journal consumers: read_journal(..., kind="x")
            if dotted_name(func) in ("read_journal",) or (
                isinstance(func, ast.Attribute) and func.attr == "read_journal"
            ):
                for kw in node.keywords:
                    if (
                        kw.arg == "kind"
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                    ):
                        consumed_kinds.setdefault(
                            kw.value.value, (path, node.lineno)
                        )

    for kind in sorted(set(published) - handled - {"TEST_NOTIFICATION"}):
        path, line = published[kind]
        out.append(
            Violation(
                rule=RULE_LIFECYCLE,
                path=str(path),
                line=line,
                col=1,
                message=f"EventKind.{kind} is published on the bus but the "
                "elasticity controller never dispatches it — the event "
                "would be dropped on the floor (cluster/elasticity.py)",
            )
        )
    for kind in sorted(set(consumed_kinds) - produced_kinds):
        path, line = consumed_kinds[kind]
        out.append(
            Violation(
                rule=RULE_LIFECYCLE,
                path=str(path),
                line=line,
                col=1,
                message=f"journal kind {kind!r} is filtered by a reader but "
                "no record() call ever produces it",
            )
        )
    out.sort(key=lambda x: (x.path, x.line, x.col))
    return out
