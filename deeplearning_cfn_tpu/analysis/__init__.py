"""dlcfn-lint: repo-native static analysis.

The reference system's characteristic failures are GLUE failures —
drifted wire protocols, untimed network calls, silently-wrong numeric
output — and this rebuild has the same exposure surface.  This package
makes those bug classes mechanically checkable:

- :mod:`core` — the AST rule framework (registry, per-line
  ``# dlcfn: noqa[RULE]`` suppression, JSON/human output).
- :mod:`rules` — the DLC0xx per-file rules (untimed blocking calls,
  NaN-unsafe JSON, host syncs under jit, swallowed interrupts, substring
  param matching, daemonless threads, py2 remnants, missing donation).
- :mod:`contract_check` — the DLC1xx cross-language broker-contract
  checker: the canonical verb set (cluster/contract.py) against the
  Python client (broker_client.py), the supervisor (broker_service.py),
  and the C++ handler set (native/broker/broker.cpp).
- :mod:`concurrency` — the DLC2xx lockset/thread-escape rules
  (unlocked cross-thread attribute writes, bare ``acquire()``, blocking
  I/O under a lock, unstoppable daemon threads, wall-clock liveness
  deadlines).  Gated: runs only under ``--concurrency`` / ``--select``.
- :mod:`protocol` — the DLC3xx message-*shape* checkers: request arity
  and payload, reply tokens, multi-field frame arity across
  contract.py / broker_client.py / broker.cpp, plus lifecycle-kind
  consistency (EventKind publishers vs dispatchers, journal kinds).
  Gated behind ``--protocol`` / ``--select``.
- :mod:`schedules` — the deterministic interleaving harness: virtual
  clock + cooperative step scheduler driving the REAL heartbeat ->
  liveness -> terminate -> recovery choreography through permuted
  schedules (tests/test_interleaving.py).
- :mod:`runner` — file discovery, pass gating, suppression baseline
  (ratchet), orchestration behind ``python -m deeplearning_cfn_tpu.cli
  lint``.

Rule docs: docs/STATIC_ANALYSIS.md.
"""

from deeplearning_cfn_tpu.analysis.core import (  # noqa: F401
    FILE_RULES,
    FileContext,
    Rule,
    Violation,
    lint_source,
)
from deeplearning_cfn_tpu.analysis.runner import run_lint  # noqa: F401
