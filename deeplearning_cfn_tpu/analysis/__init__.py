"""dlcfn-lint: repo-native static analysis.

The reference system's characteristic failures are GLUE failures —
drifted wire protocols, untimed network calls, silently-wrong numeric
output — and this rebuild has the same exposure surface.  This package
makes those bug classes mechanically checkable:

- :mod:`core` — the AST rule framework (registry, per-line
  ``# dlcfn: noqa[RULE]`` suppression, JSON/human output).
- :mod:`rules` — the DLC0xx per-file rules (untimed blocking calls,
  NaN-unsafe JSON, host syncs under jit, swallowed interrupts, substring
  param matching, daemonless threads, py2 remnants, missing donation).
- :mod:`contract_check` — the DLC1xx cross-language broker-contract
  checker: the canonical verb set (cluster/contract.py) against the
  Python client (broker_client.py), the supervisor (broker_service.py),
  and the C++ handler set (native/broker/broker.cpp).
- :mod:`runner` — file discovery + orchestration behind
  ``python -m deeplearning_cfn_tpu.cli lint``.

Rule docs: docs/STATIC_ANALYSIS.md.
"""

from deeplearning_cfn_tpu.analysis.core import (  # noqa: F401
    FILE_RULES,
    FileContext,
    Rule,
    Violation,
    lint_source,
)
from deeplearning_cfn_tpu.analysis.runner import run_lint  # noqa: F401
