"""Deterministic interleaving harness for the heartbeat/liveness plane.

The DLC2xx rules claim the threaded choreography — Heartbeater beats,
BrokerLivenessWatcher polls, LivenessTable classifies, the bus publishes
INSTANCE_TERMINATE, recovery replaces — is safe.  This harness *confirms*
it dynamically: a virtual clock plus a cooperative step scheduler run the
REAL production objects (no forked logic, no real threads, no sleeps)
through permuted schedules, including the silent-death path, and check
ground truth at every transition:

* a worker is only classified DEAD when its virtual silence really
  exceeded ``dead_after_s`` (no false terminations under any ordering);
* a DEAD classification always publishes exactly one INSTANCE_TERMINATE
  until the worker is recovered;
* every schedule runs to completion (single-threaded cooperative steps
  cannot deadlock; a wedged invariant still fails loudly).

Everything is seeded and wall-clock free, so a failing schedule is
replayable byte-for-byte.  tests/test_interleaving.py drives >= 50
distinct interleavings of the heartbeat-death -> recovery path through
:class:`HeartbeatChoreography` via a pytest fixture.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Sequence

from deeplearning_cfn_tpu.obs.liveness import LivenessConfig, WorkerState


class VirtualClock:
    """Monotonic virtual time: only :meth:`advance` moves it.  Callable so
    it drops into every ``clock=`` seam (LivenessTable, the watcher)."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    __call__ = now

    def advance(self, dt_s: float) -> float:
        if dt_s < 0:
            raise ValueError(f"virtual time cannot go backwards: {dt_s}")
        self._now += dt_s
        return self._now


class SimBroker:
    """The C++ broker's heartbeat table on virtual time: record() is the
    HEARTBEAT <worker> verb, dump() the table-dump mode (worker ->
    (age_s, count)), exactly the shape ``BrokerLivenessWatcher``'s
    ``fetch`` seam consumes."""

    def __init__(self, clock: VirtualClock):
        self._clock = clock
        self._beats: dict[str, tuple[float, int]] = {}
        self._telem: dict[str, tuple[float, int, bytes]] = {}

    def record(self, worker: str) -> int:
        last, count = self._beats.get(worker, (0.0, 0))
        self._beats[worker] = (self._clock.now(), count + 1)
        return count + 1

    def record_telem(self, worker: str, payload: bytes) -> int:
        """The TELEM <worker> verb: last-write-wins snapshot + count."""
        _last, count, _old = self._telem.get(worker, (0.0, 0, b""))
        self._telem[worker] = (self._clock.now(), count + 1, payload)
        return count + 1

    def dump(self) -> dict[str, tuple[float, int]]:
        now = self._clock.now()
        return {
            worker: (now - last, count)
            for worker, (last, count) in self._beats.items()
        }

    def dump_telem(self) -> dict[str, tuple[float, int, bytes]]:
        """The TELEM dump mode: worker -> (age_s, count, snapshot)."""
        now = self._clock.now()
        return {
            worker: (now - last, count, payload)
            for worker, (last, count, payload) in self._telem.items()
        }

    def silence_s(self, worker: str) -> float | None:
        """Ground truth: virtual seconds since the worker's last beat."""
        if worker not in self._beats:
            return None
        return self._clock.now() - self._beats[worker][0]


class SimBrokerError(ConnectionError):
    """Injected connection failure (a broker restart mid-beat)."""


class SimBrokerConnection:
    """Duck-types the BrokerConnection surface Heartbeater uses
    (heartbeat + close).  ``fail_beats`` makes the next N beats raise, so
    schedules exercise the real reconnect path in Heartbeater.beat_step.
    ``fail_when`` is the partition predicate: while it returns True every
    beat raises (and so does every beat on a freshly redialed connection
    built with the same predicate), which models a network cut rather
    than a one-shot connection loss."""

    def __init__(
        self,
        broker: SimBroker,
        fail_beats: int = 0,
        fail_when: Callable[[], bool] | None = None,
    ):
        self._broker = broker
        self._fail_beats = fail_beats
        self._fail_when = fail_when
        self.closed = False

    def heartbeat(self, worker_id: str) -> int:
        if self.closed:
            raise SimBrokerError("connection is closed")
        if self._fail_when is not None and self._fail_when():
            raise SimBrokerError("network partition")
        if self._fail_beats > 0:
            self._fail_beats -= 1
            raise SimBrokerError("injected beat failure")
        return self._broker.record(worker_id)

    def telem(self, worker_id: str, snapshot: bytes) -> int:
        if self.closed:
            raise SimBrokerError("connection is closed")
        if self._fail_when is not None and self._fail_when():
            raise SimBrokerError("network partition")
        return self._broker.record_telem(worker_id, snapshot)

    def close(self) -> None:
        self.closed = True


@dataclass
class StepScheduler:
    """Cooperative scheduler: actors are named step functions; a schedule
    is an explicit sequence of actor names, executed synchronously in
    order.  No threads, no preemption — the *schedule* is the
    interleaving."""

    actors: dict[str, Callable[[], Any]] = field(default_factory=dict)
    trace: list[str] = field(default_factory=list)

    def add(self, name: str, step: Callable[[], Any]) -> None:
        if name in self.actors:
            raise ValueError(f"duplicate actor {name!r}")
        self.actors[name] = step

    def run(self, schedule: Iterable[str]) -> list[str]:
        for name in schedule:
            self.actors[name]()  # unknown actor -> KeyError, loudly
            self.trace.append(name)
        return self.trace


def interleavings(
    actions: Sequence[str],
    count: int,
    seed: int = 0,
) -> list[tuple[str, ...]]:
    """``count`` distinct seeded shuffles of ``actions``.  Deterministic:
    the same (actions, count, seed) always yields the same schedules, so
    a failure names its schedule reproducibly."""
    rng = random.Random(seed)
    seen: set[tuple[str, ...]] = set()
    out: list[tuple[str, ...]] = []
    attempts = 0
    limit = count * 1000
    while len(out) < count:
        attempts += 1
        if attempts > limit:
            raise RuntimeError(
                f"could not generate {count} distinct schedules from "
                f"{len(actions)} actions (got {len(out)})"
            )
        shuffled = list(actions)
        rng.shuffle(shuffled)
        candidate = tuple(shuffled)
        if candidate not in seen:
            seen.add(candidate)
            out.append(candidate)
    return out


class InvariantViolation(AssertionError):
    """A liveness classification contradicted virtual-clock ground truth."""


class HeartbeatChoreography:
    """The full heartbeat-death -> recovery loop wired from REAL parts over
    virtual time: real ``Heartbeater`` instances (driven cooperatively via
    ``beat_step()``, never started as threads) beat at a :class:`SimBroker`;
    a real ``BrokerLivenessWatcher`` polls it through the ``fetch`` seam
    into the real ``LivenessTable``; DEAD transitions publish
    INSTANCE_TERMINATE on a real ``EventBus``; the recover step replaces
    terminated workers with fresh heartbeaters, as RecoveryManager would.

    Step vocabulary (for :class:`StepScheduler` schedules):

    * ``beat:<worker>``  one heartbeat from that worker (no-op once killed)
    * ``tick``           advance the virtual clock by ``tick_s``
    * ``poll``           watcher fetch + sweep, with ground-truth checks
    * ``kill:<worker>``  the worker dies silently (stops beating)
    * ``cut:<worker>``   network partition: its beats fail until healed
    * ``heal:<worker>``  the partition heals; its beats land again
    * ``recover``        replace every terminated-but-unrecovered worker

    Every ``poll`` validates transitions against the broker's own virtual
    timeline, so no schedule can smuggle in a false DEAD or a missed one.
    """

    def __init__(
        self,
        workers: Sequence[str],
        config: LivenessConfig | None = None,
        tick_s: float = 5.0,
        fail_first_beats: int = 0,
    ):
        from deeplearning_cfn_tpu.cluster.broker_service import (
            BrokerLivenessWatcher,
        )
        from deeplearning_cfn_tpu.obs.heartbeat import Heartbeater
        from deeplearning_cfn_tpu.provision.events import EventBus, EventKind

        self.clock = VirtualClock()
        self.broker = SimBroker(self.clock)
        self.config = config or LivenessConfig()
        self.tick_s = tick_s
        self.bus = EventBus()
        self.terminated: list[tuple[Any, float | None]] = []
        self._verified = 0
        self._terminate_kind = EventKind.INSTANCE_TERMINATE
        self.bus.subscribe(self._on_event)
        self.watcher = BrokerLivenessWatcher(
            cluster_name="sim",
            group="workers",
            bus=self.bus,
            config=self.config,
            clock=self.clock,
            fetch=self.broker.dump,
        )
        # A one-shot failure budget: only the FIRST dial gets the failing
        # connection, so Heartbeater's drop-and-redial recovery actually
        # lands a beat afterwards (a per-connection budget would fail
        # every redial forever).
        self._fail_budget = max(0, fail_first_beats)
        # Workers currently on the wrong side of a network cut: their
        # beats (on live AND freshly redialed connections) raise until a
        # heal step removes them.
        self.partitioned: set[str] = set()
        self._mk_heartbeater = lambda worker: Heartbeater(
            host="sim",
            port=0,
            worker_id=worker,
            interval_s=tick_s,
            connection_factory=lambda w=worker: self._dial_sim(w),
        )
        self.heartbeaters = {w: self._mk_heartbeater(w) for w in workers}
        self.alive: set[str] = set(workers)
        self.recovered: dict[str, str] = {}  # dead worker -> replacement

    def _dial_sim(self, worker: str | None = None) -> SimBrokerConnection:
        fails, self._fail_budget = self._fail_budget, 0
        return SimBrokerConnection(
            self.broker,
            fail_beats=fails,
            fail_when=(
                (lambda: worker in self.partitioned)
                if worker is not None
                else None
            ),
        )

    # --- bus + truth checking -------------------------------------------
    def _on_event(self, event: Any) -> None:
        # Never raise here: EventBus isolates handler exceptions by
        # contract, which would swallow the invariant.  Capture the
        # ground-truth silence at publish time; poll verifies it.
        if event.kind is self._terminate_kind:
            self.terminated.append(
                (event, self.broker.silence_s(event.instance_id))
            )

    def _check_terminates(self) -> None:
        while self._verified < len(self.terminated):
            event, silence = self.terminated[self._verified]
            self._verified += 1
            if silence is None or silence < self.config.dead_after_s:
                raise InvariantViolation(
                    f"INSTANCE_TERMINATE for {event.instance_id} at "
                    f"virtual silence {silence}; dead_after_s="
                    f"{self.config.dead_after_s}"
                )

    def _check_transitions(self, transitions: Iterable[Any]) -> None:
        for worker, _old, new in transitions:
            silence = self.broker.silence_s(worker)
            if silence is None:
                continue
            if new is WorkerState.DEAD and silence < self.config.dead_after_s:
                raise InvariantViolation(
                    f"{worker} marked DEAD at silence {silence:.1f}s "
                    f"< dead_after {self.config.dead_after_s}s"
                )
            if new is WorkerState.SUSPECT and (
                silence < self.config.suspect_after_s
            ):
                raise InvariantViolation(
                    f"{worker} marked SUSPECT at silence {silence:.1f}s "
                    f"< suspect_after {self.config.suspect_after_s}s"
                )
            if new is WorkerState.ALIVE and silence >= self.config.dead_after_s:
                raise InvariantViolation(
                    f"{worker} marked ALIVE at silence {silence:.1f}s "
                    f">= dead_after {self.config.dead_after_s}s"
                )

    # --- the step vocabulary --------------------------------------------
    def step(self, action: str) -> None:
        name, _, arg = action.partition(":")
        if name == "beat":
            if arg in self.alive:
                self.heartbeaters[arg].beat_step()
        elif name == "tick":
            self.clock.advance(self.tick_s)
        elif name == "poll":
            self._check_transitions(self.watcher.poll())
            self._check_terminates()
        elif name == "kill":
            self.alive.discard(arg)
        elif name == "cut":
            # Network partition: the worker keeps trying to beat (stays in
            # alive) but every beat fails until healed.
            self.partitioned.add(arg)
        elif name == "heal":
            self.partitioned.discard(arg)
        elif name == "recover":
            for event, _silence in list(self.terminated):
                dead = event.instance_id
                if dead in self.recovered:
                    continue  # duplicate terminate: recovery is idempotent
                replacement = f"{dead}+1"
                self.recovered[dead] = replacement
                self.heartbeaters[replacement] = self._mk_heartbeater(
                    replacement
                )
                self.alive.add(replacement)
                self.heartbeaters[replacement].beat_step()
        else:
            raise ValueError(f"unknown step {action!r}")

    def run(self, schedule: Iterable[str]) -> "HeartbeatChoreography":
        scheduler = StepScheduler()
        executed = list(schedule)
        for action in dict.fromkeys(executed):
            scheduler.add(action, lambda a=action: self.step(a))
        scheduler.run(executed)
        if len(scheduler.trace) != len(executed):
            raise InvariantViolation("schedule did not run to completion")
        return self

    # --- end-state assertions -------------------------------------------
    def states(self) -> dict[str, str]:
        return {
            worker: info["state"]
            for worker, info in self.watcher.snapshot().items()
        }

    def terminated_workers(self) -> list[str]:
        return [event.instance_id for event, _silence in self.terminated]


# --- replicated control plane on virtual time --------------------------------


class SimNotPrimary(SimBrokerError):
    """Write rejected by a standby or deposed node ("ERR not primary")."""


class SimFenced(SimBrokerError):
    """Replication entry rejected by epoch fencing ("ERR fenced")."""


class SimBrokerNode(SimBroker):
    """One virtual broker process: :class:`SimBroker`'s heartbeat table
    plus the replicated queue/KV state, a role, an epoch, and — while
    primary — a journal of applied frames (the sim twin of the C++
    broker's ``DLCFN_BROKER_REPL_LOG`` stream).  Mutations mirror the
    wire contract: they raise :class:`SimNotPrimary` on a non-primary
    and plain :class:`SimBrokerError` once the process is killed; reads
    stay open on a live standby.

    One deliberate divergence from the binary: replayed HEARTBEAT frames
    carry the ORIGINAL beat timestamp instead of being restamped at
    apply time.  The real pair restamps because two hosts' clocks are
    not comparable; the sim shares one virtual clock, so carrying the
    send instant keeps silence ground truth exact across a failover.
    """

    def __init__(
        self,
        clock: VirtualClock,
        name: str = "broker-a",
        role: str = "primary",
        epoch: int = 0,
    ):
        super().__init__(clock)
        self.name = name
        self.role = role
        self.epoch = epoch
        self.up = True
        self.journal: list[dict] = []  # [{"seq","epoch","ts","frame"}]
        self.seq = 0  # last seq journaled as primary
        self.sync_seq = 0  # last seq applied as standby
        self.fenced = 0  # stale-epoch SYNC rejections
        self.queues: dict[str, list[tuple[str, bytes]]] = {}
        self.applied: dict[str, set[str]] = {}  # queue -> idempotency keys
        self.kv: dict[str, bytes] = {}

    # -- role / liveness gates -------------------------------------------
    def _gate_write(self) -> None:
        if not self.up:
            raise SimBrokerError("closed connection")
        if self.role != "primary":
            raise SimNotPrimary("not primary")

    def _journal_frame(self, frame: dict) -> None:
        self.seq += 1
        self.journal.append(
            {
                "seq": self.seq,
                "epoch": self.epoch,
                "ts": self._clock.now(),
                "frame": frame,
            }
        )

    # -- client verbs (mutating: primary only) ---------------------------
    def record(self, worker: str) -> int:
        self._gate_write()
        count = super().record(worker)
        self._journal_frame(
            {
                "verb": "HEARTBEAT",
                "worker": worker,
                "ts": self._beats[worker][0],
                "count": count,
            }
        )
        return count

    def record_telem(self, worker: str, payload: bytes) -> int:
        self._gate_write()
        count = super().record_telem(worker, payload)
        self._journal_frame(
            {
                "verb": "TELEM",
                "worker": worker,
                "ts": self._telem[worker][0],
                "count": count,
                "payload": payload,
            }
        )
        return count

    def send_idempotent(self, queue: str, body: bytes, rid: str) -> str:
        self._gate_write()
        if self._apply_send(queue, body, rid):
            # Journaled only when actually applied — a deduped re-send
            # must not inflate the replication stream (matches the
            # binary's applied-gated repl_append).
            self._journal_frame(
                {"verb": "SENDID", "queue": queue, "rid": rid, "body": body}
            )
        return rid

    def set(self, key: str, value: bytes) -> None:
        self._gate_write()
        self.kv[key] = value
        self._journal_frame({"verb": "SET", "key": key, "value": value})

    # -- reads (open on any live node) -----------------------------------
    def dump(self) -> dict[str, tuple[float, int]]:
        if not self.up:
            raise SimBrokerError("closed connection")
        return super().dump()

    def dump_telem(self) -> dict[str, tuple[float, int, bytes]]:
        if not self.up:
            raise SimBrokerError("closed connection")
        return super().dump_telem()

    def depth(self, queue: str) -> int:
        if not self.up:
            raise SimBrokerError("closed connection")
        return len(self.queues.get(queue, ()))

    # -- replication (standby side) --------------------------------------
    def _apply_send(self, queue: str, body: bytes, rid: str) -> bool:
        seen = self.applied.setdefault(queue, set())
        if rid in seen:
            return False
        seen.add(rid)
        self.queues.setdefault(queue, []).append((rid, body))
        return True

    def _apply_frame(self, frame: dict) -> None:
        verb = frame["verb"]
        if verb == "SENDID":
            self._apply_send(frame["queue"], frame["body"], frame["rid"])
        elif verb == "SET":
            self.kv[frame["key"]] = frame["value"]
        elif verb == "HEARTBEAT":
            self._beats[frame["worker"]] = (frame["ts"], frame["count"])
        elif verb == "TELEM":
            self._telem[frame["worker"]] = (
                frame["ts"],
                frame["count"],
                frame["payload"],
            )
        else:
            raise ValueError(f"unknown replication verb {verb!r}")

    def sync(self, epoch: int, seq: int, frame: dict) -> int:
        """Apply one replicated journal entry (the SYNC verb).  Epoch
        fencing first: a stale term is rejected and counted; a HIGHER
        term demotes this node if it thought itself primary (the deposed
        half of a split brain learns it lost).  Then seq dedup: entries
        at-or-below the applied watermark are skipped, so at-least-once
        shipping never double-applies."""
        if not self.up:
            raise SimBrokerError("closed connection")
        if epoch < self.epoch or (epoch == self.epoch and self.role == "primary"):
            self.fenced += 1
            raise SimFenced(
                f"fenced: epoch {epoch} is stale at {self.name} "
                f"(epoch {self.epoch}, role {self.role})"
            )
        if epoch > self.epoch:
            self.epoch = epoch
            self.role = "standby"
        if seq > self.sync_seq:
            self._apply_frame(frame)
            self.sync_seq = seq
            # Journal the replicated entry at its INCOMING seq/epoch: the
            # standby keeps a complete copy of the history it applied, so
            # after ITS promotion it can re-provision a fresh standby and
            # resume replication from its own journal (the self-healing
            # half of the pair).  Seq-faithful, so a re-ship of the same
            # history dedups exactly like the original stream.
            self.journal.append(
                {
                    "seq": seq,
                    "epoch": epoch,
                    "ts": self._clock.now(),
                    "frame": frame,
                }
            )
        return seq

    def promote(self, epoch: int) -> int:
        """Fence to a strictly-higher epoch and take over as primary;
        the journal seq resumes from the replication watermark so the
        new term's entries extend (never collide with) the applied
        history."""
        if not self.up:
            raise SimBrokerError("closed connection")
        if epoch <= self.epoch:
            raise SimBrokerError(
                f"stale epoch {epoch} (current {self.epoch})"
            )
        self.epoch = epoch
        self.role = "primary"
        self.seq = max(self.seq, self.sync_seq)
        return epoch


class ReplicatedSimBroker:
    """A primary + warm-standby broker pair on virtual time.

    ``stream()`` plays :class:`ReplicationStreamer`: it ships journal
    entries the standby has not applied (``max_entries`` models a
    streamer that had not caught up when the primary died — the
    unshipped tail is what a warm standby genuinely loses).
    ``kill_primary()`` is the process dying; ``promote_standby()`` is
    the ``_adopt_standby`` ladder (fence to ``max(epochs) + 1``).  For
    split-brain schedules the primary is NOT killed: it keeps accepting
    writes on its side of the partition, and its post-promotion
    ``stream()`` attempts must all raise :class:`SimFenced` at the new
    primary, with ``demote()`` modelling the deposed node standing down
    once fenced."""

    def __init__(
        self,
        clock: VirtualClock,
        primary_name: str = "broker-a",
        standby_name: str = "broker-b",
    ):
        self.clock = clock
        self.primary = SimBrokerNode(clock, primary_name, role="primary")
        self.standby = SimBrokerNode(clock, standby_name, role="standby")
        self.reprovisions = 0  # fresh standbys spawned by auto-heal

    def nodes(self) -> list[SimBrokerNode]:
        return [self.primary, self.standby]

    def active(self) -> SimBrokerNode | None:
        """The live node currently claiming primary, if any."""
        for node in self.nodes():
            if node.up and node.role == "primary":
                return node
        return None

    def active_dump(self) -> dict[str, tuple[float, int]]:
        """The heartbeat table a liveness watcher would fetch: from the
        live primary, or empty while no node serves (broker outage)."""
        node = self.active()
        return node.dump() if node is not None else {}

    def active_dump_telem(self) -> dict[str, tuple[float, int, bytes]]:
        """The telemetry table a fleet aggregator would fetch: from the
        live primary, or empty while no node serves (broker outage)."""
        node = self.active()
        return node.dump_telem() if node is not None else {}

    def pending(self, src: SimBrokerNode | None = None) -> list[dict]:
        """Journal entries the standby has not applied, oldest first."""
        src = src or self.primary
        return [e for e in src.journal if e["seq"] > self.standby.sync_seq]

    def stream(
        self,
        src: SimBrokerNode | None = None,
        dst: SimBrokerNode | None = None,
        max_entries: int | None = None,
    ) -> int:
        """Ship unapplied journal entries ``src`` -> ``dst``; returns the
        count.  Raises :class:`SimFenced` the moment the receiver fences
        the stream (a deposed primary learns about its deposition here)."""
        src = src or self.primary
        dst = dst or self.standby
        if not src.up:
            raise SimBrokerError(f"{src.name} is down")
        todo = [e for e in src.journal if e["seq"] > dst.sync_seq]
        if max_entries is not None:
            todo = todo[:max_entries]
        for entry in todo:
            # Ship under the SENDER's current term (never below the
            # entry's own): a promoted primary re-replays old-term
            # history to a fresh standby under its new epoch, while a
            # deposed primary's stream still carries its stale epoch and
            # fences.  SYNC's epoch names the stream's term, not the
            # entry's origin.
            dst.sync(
                max(int(entry["epoch"]), src.epoch),
                entry["seq"],
                entry["frame"],
            )
        return len(todo)

    def kill_primary(self) -> None:
        self.primary.up = False

    def promote_standby(self) -> int:
        epoch = max(self.primary.epoch, self.standby.epoch) + 1
        return self.standby.promote(epoch)

    def reprovision_standby(self, name: str | None = None) -> SimBrokerNode:
        """Auto-heal after a failover: the acting primary spawns a FRESH
        standby at its own epoch and replays its full journal into it —
        the sim twin of ``_adopt_standby``'s re-provision step.  The
        deposed node is never reused; ``primary``/``standby`` are
        re-pointed so the pair is whole again (``pending()`` == 0 once
        the replay completes, which this method runs to the end)."""
        acting = self.active()
        if acting is None:
            raise SimBrokerError("no live primary to re-provision from")
        fresh = SimBrokerNode(
            self.clock,
            name or f"{acting.name}+standby{self.reprovisions}",
            role="standby",
            epoch=acting.epoch,
        )
        self.primary = acting
        self.standby = fresh
        self.reprovisions += 1
        self.stream()  # resume replication from the promoted journal
        return fresh

    def demote(self, node: SimBrokerNode) -> None:
        """A fenced ex-primary stands down (what the real deposed broker
        does on seeing a higher-epoch SYNC or BrokerFenced)."""
        node.role = "standby"
        node.epoch = max(n.epoch for n in self.nodes())


class FailoverSimConnection:
    """Duck-types the BrokerConnection surface agents use (heartbeat,
    send_idempotent, close) with ``FailoverBrokerConnection``'s
    walk-the-endpoint-list behavior: a dead node or a standby's
    "not primary" rejection advances to the next endpoint; success on a
    later endpoint IS the failover.  ``fail_when`` cuts this client off
    from every endpoint (its side of a partition)."""

    def __init__(
        self,
        nodes: Sequence[SimBrokerNode] | None = None,
        fail_when: Callable[[], bool] | None = None,
        nodes_source: Callable[[], Sequence[SimBrokerNode]] | None = None,
    ):
        if nodes is None and nodes_source is None:
            raise ValueError("need nodes or nodes_source")
        self._nodes = list(nodes) if nodes is not None else []
        self._nodes_source = nodes_source
        self._fail_when = fail_when
        self.closed = False
        self.failovers = 0

    def _call(self, op: Callable[[SimBrokerNode], Any]) -> Any:
        if self.closed:
            raise SimBrokerError("connection is closed")
        if self._fail_when is not None and self._fail_when():
            raise SimBrokerError("network partition")
        if self._nodes_source is not None:
            # Re-read the endpoint list each call — the sim twin of
            # FailoverBrokerConnection's endpoints_source refresh: a
            # client started before a failover finds the fresh
            # auto-re-provisioned standby without a restart.
            self._nodes = list(self._nodes_source())
        last: Exception | None = None
        for i, node in enumerate(self._nodes):
            try:
                result = op(node)
            except SimBrokerError as exc:
                last = exc
                continue
            if i > 0:
                self.failovers += 1
            return result
        raise SimBrokerError(f"no broker endpoint available: {last}")

    def heartbeat(self, worker_id: str) -> int:
        return self._call(lambda node: node.record(worker_id))

    def telem(self, worker_id: str, snapshot: bytes) -> int:
        return self._call(lambda node: node.record_telem(worker_id, snapshot))

    def send_idempotent(self, queue: str, body: bytes, rid: str) -> str:
        return self._call(lambda node: node.send_idempotent(queue, body, rid))

    def close(self) -> None:
        self.closed = True


def soak_failover(
    agents: int = 1000,
    seed: int = 0,
    kill_count: int = 50,
    senders: int = 100,
    unshipped_tail: int = 37,
    tick_s: float = 5.0,
    config: LivenessConfig | None = None,
) -> dict:
    """1,000-agent (by default) broker-failover soak on virtual time.

    Real ``Heartbeater`` instances beat through failover connections at a
    :class:`ReplicatedSimBroker`; a real ``BrokerLivenessWatcher``
    classifies silence from whichever node is primary.  A seeded subset
    of agents dies silently; then the PRIMARY dies mid-round with
    ``unshipped_tail`` journal entries never shipped; the standby is
    promoted; traffic resumes through the failover path.  Meanwhile
    ``senders`` agents each submit one idempotent request before the
    kill and blindly RE-SEND the same request id after promotion (the
    client cannot know whether its frame was replicated), so exactly-once
    effects must come from idempotency keys honored by replay.

    Returns structural facts only — no wall-clock, no paths — so chaos
    reports and perf-smoke stages built on it are byte-deterministic per
    seed:  ``lost_terminates`` / ``spurious_terminates`` /
    ``duplicate_terminates`` / ``premature_terminates`` must all be 0,
    ``duplicate_sends`` must be 0 with ``work_depth == senders``, and
    ``fenced_writes`` stays 0 (no split brain in this scenario).
    """
    from deeplearning_cfn_tpu.cluster.broker_service import (
        BrokerLivenessWatcher,
    )
    from deeplearning_cfn_tpu.obs.heartbeat import Heartbeater
    from deeplearning_cfn_tpu.provision.events import EventBus, EventKind

    rng = random.Random(seed)
    clock = VirtualClock()
    cluster = ReplicatedSimBroker(clock)
    cfg = config or LivenessConfig()
    bus = EventBus()
    terminated: list[tuple[str, float | None]] = []

    def on_event(event: Any) -> None:
        if event.kind is EventKind.INSTANCE_TERMINATE:
            node = cluster.active() or cluster.standby
            terminated.append(
                (event.instance_id, node.silence_s(event.instance_id))
            )

    bus.subscribe(on_event)
    watcher = BrokerLivenessWatcher(
        cluster_name="sim-failover",
        group="agents",
        bus=bus,
        config=cfg,
        clock=clock,
        fetch=cluster.active_dump,
    )

    names = [f"agent-{i:04d}" for i in range(agents)]
    killed = set(rng.sample(names, kill_count))
    live = [w for w in names if w not in killed]
    sender_names = rng.sample(live, senders)
    beaters = {
        w: Heartbeater(
            host="sim",
            port=0,
            worker_id=w,
            interval_s=tick_s,
            connection_factory=lambda: FailoverSimConnection(cluster.nodes()),
        )
        for w in names
    }
    alive = set(names)

    def round_(stream: bool = True) -> None:
        for w in names:
            if w in alive:
                beaters[w].beat_step()
        if stream and cluster.active() is cluster.primary:
            cluster.stream()
        clock.advance(tick_s)
        watcher.poll()

    # Warmup: everyone beating, replication caught up.
    for _ in range(3):
        round_()
    # A seeded subset dies silently, mid-traffic.
    alive -= killed
    for _ in range(2):
        round_()

    # The kill round: beats + idempotent submissions land on the primary,
    # which then dies with the journal tail unshipped.
    for w in names:
        if w in alive:
            beaters[w].beat_step()
    rids = {w: f"{w}/job-{seed}" for w in sender_names}
    for w in sender_names:
        cluster.primary.send_idempotent(
            "work", f"payload-{w}".encode(), rids[w]
        )
    backlog = len(cluster.pending())
    cluster.stream(max_entries=max(0, backlog - unshipped_tail))
    lag_at_kill = len(cluster.pending())
    cluster.kill_primary()
    clock.advance(tick_s)
    watcher.poll()  # broker outage: fetch is empty, nobody terminates early

    # Promotion ladder: standby fenced to a strictly-higher epoch.
    epoch = cluster.promote_standby()

    # At-least-once across the switch: every sender blindly re-sends its
    # request id through the failover path; replayed rids dedup, the
    # unshipped tail lands exactly once.
    resend = FailoverSimConnection(cluster.nodes())
    for w in sender_names:
        resend.send_idempotent("work", f"payload-{w}".encode(), rids[w])
    resend.close()

    # Drain: silence of the killed agents crosses dead_after_s on the NEW
    # primary's replicated heartbeat table.
    drain_rounds = int(cfg.dead_after_s // tick_s) + 3
    for _ in range(drain_rounds):
        round_(stream=False)

    new_primary = cluster.standby
    work = new_primary.queues.get("work", [])
    rid_list = [rid for rid, _body in work]
    term_names = [w for w, _s in terminated]
    return {
        "agents": agents,
        "killed": len(killed),
        "terminated": len(term_names),
        "lost_terminates": len(killed - set(term_names)),
        "spurious_terminates": len(set(term_names) - killed),
        "duplicate_terminates": len(term_names) - len(set(term_names)),
        "premature_terminates": sum(
            1
            for _w, s in terminated
            if s is None or s < cfg.dead_after_s
        ),
        "senders": senders,
        "work_depth": len(work),
        "duplicate_sends": len(rid_list) - len(set(rid_list)),
        "unshipped_at_kill": lag_at_kill,
        "replayed_seq": new_primary.sync_seq,
        "journaled_seq": cluster.primary.seq,
        "epoch": epoch,
        "fenced_writes": cluster.primary.fenced + cluster.standby.fenced,
        "client_failovers": resend.failovers,
        "rounds": 6 + drain_rounds,
    }


def _shard_for_key(key: str, n_shards: int) -> int:
    """The production hash ring — ONE routing function shared by the
    real client and the sim, so a schedule proven here routes identically
    against the sharded binary fleet."""
    from deeplearning_cfn_tpu.cluster.broker_client import shard_for_key

    return shard_for_key(key, n_shards)


class ShardedSimBroker:
    """N independent :class:`ReplicatedSimBroker` pairs behind the
    production consistent-hash ring (``broker_client.shard_for_key``).

    Queues/keys/workers route to ``shard_for_key(key, n_shards)``; each
    shard fails over, fences, and auto-re-provisions independently, so a
    single shard's outage stalls only the keys that hash there — the sim
    twin of ``ensure_sharded_broker``'s per-shard pairs."""

    def __init__(self, clock: VirtualClock, n_shards: int = 4):
        if n_shards < 1:
            raise ValueError(f"need at least one shard, got {n_shards}")
        self.clock = clock
        self.n_shards = n_shards
        self.shards = [
            ReplicatedSimBroker(
                clock,
                primary_name=f"shard{k}-a",
                standby_name=f"shard{k}-b",
            )
            for k in range(n_shards)
        ]

    def shard_index(self, key: str) -> int:
        return _shard_for_key(key, self.n_shards)

    def route(self, key: str) -> ReplicatedSimBroker:
        return self.shards[self.shard_index(key)]

    def active_dump(self) -> dict[str, tuple[float, int]]:
        """The merged heartbeat table a liveness watcher fetches: every
        shard's live primary contributes its slice; a shard mid-failover
        contributes nothing (only ITS workers go briefly unobserved)."""
        merged: dict[str, tuple[float, int]] = {}
        for shard in self.shards:
            merged.update(shard.active_dump())
        return merged

    def stream_all(self) -> int:
        """One replication pass over every shard whose recorded primary
        is the acting one (a shard mid-failover is skipped, exactly as
        ``ReplicationStreamer`` has no live source there)."""
        shipped = 0
        for shard in self.shards:
            if shard.active() is shard.primary:
                shipped += shard.stream()
        return shipped

    def healed_pairs(self) -> int:
        """Shards whose pair is whole and caught up: a live primary, a
        live replicating standby, zero replication lag."""
        healed = 0
        for shard in self.shards:
            acting = shard.active()
            if (
                acting is not None
                and acting is shard.primary
                and shard.standby.up
                and shard.standby.role == "standby"
                and not shard.pending()
            ):
                healed += 1
        return healed


class ShardedSimConnection:
    """Duck-types the agent-facing connection surface over a
    :class:`ShardedSimBroker`: each op hashes its key to a shard and
    walks THAT shard's endpoints through a per-shard
    :class:`FailoverSimConnection` (``nodes_source`` re-reads the pair,
    so an auto-re-provisioned standby is visible without a redial)."""

    def __init__(self, cluster: ShardedSimBroker):
        self._cluster = cluster
        self._conns = [
            FailoverSimConnection(nodes_source=shard.nodes)
            for shard in cluster.shards
        ]
        self.closed = False

    @property
    def failovers(self) -> int:
        return sum(conn.failovers for conn in self._conns)

    def _conn_for(self, key: str) -> FailoverSimConnection:
        if self.closed:
            raise SimBrokerError("connection is closed")
        return self._conns[self._cluster.shard_index(key)]

    def heartbeat(self, worker_id: str) -> int:
        return self._conn_for(worker_id).heartbeat(worker_id)

    def telem(self, worker_id: str, snapshot: bytes) -> int:
        return self._conn_for(worker_id).telem(worker_id, snapshot)

    def send_idempotent(self, queue: str, body: bytes, rid: str) -> str:
        return self._conn_for(queue).send_idempotent(queue, body, rid)

    def close(self) -> None:
        self.closed = True
        for conn in self._conns:
            conn.close()


def soak_fleet(
    agents: int = 10000,
    shards: int = 8,
    seed: int = 0,
    kill_count: int = 200,
    senders: int = 400,
    failover_shards: int = 3,
    unshipped_tail: int = 11,
    stale_writes: int = 5,
    tick_s: float = 5.0,
    config: LivenessConfig | None = None,
) -> dict:
    """10,000-agent (by default) multi-shard fleet soak on virtual time.

    The fleet-scale schedule the sharded control plane must survive, all
    in one seeded run: real ``Heartbeater`` instances beat through
    shard-routed failover connections; a real ``BrokerLivenessWatcher``
    classifies silence from the MERGED per-shard heartbeat tables; a
    seeded subset of agents dies silently.  Then, concurrently:
    ``failover_shards`` primaries die mid-traffic with unshipped journal
    tails (promotion + AUTO-RE-PROVISION of a fresh standby, half the
    shards healing before the client re-send storm and half after — the
    re-provision race); one healthy shard suffers a partition cut (its
    standby is promoted while the deposed primary keeps accepting
    writes, whose replication attempt must fence WITHOUT advancing the
    new primary — reject, never diverge); every sender blindly re-sends
    its request id through the shard router.  Traffic then drains until
    every silent death is detected on the replicated tables.

    Returns structural facts only (no wall-clock, no paths), so reports
    are byte-deterministic per seed: the terminate counters and
    ``duplicate_sends`` / ``diverged_entries`` must be 0 with
    ``delivered == senders + stale_writes``, ``degraded_pairs`` must be
    0 (no post-failover steady state missing a standby), and
    ``unaffected_shard_failovers`` must be 0 (a one-shard outage stalls
    only that shard's clients).
    """
    from deeplearning_cfn_tpu.cluster.broker_service import (
        BrokerLivenessWatcher,
    )
    from deeplearning_cfn_tpu.obs.heartbeat import Heartbeater
    from deeplearning_cfn_tpu.provision.events import EventBus, EventKind

    rng = random.Random(seed)
    clock = VirtualClock()
    cluster = ShardedSimBroker(clock, n_shards=shards)
    cfg = config or LivenessConfig()
    bus = EventBus()
    terminated: list[tuple[str, float | None]] = []

    def on_event(event: Any) -> None:
        if event.kind is EventKind.INSTANCE_TERMINATE:
            shard = cluster.route(event.instance_id)
            node = shard.active() or shard.standby
            terminated.append(
                (event.instance_id, node.silence_s(event.instance_id))
            )

    bus.subscribe(on_event)
    watcher = BrokerLivenessWatcher(
        cluster_name="sim-fleet",
        group="agents",
        bus=bus,
        config=cfg,
        clock=clock,
        fetch=cluster.active_dump,
    )

    names = [f"agent-{i:05d}" for i in range(agents)]
    killed = set(rng.sample(names, kill_count))
    live = [w for w in names if w not in killed]
    sender_names = rng.sample(live, senders)
    # One failover connection per agent, pinned to ITS shard; tagged by
    # shard so the blast radius of each outage is attributable.
    agent_conns: list[tuple[int, FailoverSimConnection]] = []

    def make_conn(worker: str) -> FailoverSimConnection:
        k = cluster.shard_index(worker)
        conn = FailoverSimConnection(nodes_source=cluster.shards[k].nodes)
        agent_conns.append((k, conn))
        return conn

    beaters = {
        w: Heartbeater(
            host="sim",
            port=0,
            worker_id=w,
            interval_s=tick_s,
            connection_factory=lambda w=w: make_conn(w),
        )
        for w in names
    }
    alive = set(names)

    def round_() -> None:
        for w in names:
            if w in alive:
                beaters[w].beat_step()
        cluster.stream_all()
        clock.advance(tick_s)
        watcher.poll()

    # Warmup: everyone beating on every shard, replication caught up.
    for _ in range(3):
        round_()
    # A seeded subset dies silently, mid-traffic.
    alive -= killed
    for _ in range(2):
        round_()

    # The kill round: beats + shard-routed idempotent submissions land,
    # then a seeded subset of shard PRIMARIES dies with their journal
    # tails unshipped.
    for w in names:
        if w in alive:
            beaters[w].beat_step()
    queues = {w: f"work/{w}" for w in sender_names}
    rids = {w: f"{w}/job-{seed}" for w in sender_names}
    for w in sender_names:
        cluster.route(queues[w]).primary.send_idempotent(
            queues[w], f"payload-{w}".encode(), rids[w]
        )
    fail_shards = sorted(rng.sample(range(shards), failover_shards))
    unshipped_total = 0
    for k in range(shards):
        shard = cluster.shards[k]
        if k in fail_shards:
            backlog = len(shard.pending())
            shard.stream(max_entries=max(0, backlog - unshipped_tail))
            unshipped_total += len(shard.pending())
            shard.kill_primary()
        else:
            shard.stream()
    clock.advance(tick_s)
    watcher.poll()  # dead shards fetch empty: nobody terminates early

    # Promotion + auto-heal wave.  Even-indexed shards re-provision their
    # fresh standby BEFORE the client re-send storm, odd-indexed after —
    # both orders of the re-provision race run every seed.
    epochs: dict[str, int] = {}
    for idx, k in enumerate(fail_shards):
        shard = cluster.shards[k]
        epochs[str(k)] = shard.promote_standby()
        if idx % 2 == 0:
            shard.reprovision_standby()

    # Partition cut on the lowest HEALTHY shard: its standby is promoted
    # while the deposed primary is still up and accepting writes on its
    # side of the cut.
    split_shard = min(k for k in range(shards) if k not in fail_shards)
    sp = cluster.shards[split_shard]
    epochs[str(split_shard)] = sp.promote_standby()
    split_queue = next(
        q
        for q in (f"split/{i}" for i in range(10 * shards))
        if cluster.shard_index(q) == split_shard
    )
    stale_rids = [f"stale/{j}/job-{seed}" for j in range(stale_writes)]
    for rid in stale_rids:
        sp.primary.send_idempotent(split_queue, rid.encode(), rid)
    # The deposed primary's replication attempt must be REJECTED without
    # the new primary applying a single entry: fence, never diverge.
    seq_before = sp.standby.sync_seq
    fenced_streams = 0
    try:
        sp.stream(src=sp.primary, dst=sp.standby)
    except SimFenced:
        fenced_streams += 1
    diverged_entries = (sp.standby.sync_seq - seq_before) + sum(
        1
        for rid in stale_rids
        if rid in sp.standby.applied.get(split_queue, set())
    )
    # Heal the cut: the fenced ex-primary stands down and dies; the
    # acting primary auto-re-provisions a fresh standby from its journal.
    sp.demote(sp.primary)
    sp.primary.up = False
    sp.reprovision_standby()

    # At-least-once across every switch: senders blindly re-send their
    # request ids through the shard router, and the partition-era writes
    # (lost with the deposed primary) are re-driven the same way.
    resend = ShardedSimConnection(cluster)
    for w in sender_names:
        resend.send_idempotent(queues[w], f"payload-{w}".encode(), rids[w])
    for rid in stale_rids:
        resend.send_idempotent(split_queue, rid.encode(), rid)
    resend.close()
    for idx, k in enumerate(fail_shards):
        if idx % 2 == 1:
            cluster.shards[k].reprovision_standby()

    # Drain: silence of the killed agents crosses dead_after_s on the
    # replicated per-shard tables; continuous streaming keeps every
    # fresh standby caught up.
    drain_rounds = int(cfg.dead_after_s // tick_s) + 3
    for _ in range(drain_rounds):
        round_()

    delivered = 0
    rid_dupes = 0
    for shard in cluster.shards:
        acting = shard.active()
        if acting is None:
            continue
        for entries in acting.queues.values():
            rid_list = [rid for rid, _body in entries]
            delivered += len(rid_list)
            rid_dupes += len(rid_list) - len(set(rid_list))
    affected = set(fail_shards) | {split_shard}
    term_names = [w for w, _s in terminated]
    return {
        "agents": agents,
        "shards": shards,
        "killed": len(killed),
        "terminated": len(term_names),
        "lost_terminates": len(killed - set(term_names)),
        "spurious_terminates": len(set(term_names) - killed),
        "duplicate_terminates": len(term_names) - len(set(term_names)),
        "premature_terminates": sum(
            1 for _w, s in terminated if s is None or s < cfg.dead_after_s
        ),
        "senders": senders,
        "sender_shards": len(
            {cluster.shard_index(q) for q in queues.values()}
        ),
        "delivered": delivered,
        "duplicate_sends": rid_dupes,
        "failover_shards": [str(k) for k in fail_shards],
        "split_shard": split_shard,
        "epochs": epochs,
        "unshipped_at_kill": unshipped_total,
        "stale_writes": stale_writes,
        "fenced_writes": sum(
            n.fenced for sh in cluster.shards for n in sh.nodes()
        ),
        "fenced_streams": fenced_streams,
        "diverged_entries": diverged_entries,
        "reprovisions": sum(sh.reprovisions for sh in cluster.shards),
        "healed_pairs": cluster.healed_pairs(),
        "degraded_pairs": shards - cluster.healed_pairs(),
        "client_failovers": sum(c.failovers for _k, c in agent_conns)
        + resend.failovers,
        "unaffected_shard_failovers": sum(
            c.failovers for k, c in agent_conns if k not in affected
        ),
        "rounds": 6 + drain_rounds,
    }
