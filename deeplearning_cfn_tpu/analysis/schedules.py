"""Deterministic interleaving harness for the heartbeat/liveness plane.

The DLC2xx rules claim the threaded choreography — Heartbeater beats,
BrokerLivenessWatcher polls, LivenessTable classifies, the bus publishes
INSTANCE_TERMINATE, recovery replaces — is safe.  This harness *confirms*
it dynamically: a virtual clock plus a cooperative step scheduler run the
REAL production objects (no forked logic, no real threads, no sleeps)
through permuted schedules, including the silent-death path, and check
ground truth at every transition:

* a worker is only classified DEAD when its virtual silence really
  exceeded ``dead_after_s`` (no false terminations under any ordering);
* a DEAD classification always publishes exactly one INSTANCE_TERMINATE
  until the worker is recovered;
* every schedule runs to completion (single-threaded cooperative steps
  cannot deadlock; a wedged invariant still fails loudly).

Everything is seeded and wall-clock free, so a failing schedule is
replayable byte-for-byte.  tests/test_interleaving.py drives >= 50
distinct interleavings of the heartbeat-death -> recovery path through
:class:`HeartbeatChoreography` via a pytest fixture.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Sequence

from deeplearning_cfn_tpu.obs.liveness import LivenessConfig, WorkerState


class VirtualClock:
    """Monotonic virtual time: only :meth:`advance` moves it.  Callable so
    it drops into every ``clock=`` seam (LivenessTable, the watcher)."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    __call__ = now

    def advance(self, dt_s: float) -> float:
        if dt_s < 0:
            raise ValueError(f"virtual time cannot go backwards: {dt_s}")
        self._now += dt_s
        return self._now


class SimBroker:
    """The C++ broker's heartbeat table on virtual time: record() is the
    HEARTBEAT <worker> verb, dump() the table-dump mode (worker ->
    (age_s, count)), exactly the shape ``BrokerLivenessWatcher``'s
    ``fetch`` seam consumes."""

    def __init__(self, clock: VirtualClock):
        self._clock = clock
        self._beats: dict[str, tuple[float, int]] = {}

    def record(self, worker: str) -> int:
        last, count = self._beats.get(worker, (0.0, 0))
        self._beats[worker] = (self._clock.now(), count + 1)
        return count + 1

    def dump(self) -> dict[str, tuple[float, int]]:
        now = self._clock.now()
        return {
            worker: (now - last, count)
            for worker, (last, count) in self._beats.items()
        }

    def silence_s(self, worker: str) -> float | None:
        """Ground truth: virtual seconds since the worker's last beat."""
        if worker not in self._beats:
            return None
        return self._clock.now() - self._beats[worker][0]


class SimBrokerError(ConnectionError):
    """Injected connection failure (a broker restart mid-beat)."""


class SimBrokerConnection:
    """Duck-types the BrokerConnection surface Heartbeater uses
    (heartbeat + close).  ``fail_beats`` makes the next N beats raise, so
    schedules exercise the real reconnect path in Heartbeater.beat_step.
    ``fail_when`` is the partition predicate: while it returns True every
    beat raises (and so does every beat on a freshly redialed connection
    built with the same predicate), which models a network cut rather
    than a one-shot connection loss."""

    def __init__(
        self,
        broker: SimBroker,
        fail_beats: int = 0,
        fail_when: Callable[[], bool] | None = None,
    ):
        self._broker = broker
        self._fail_beats = fail_beats
        self._fail_when = fail_when
        self.closed = False

    def heartbeat(self, worker_id: str) -> int:
        if self.closed:
            raise SimBrokerError("connection is closed")
        if self._fail_when is not None and self._fail_when():
            raise SimBrokerError("network partition")
        if self._fail_beats > 0:
            self._fail_beats -= 1
            raise SimBrokerError("injected beat failure")
        return self._broker.record(worker_id)

    def close(self) -> None:
        self.closed = True


@dataclass
class StepScheduler:
    """Cooperative scheduler: actors are named step functions; a schedule
    is an explicit sequence of actor names, executed synchronously in
    order.  No threads, no preemption — the *schedule* is the
    interleaving."""

    actors: dict[str, Callable[[], Any]] = field(default_factory=dict)
    trace: list[str] = field(default_factory=list)

    def add(self, name: str, step: Callable[[], Any]) -> None:
        if name in self.actors:
            raise ValueError(f"duplicate actor {name!r}")
        self.actors[name] = step

    def run(self, schedule: Iterable[str]) -> list[str]:
        for name in schedule:
            self.actors[name]()  # unknown actor -> KeyError, loudly
            self.trace.append(name)
        return self.trace


def interleavings(
    actions: Sequence[str],
    count: int,
    seed: int = 0,
) -> list[tuple[str, ...]]:
    """``count`` distinct seeded shuffles of ``actions``.  Deterministic:
    the same (actions, count, seed) always yields the same schedules, so
    a failure names its schedule reproducibly."""
    rng = random.Random(seed)
    seen: set[tuple[str, ...]] = set()
    out: list[tuple[str, ...]] = []
    attempts = 0
    limit = count * 1000
    while len(out) < count:
        attempts += 1
        if attempts > limit:
            raise RuntimeError(
                f"could not generate {count} distinct schedules from "
                f"{len(actions)} actions (got {len(out)})"
            )
        shuffled = list(actions)
        rng.shuffle(shuffled)
        candidate = tuple(shuffled)
        if candidate not in seen:
            seen.add(candidate)
            out.append(candidate)
    return out


class InvariantViolation(AssertionError):
    """A liveness classification contradicted virtual-clock ground truth."""


class HeartbeatChoreography:
    """The full heartbeat-death -> recovery loop wired from REAL parts over
    virtual time: real ``Heartbeater`` instances (driven cooperatively via
    ``beat_step()``, never started as threads) beat at a :class:`SimBroker`;
    a real ``BrokerLivenessWatcher`` polls it through the ``fetch`` seam
    into the real ``LivenessTable``; DEAD transitions publish
    INSTANCE_TERMINATE on a real ``EventBus``; the recover step replaces
    terminated workers with fresh heartbeaters, as RecoveryManager would.

    Step vocabulary (for :class:`StepScheduler` schedules):

    * ``beat:<worker>``  one heartbeat from that worker (no-op once killed)
    * ``tick``           advance the virtual clock by ``tick_s``
    * ``poll``           watcher fetch + sweep, with ground-truth checks
    * ``kill:<worker>``  the worker dies silently (stops beating)
    * ``cut:<worker>``   network partition: its beats fail until healed
    * ``heal:<worker>``  the partition heals; its beats land again
    * ``recover``        replace every terminated-but-unrecovered worker

    Every ``poll`` validates transitions against the broker's own virtual
    timeline, so no schedule can smuggle in a false DEAD or a missed one.
    """

    def __init__(
        self,
        workers: Sequence[str],
        config: LivenessConfig | None = None,
        tick_s: float = 5.0,
        fail_first_beats: int = 0,
    ):
        from deeplearning_cfn_tpu.cluster.broker_service import (
            BrokerLivenessWatcher,
        )
        from deeplearning_cfn_tpu.obs.heartbeat import Heartbeater
        from deeplearning_cfn_tpu.provision.events import EventBus, EventKind

        self.clock = VirtualClock()
        self.broker = SimBroker(self.clock)
        self.config = config or LivenessConfig()
        self.tick_s = tick_s
        self.bus = EventBus()
        self.terminated: list[tuple[Any, float | None]] = []
        self._verified = 0
        self._terminate_kind = EventKind.INSTANCE_TERMINATE
        self.bus.subscribe(self._on_event)
        self.watcher = BrokerLivenessWatcher(
            cluster_name="sim",
            group="workers",
            bus=self.bus,
            config=self.config,
            clock=self.clock,
            fetch=self.broker.dump,
        )
        # A one-shot failure budget: only the FIRST dial gets the failing
        # connection, so Heartbeater's drop-and-redial recovery actually
        # lands a beat afterwards (a per-connection budget would fail
        # every redial forever).
        self._fail_budget = max(0, fail_first_beats)
        # Workers currently on the wrong side of a network cut: their
        # beats (on live AND freshly redialed connections) raise until a
        # heal step removes them.
        self.partitioned: set[str] = set()
        self._mk_heartbeater = lambda worker: Heartbeater(
            host="sim",
            port=0,
            worker_id=worker,
            interval_s=tick_s,
            connection_factory=lambda w=worker: self._dial_sim(w),
        )
        self.heartbeaters = {w: self._mk_heartbeater(w) for w in workers}
        self.alive: set[str] = set(workers)
        self.recovered: dict[str, str] = {}  # dead worker -> replacement

    def _dial_sim(self, worker: str | None = None) -> SimBrokerConnection:
        fails, self._fail_budget = self._fail_budget, 0
        return SimBrokerConnection(
            self.broker,
            fail_beats=fails,
            fail_when=(
                (lambda: worker in self.partitioned)
                if worker is not None
                else None
            ),
        )

    # --- bus + truth checking -------------------------------------------
    def _on_event(self, event: Any) -> None:
        # Never raise here: EventBus isolates handler exceptions by
        # contract, which would swallow the invariant.  Capture the
        # ground-truth silence at publish time; poll verifies it.
        if event.kind is self._terminate_kind:
            self.terminated.append(
                (event, self.broker.silence_s(event.instance_id))
            )

    def _check_terminates(self) -> None:
        while self._verified < len(self.terminated):
            event, silence = self.terminated[self._verified]
            self._verified += 1
            if silence is None or silence < self.config.dead_after_s:
                raise InvariantViolation(
                    f"INSTANCE_TERMINATE for {event.instance_id} at "
                    f"virtual silence {silence}; dead_after_s="
                    f"{self.config.dead_after_s}"
                )

    def _check_transitions(self, transitions: Iterable[Any]) -> None:
        for worker, _old, new in transitions:
            silence = self.broker.silence_s(worker)
            if silence is None:
                continue
            if new is WorkerState.DEAD and silence < self.config.dead_after_s:
                raise InvariantViolation(
                    f"{worker} marked DEAD at silence {silence:.1f}s "
                    f"< dead_after {self.config.dead_after_s}s"
                )
            if new is WorkerState.SUSPECT and (
                silence < self.config.suspect_after_s
            ):
                raise InvariantViolation(
                    f"{worker} marked SUSPECT at silence {silence:.1f}s "
                    f"< suspect_after {self.config.suspect_after_s}s"
                )
            if new is WorkerState.ALIVE and silence >= self.config.dead_after_s:
                raise InvariantViolation(
                    f"{worker} marked ALIVE at silence {silence:.1f}s "
                    f">= dead_after {self.config.dead_after_s}s"
                )

    # --- the step vocabulary --------------------------------------------
    def step(self, action: str) -> None:
        name, _, arg = action.partition(":")
        if name == "beat":
            if arg in self.alive:
                self.heartbeaters[arg].beat_step()
        elif name == "tick":
            self.clock.advance(self.tick_s)
        elif name == "poll":
            self._check_transitions(self.watcher.poll())
            self._check_terminates()
        elif name == "kill":
            self.alive.discard(arg)
        elif name == "cut":
            # Network partition: the worker keeps trying to beat (stays in
            # alive) but every beat fails until healed.
            self.partitioned.add(arg)
        elif name == "heal":
            self.partitioned.discard(arg)
        elif name == "recover":
            for event, _silence in list(self.terminated):
                dead = event.instance_id
                if dead in self.recovered:
                    continue  # duplicate terminate: recovery is idempotent
                replacement = f"{dead}+1"
                self.recovered[dead] = replacement
                self.heartbeaters[replacement] = self._mk_heartbeater(
                    replacement
                )
                self.alive.add(replacement)
                self.heartbeaters[replacement].beat_step()
        else:
            raise ValueError(f"unknown step {action!r}")

    def run(self, schedule: Iterable[str]) -> "HeartbeatChoreography":
        scheduler = StepScheduler()
        executed = list(schedule)
        for action in dict.fromkeys(executed):
            scheduler.add(action, lambda a=action: self.step(a))
        scheduler.run(executed)
        if len(scheduler.trace) != len(executed):
            raise InvariantViolation("schedule did not run to completion")
        return self

    # --- end-state assertions -------------------------------------------
    def states(self) -> dict[str, str]:
        return {
            worker: info["state"]
            for worker, info in self.watcher.snapshot().items()
        }

    def terminated_workers(self) -> list[str]:
        return [event.instance_id for event, _silence in self.terminated]
