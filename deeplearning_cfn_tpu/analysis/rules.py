"""The DLC0xx per-file rules.

Each rule encodes one repo idiom whose violation has already bitten (or
demonstrably would): the module docstrings cite the incident.  Rules are
deliberately conservative — a lint that cries wolf gets noqa'd into
uselessness — so every matcher anchors on the specific shape of the bug,
not on a keyword.

Registered ids (docs/STATIC_ANALYSIS.md has the operator-facing table):

DLC001 untimed blocking call        DLC005 substring param-name match
DLC002 NaN-unsafe json.dumps       DLC006 thread without daemon/join
DLC003 host sync under jit          DLC007 mutable default / py2 remnant
DLC004 interrupt-swallowing except  DLC008 undonated state-threading jit
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator

from deeplearning_cfn_tpu.analysis.core import (
    FileContext,
    Rule,
    Violation,
    call_name,
    dotted_name,
    has_keyword,
    register,
    walk_skipping_nested_functions,
)

# --- DLC001: untimed blocking calls ---------------------------------------
# The repo's idiom is utils/timeouts.py: every phase draws from an explicit
# budget, and every blocking primitive states its own bound.  An untimed
# socket/subprocess call in the cluster/provision layers hangs bootstrap
# forever on the exact failure (unreachable broker, wedged make) the
# budget machinery exists to survive.

# dotted call name -> how a timeout may be passed: a kwarg name, plus an
# optional positional index that also counts.
_TIMEOUT_CALLS: dict[str, int | None] = {
    "socket.create_connection": 1,
    "subprocess.run": None,
    "subprocess.call": None,
    "subprocess.check_call": None,
    "subprocess.check_output": None,
    "urllib.request.urlopen": 2,
    "requests.get": None,
    "requests.post": None,
    "requests.put": None,
    "requests.head": None,
    "requests.delete": None,
    "requests.request": None,
}
# Receivers whose .wait()/.communicate() are Popen-shaped (a bare
# `self.wait()` on an unrelated class must not match).
_PROC_RECEIVERS = ("proc", "process", "popen", "child")


def _receiver_is_proc(func: ast.Attribute) -> bool:
    name = dotted_name(func.value)
    if name is None:
        return False
    terminal = name.rsplit(".", 1)[-1].lower()
    return any(marker in terminal for marker in _PROC_RECEIVERS)


def _check_untimed_calls(tree: ast.Module, ctx: FileContext) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if has_keyword(node, "timeout", "timeout_s"):
            continue
        name = call_name(node)
        if name in _TIMEOUT_CALLS:
            pos = _TIMEOUT_CALLS[name]
            if pos is not None and len(node.args) > pos:
                continue  # timeout passed positionally
            yield ctx.violation(
                "DLC001",
                node,
                f"{name}() without a timeout can hang forever; pass "
                "timeout= (the utils/timeouts.py budget discipline)",
            )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("wait", "communicate")
            and _receiver_is_proc(node.func)
        ):
            yield ctx.violation(
                "DLC001",
                node,
                f".{node.func.attr}() on a subprocess without timeout= "
                "blocks indefinitely if the child wedges",
            )


register(
    Rule(
        id="DLC001",
        name="untimed-blocking-call",
        doc="socket/subprocess/requests calls must carry an explicit timeout",
        check=_check_untimed_calls,
    )
)

# --- DLC002: NaN-unsafe json.dumps in bench/metrics emitters ---------------
# json.dumps serializes float('nan') as the bare token `NaN`, which is NOT
# JSON: every strict consumer (jq, json.loads in CI comparisons, the
# BENCH_*.json history) chokes or silently skips the record.  Round-5
# ADVICE caught exactly this leaking from scripts/chip_measure.py.  The
# idiom: sanitize computed floats (train/metrics.py json_safe) and pass
# allow_nan=False so regressions fail at the emitter, not the reader.


def _applies_bench_paths(path: Path) -> bool:
    parts = path.parts
    return (
        "scripts" in parts
        or path.name == "bench.py"
        or (path.name == "metrics.py" and "train" in parts)
    )


def _check_nan_unsafe_dumps(tree: ast.Module, ctx: FileContext) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or call_name(node) != "json.dumps":
            continue
        kw = next((k for k in node.keywords if k.arg == "allow_nan"), None)
        strict = (
            kw is not None
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
        )
        if not strict:
            yield ctx.violation(
                "DLC002",
                node,
                "json.dumps in a bench/metrics emitter must pass "
                "allow_nan=False (and sanitize computed floats with "
                "train/metrics.json_safe): NaN serializes as invalid JSON",
            )


register(
    Rule(
        id="DLC002",
        name="nan-unsafe-json",
        doc="bench/metrics json.dumps must be strict (allow_nan=False)",
        check=_check_nan_unsafe_dumps,
        applies=_applies_bench_paths,
    )
)

# --- DLC003: host synchronization inside jitted functions ------------------
# Under @jax.jit these calls either fail at trace time or, worse, force a
# silent device->host sync per step when the function falls back to eager
# (e.g. after a refactor drops the decorator's argument threading).

_JIT_NAMES = ("jax.jit", "jit", "jax.pmap", "pmap")
_HOST_SYNC_CALLS = (
    "jax.device_get",
    "np.asarray",
    "numpy.asarray",
    "np.array",
    "numpy.array",
)
_HOST_SYNC_METHODS = ("item", "block_until_ready")


def _is_jit_expr(expr: ast.AST) -> bool:
    name = dotted_name(expr)
    if name in _JIT_NAMES:
        return True
    if isinstance(expr, ast.Call):
        fname = call_name(expr)
        if fname in _JIT_NAMES:
            return True  # decorator factory form
        if fname in ("partial", "functools.partial") and expr.args:
            return _is_jit_expr(expr.args[0])
    return False


def _jit_decorated(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    return any(_is_jit_expr(d) for d in fn.decorator_list)


def _check_host_sync_in_jit(tree: ast.Module, ctx: FileContext) -> Iterator[Violation]:
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _jit_decorated(fn):
            continue
        for node in walk_skipping_nested_functions(fn.body):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in _HOST_SYNC_CALLS:
                yield ctx.violation(
                    "DLC003",
                    node,
                    f"{name}() inside jit-decorated {fn.name}() forces a "
                    "host sync (or fails at trace time); keep device->host "
                    "transfers outside the compiled step",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_METHODS
                and not node.args
            ):
                yield ctx.violation(
                    "DLC003",
                    node,
                    f".{node.func.attr}() inside jit-decorated {fn.name}() "
                    "is a host sync; compute on-device and read back after "
                    "dispatch",
                )


register(
    Rule(
        id="DLC003",
        name="host-sync-in-jit",
        doc="no device_get/.item()/np.asarray inside jit-compiled functions",
        check=_check_host_sync_in_jit,
    )
)

# --- DLC004: interrupt-swallowing exception handlers -----------------------
# A bare `except:` (or `except BaseException` without a re-raise) catches
# KeyboardInterrupt/SystemExit: Ctrl-C against an agent/broker retry loop
# then becomes "log and keep looping" and the operator cannot stop the
# process.  A BaseException handler is legitimate exactly when it re-raises
# after cleanup — that shape is allowed.


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    caught = handler.name
    for node in walk_skipping_nested_functions(handler.body):
        if isinstance(node, ast.Raise):
            if node.exc is None:
                return True  # bare `raise`
            if (
                caught
                and isinstance(node.exc, ast.Name)
                and node.exc.id == caught
            ):
                return True  # `raise e` — re-raises the original
    return False


def _catches_base_exception(handler: ast.ExceptHandler) -> bool:
    types = (
        handler.type.elts if isinstance(handler.type, ast.Tuple) else [handler.type]
    )
    return any(dotted_name(t) == "BaseException" for t in types if t is not None)


def _check_swallowed_interrupts(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            yield ctx.violation(
                "DLC004",
                node,
                "bare `except:` swallows KeyboardInterrupt/SystemExit; "
                "catch Exception (or re-raise BaseException after cleanup)",
            )
        elif _catches_base_exception(node) and not _handler_reraises(node):
            yield ctx.violation(
                "DLC004",
                node,
                "`except BaseException` without a re-raise swallows "
                "KeyboardInterrupt; re-raise after cleanup or catch "
                "Exception",
            )


register(
    Rule(
        id="DLC004",
        name="interrupt-swallowing-except",
        doc="no bare except / BaseException handlers that fail to re-raise",
        check=_check_swallowed_interrupts,
    )
)

# --- DLC005: substring-based pytree param-name matching --------------------
# `'norm' in leaf` also matches 'normalizer_proj' — a layer that should
# receive weight decay silently stops decaying (train/trainer.py:124 was
# exactly this).  Param-name predicates must anchor: exact match or
# whole-component match on '_'-split names.

_PARAM_NAME_MARKERS = ("leaf", "param")


def _names_a_param(node: ast.AST) -> bool:
    name = dotted_name(node)
    if name is None:
        return False
    terminal = name.rsplit(".", 1)[-1].lower()
    return any(marker in terminal for marker in _PARAM_NAME_MARKERS)


def _check_substring_param_match(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        if not (
            isinstance(node.left, ast.Constant) and isinstance(node.left.value, str)
        ):
            continue
        for op, comparator in zip(node.ops, node.comparators):
            if isinstance(op, (ast.In, ast.NotIn)) and _names_a_param(comparator):
                yield ctx.violation(
                    "DLC005",
                    node,
                    f"substring match {node.left.value!r} in a param/leaf "
                    "name also matches unrelated layers (e.g. "
                    "'normalizer_proj'); use exact or '_'-component-"
                    "anchored matching",
                )


register(
    Rule(
        id="DLC005",
        name="substring-param-match",
        doc="pytree param-name predicates must anchor, not substring-match",
        check=_check_substring_param_match,
    )
)

# --- DLC006: threads without a daemon flag or join path --------------------
# A non-daemon thread with no join keeps the interpreter alive after main
# exits (the classic hung-agent-on-shutdown); a daemon=True producer is
# the repo idiom (train/data.py PrefetchIterator).  Either state
# daemon= explicitly or join the thread somewhere in the same scope.


def _scope_has_join(node: ast.AST, ctx: FileContext) -> bool:
    scope = ctx.enclosing(node, ast.ClassDef) or ctx.enclosing(
        node, ast.FunctionDef, ast.AsyncFunctionDef
    ) or ctx.tree
    for n in ast.walk(scope):
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "join"
        ):
            return True
    return False


def _check_thread_daemon(tree: ast.Module, ctx: FileContext) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name not in ("threading.Thread", "Thread"):
            continue
        if has_keyword(node, "daemon"):
            continue
        if _scope_has_join(node, ctx):
            continue
        yield ctx.violation(
            "DLC006",
            node,
            "Thread() without daemon= and with no join path in scope: "
            "the thread outlives (and can hang) interpreter shutdown",
        )


register(
    Rule(
        id="DLC006",
        name="thread-without-daemon",
        doc="threads must state daemon= or have a join path",
        check=_check_thread_daemon,
    )
)

# --- DLC007: mutable default arguments + Python-2 remnants -----------------
# The cluster scripts descend from a py2 CloudFormation codebase; remnants
# (xrange, dict.iteritems, has_key) crash at runtime on py3, and mutable
# defaults alias state across calls — both pure foot-guns with zero
# legitimate uses here.

_PY2_NAMES = ("xrange", "basestring")
_PY2_METHODS = ("has_key", "iteritems", "iterkeys", "itervalues")


def _is_mutable_default(node: ast.AST | None) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call) and not node.args and not node.keywords:
        return call_name(node) in ("list", "dict", "set")
    return False


def _check_py_hygiene(tree: ast.Module, ctx: FileContext) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]
            for d in defaults:
                if _is_mutable_default(d):
                    yield ctx.violation(
                        "DLC007",
                        d,
                        f"mutable default argument in {node.name}() aliases "
                        "state across calls; default to None and construct "
                        "inside",
                    )
        elif isinstance(node, ast.Name) and node.id in _PY2_NAMES:
            yield ctx.violation(
                "DLC007", node, f"python-2 remnant {node.id!r} does not exist on py3"
            )
        elif isinstance(node, ast.Attribute) and node.attr in _PY2_METHODS:
            yield ctx.violation(
                "DLC007",
                node,
                f"python-2 remnant .{node.attr}() does not exist on py3 dicts",
            )


register(
    Rule(
        id="DLC007",
        name="py-hygiene",
        doc="no mutable default args; no python-2 remnants",
        check=_check_py_hygiene,
    )
)

# --- DLC008: state-threading jit steps must donate -------------------------
# A train step that takes the state and returns the new state holds BOTH
# copies live across the update unless the input is donated — on a 16 GiB
# chip that silently halves the trainable model size.  The repo idiom is
# donate_argnums=(0,) on every state-threading jit (train/trainer.py).

_DONATE_KWARGS = ("donate_argnums", "donate_argnames")


def _first_arg_is_state(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    args = fn.args.args
    if args and args[0].arg == "self":
        args = args[1:]
    return bool(args) and args[0].arg == "state"


def _decorator_donates(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for d in fn.decorator_list:
        if isinstance(d, ast.Call) and _is_jit_expr(d):
            if has_keyword(d, *_DONATE_KWARGS):
                return True
    return False


def _check_missing_donation(tree: ast.Module, ctx: FileContext) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (
                _jit_decorated(node)
                and _first_arg_is_state(node)
                and not _decorator_donates(node)
            ):
                yield ctx.violation(
                    "DLC008",
                    node,
                    f"jit-decorated {node.name}(state, ...) without "
                    "donate_argnums holds two full state copies live; "
                    "donate the input state",
                )
        elif isinstance(node, ast.Call) and call_name(node) in ("jax.jit", "jit"):
            # Call form: jax.jit(step_fn, in_shardings=..., out_shardings=...)
            # with BOTH sharding sets is the state-in/state-out trainer
            # shape; eval-style jits (in_shardings only) reuse their inputs
            # and must NOT donate.
            if (
                node.args
                and has_keyword(node, "in_shardings")
                and has_keyword(node, "out_shardings")
                and not has_keyword(node, *_DONATE_KWARGS)
            ):
                yield ctx.violation(
                    "DLC008",
                    node,
                    "jax.jit(...) with in_shardings+out_shardings but no "
                    "donate_argnums: a state-threading step holds two "
                    "state copies live without donation",
                )


register(
    Rule(
        id="DLC008",
        name="undonated-state-jit",
        doc="state-threading jitted steps must donate the input state",
        check=_check_missing_donation,
    )
)
