"""DLC2xx: the concurrency lockset / thread-escape analyzer.

PR 2 made the control plane genuinely concurrent — Heartbeater daemon
threads, the DevicePrefetcher producer, the FlightRecorder ring — and
control-plane races are exactly the class of silent failure large-scale
systems papers identify as the dominant source of distributed-training
flakiness.  These rules encode the repo's threading discipline:

DLC201 unlocked-shared-attribute  attribute written from thread-side code
                                  (a Thread subclass's run() closure, or a
                                  ``target=self.method``) and visible
                                  outside the thread without a common lock
DLC202 bare-acquire               ``lock.acquire()`` as a statement with no
                                  try/finally release — an exception leaks
                                  the lock forever
DLC203 blocking-under-lock        socket/subprocess/sleep inside a
                                  ``with <lock>:`` body — every other
                                  thread stalls behind one peer's I/O
DLC204 daemon-without-stop        a daemon thread with neither a stop
                                  Event nor a join path — "daemon" becomes
                                  "unkillable until process exit"
DLC205 wall-clock-liveness        ``time.time()`` arithmetic/comparison in
                                  cluster/obs timing paths — NTP steps the
                                  wall clock; liveness and retry deadlines
                                  must use time.monotonic() (the broker
                                  side already uses std::chrono::steady_clock)

Like the DLC0xx rules, every matcher anchors on the bug's shape, not a
keyword: DLC201 only fires on classes that actually spawn a thread at one
of their own methods, DLC203 only inside a lock-typed ``with``, DLC205
only where the timestamp feeds arithmetic or a deadline-named binding
(record metadata like ``"started_ts": time.time()`` stays legal).

All five are gated behind ``dlcfn lint --concurrency`` (or an explicit
``--select``), so the pass ratchets via the committed baseline instead of
flag-flooding a previously-clean tree.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator

from deeplearning_cfn_tpu.analysis.core import (
    FileContext,
    Rule,
    Violation,
    call_name,
    dotted_name,
    keyword,
    register,
)

GATE = "concurrency"
RULE_IDS = ("DLC201", "DLC202", "DLC203", "DLC204", "DLC205")

_LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "Lock",
    "RLock",
    "Condition",
}
# Attribute types that are themselves synchronization/thread-safe
# primitives: writes to (or through) them do not need an extra lock.
_SAFE_FACTORIES = _LOCK_FACTORIES | {
    "threading.Event",
    "Event",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    "Semaphore",
    "queue.Queue",
    "Queue",
    "collections.deque",
    "deque",
}

_THREAD_NAMES = ("threading.Thread", "Thread")


def _is_thread_class(cls: ast.ClassDef) -> bool:
    return any(dotted_name(b) in _THREAD_NAMES for b in cls.bases)


def _self_attr(node: ast.AST) -> str | None:
    """'x' for a ``self.x`` attribute node, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _attr_factories(cls: ast.ClassDef) -> dict[str, set[str]]:
    """attr name -> dotted names of calls ever assigned to ``self.attr``."""
    out: dict[str, set[str]] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            attr = _self_attr(target)
            if attr is None:
                continue
            name = (
                call_name(node.value) if isinstance(node.value, ast.Call) else None
            )
            out.setdefault(attr, set()).add(name or "")
    return out


def _thread_side_methods(cls: ast.ClassDef) -> set[str]:
    """Methods that execute on a spawned thread: ``run`` of a Thread
    subclass, every ``target=self.m``, and the closure of self-calls
    reachable from those entries."""
    methods = {
        fn.name: fn
        for fn in cls.body
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    entries: set[str] = set()
    if _is_thread_class(cls) and "run" in methods:
        entries.add("run")
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and call_name(node) in _THREAD_NAMES:
            kw = keyword(node, "target")
            if kw is not None:
                attr = _self_attr(kw.value)
                if attr in methods:
                    entries.add(attr)
    # Transitive closure over self.<m>() calls.
    frontier = list(entries)
    while frontier:
        fn = methods.get(frontier.pop())
        if fn is None:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee in methods and callee not in entries:
                    entries.add(callee)
                    frontier.append(callee)
    return entries


def _under_lock(node: ast.AST, ctx: FileContext, lock_attrs: set[str]) -> bool:
    """Is ``node`` lexically inside ``with self.<lock>:`` for a known lock?"""
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                if _self_attr(item.context_expr) in lock_attrs:
                    return True
        cur = ctx.parents.get(cur)
    return False


def _check_unlocked_shared_attr(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        thread_side = _thread_side_methods(cls)
        if not thread_side:
            continue
        factories = _attr_factories(cls)
        lock_attrs = {
            a for a, fs in factories.items() if fs & _LOCK_FACTORIES
        }
        safe_attrs = {a for a, fs in factories.items() if fs & _SAFE_FACTORIES}
        methods = [
            fn
            for fn in cls.body
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # attr -> (node of first unlocked thread-side write, method name)
        unlocked_writes: dict[str, tuple[ast.AST, str]] = {}
        main_unlocked: set[str] = set()
        for fn in methods:
            if fn.name == "__init__":
                continue  # construction happens-before the thread starts
            for node in ast.walk(fn):
                attr = _self_attr(node)
                if attr is None or attr in safe_attrs:
                    continue
                assert isinstance(node, ast.Attribute)
                if fn.name in thread_side:
                    if isinstance(node.ctx, ast.Store) and not _under_lock(
                        node, ctx, lock_attrs
                    ):
                        unlocked_writes.setdefault(attr, (node, fn.name))
                else:
                    if not _under_lock(node, ctx, lock_attrs):
                        main_unlocked.add(attr)
        for attr, (node, method) in sorted(unlocked_writes.items()):
            # Escapes the thread if the class's own main-side code touches
            # it without the lock, or if it is public API (readable by any
            # caller while the thread mutates it).
            if attr in main_unlocked or not attr.startswith("_"):
                yield ctx.violation(
                    "DLC201",
                    node,
                    f"self.{attr} is written in thread-side "
                    f"{cls.name}.{method}() without a lock but is visible "
                    "outside the thread; guard both sides with a common "
                    "`with self.<lock>:`",
                )


register(
    Rule(
        id="DLC201",
        name="unlocked-shared-attribute",
        doc="thread-side attribute writes visible outside the thread need a lock",
        check=_check_unlocked_shared_attr,
        gate=GATE,
    )
)

# --- DLC202: bare acquire() ------------------------------------------------

_LOCKISH_MARKERS = ("lock", "mutex", "sem", "cond")


def _is_lockish(expr: ast.AST) -> bool:
    name = dotted_name(expr)
    if name is None:
        return False
    terminal = name.rsplit(".", 1)[-1].lower()
    return any(marker in terminal for marker in _LOCKISH_MARKERS)


def _releases(try_node: ast.Try, receiver: str) -> bool:
    for stmt in try_node.finalbody:
        for node in ast.walk(stmt):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "release"
                and dotted_name(node.func.value) == receiver
            ):
                return True
    return False


def _check_bare_acquire(tree: ast.Module, ctx: FileContext) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        if not (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "acquire"
            and _is_lockish(call.func.value)
        ):
            continue
        receiver = dotted_name(call.func.value) or ""
        # Clean shape: acquire() guarded by a try/finally that releases the
        # same receiver — either the acquire sits inside the try, or the
        # try is a sibling in the same block right after it.
        enclosing_try = ctx.enclosing(node, ast.Try)
        if isinstance(enclosing_try, ast.Try) and _releases(enclosing_try, receiver):
            continue
        parent = ctx.parents.get(node)
        siblings: list[ast.stmt] = []
        for attr in ("body", "orelse", "finalbody"):
            block = getattr(parent, attr, None)
            if isinstance(block, list) and node in block:
                siblings = block
        idx = siblings.index(node) if node in siblings else -1
        follower = siblings[idx + 1] if 0 <= idx < len(siblings) - 1 else None
        if isinstance(follower, ast.Try) and _releases(follower, receiver):
            continue
        yield ctx.violation(
            "DLC202",
            node,
            f"{receiver}.acquire() with no try/finally release: an "
            "exception before the release leaks the lock forever; use "
            f"`with {receiver}:` (or release in a finally)",
        )


register(
    Rule(
        id="DLC202",
        name="bare-acquire",
        doc="acquire() must be `with lock:` or paired with try/finally release",
        check=_check_bare_acquire,
        gate=GATE,
    )
)

# --- DLC203: blocking I/O while holding a lock -----------------------------
# File writes are deliberately NOT in this list: the FlightRecorder
# journals under its lock by design (local appends, bounded lines).  The
# bug shape is unbounded waits — network, child processes, sleeps — that
# stall every thread queued on the lock behind one peer's I/O.

_BLOCKING_CALLS = {
    "time.sleep",
    "socket.create_connection",
    "urllib.request.urlopen",
}
_BLOCKING_PREFIXES = ("subprocess.", "requests.")
_SOCK_METHODS = ("recv", "sendall", "connect", "accept")
_SOCK_MARKERS = ("sock", "conn")
_PROC_METHODS = ("wait", "communicate")
_PROC_MARKERS = ("proc", "process", "popen", "child")


def _receiver_matches(func: ast.Attribute, markers: tuple[str, ...]) -> bool:
    name = dotted_name(func.value)
    if name is None:
        return False
    terminal = name.rsplit(".", 1)[-1].lower()
    return any(marker in terminal for marker in markers)


def _blocking_call(node: ast.Call) -> str | None:
    name = call_name(node)
    if name in _BLOCKING_CALLS or (
        name and name.startswith(_BLOCKING_PREFIXES)
    ):
        return f"{name}()"
    if isinstance(node.func, ast.Attribute):
        if node.func.attr in _SOCK_METHODS and _receiver_matches(
            node.func, _SOCK_MARKERS
        ):
            return f".{node.func.attr}() on a socket"
        if node.func.attr in _PROC_METHODS and _receiver_matches(
            node.func, _PROC_MARKERS
        ):
            return f".{node.func.attr}() on a subprocess"
    return None


def _check_blocking_under_lock(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        what = _blocking_call(node)
        if what is None:
            continue
        cur = ctx.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                break  # a nested def's body runs later, not under the with
            if isinstance(cur, ast.With) and any(
                _is_lockish(item.context_expr) for item in cur.items
            ):
                yield ctx.violation(
                    "DLC203",
                    node,
                    f"{what} while holding a lock blocks every thread "
                    "queued on it; move the I/O outside the `with` and "
                    "only mutate shared state under the lock",
                )
                break
            cur = ctx.parents.get(cur)


register(
    Rule(
        id="DLC203",
        name="blocking-under-lock",
        doc="no socket/subprocess/sleep calls inside a `with <lock>:` body",
        check=_check_blocking_under_lock,
        gate=GATE,
    )
)

# --- DLC204: daemon threads without a stop path ----------------------------
# daemon=True satisfies DLC006 (interpreter shutdown) but is not a
# lifecycle: a daemon loop with no stop Event and no join is unstoppable
# in-process — tests leak it, agents cannot drain it before teardown.
# The repo idiom is Heartbeater: a halt Event plus stop()->join().


def _scope_has_stop_path(scope: ast.AST) -> bool:
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            if call_name(node) in ("threading.Event", "Event"):
                return True
            if isinstance(node.func, ast.Attribute) and node.func.attr == "join":
                return True
    return False


def _daemon_true(call: ast.Call) -> bool:
    kw = keyword(call, "daemon")
    return (
        kw is not None
        and isinstance(kw.value, ast.Constant)
        and kw.value.value is True
    )


def _class_sets_daemon(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and _daemon_true(node):
            return True
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if (
                    _self_attr(target) == "daemon"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True
                ):
                    return True
    return False


def _check_daemon_without_stop(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    flagged_classes: set[ast.ClassDef] = set()
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        if _is_thread_class(cls) and _class_sets_daemon(cls):
            if not _scope_has_stop_path(cls):
                flagged_classes.add(cls)
                yield ctx.violation(
                    "DLC204",
                    cls,
                    f"daemon Thread subclass {cls.name} has no stop Event "
                    "and no join path: the loop is unstoppable in-process; "
                    "add a halt Event and a stop() that joins",
                )
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and call_name(node) in _THREAD_NAMES
            and _daemon_true(node)
        ):
            continue
        scope = ctx.enclosing(node, ast.ClassDef) or ctx.tree
        if scope in flagged_classes:
            continue  # already reported at the class level
        if not _scope_has_stop_path(scope):
            yield ctx.violation(
                "DLC204",
                node,
                "daemon Thread with no stop Event and no join path in "
                "scope: nothing can stop the loop before process exit; "
                "pair it with a threading.Event (or join it)",
            )


register(
    Rule(
        id="DLC204",
        name="daemon-without-stop",
        doc="daemon threads need a stop Event or join path",
        check=_check_daemon_without_stop,
        gate=GATE,
    )
)

# --- DLC205: wall-clock time in liveness/retry paths -----------------------

_DEADLINE_MARKERS = (
    "deadline",
    "expires",
    "expiry",
    "until",
    "cutoff",
    "last_beat",
)


def _applies_timing_paths(path: Path) -> bool:
    parts = path.parts
    return "cluster" in parts or "obs" in parts or "provision" in parts


def _deadline_named(target: ast.AST) -> bool:
    name = dotted_name(target)
    if name is None:
        return False
    terminal = name.rsplit(".", 1)[-1].lower()
    return any(marker in terminal for marker in _DEADLINE_MARKERS)


def _check_wall_clock_liveness(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and call_name(node) == "time.time"):
            continue
        parent = ctx.parents.get(node)
        fires = isinstance(parent, (ast.BinOp, ast.Compare))
        if isinstance(parent, ast.Assign) and any(
            _deadline_named(t) for t in parent.targets
        ):
            fires = True
        if fires:
            yield ctx.violation(
                "DLC205",
                node,
                "time.time() used for elapsed-time/deadline logic: NTP "
                "steps the wall clock backwards and forwards; use "
                "time.monotonic() (the broker side already uses "
                "std::chrono::steady_clock)",
            )


register(
    Rule(
        id="DLC205",
        name="wall-clock-liveness",
        doc="liveness/retry timing in cluster/obs must use time.monotonic()",
        check=_check_wall_clock_liveness,
        applies=_applies_timing_paths,
        gate=GATE,
    )
)
