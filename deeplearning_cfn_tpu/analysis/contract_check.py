"""DLC1xx: the cross-language broker-contract checker.

The broker wire protocol lives in FOUR places that nothing previously
forced to agree:

1. the canonical verb set, ``cluster/contract.py:BROKER_PROTOCOL_VERBS``
   (the single source of truth this checker enforces);
2. the verbs the Python client actually sends on the wire
   (``cluster/broker_client.py`` — every ``sendall(f"VERB ...")``);
3. the verbs the supervisor layer exercises through client methods
   (``cluster/broker_service.py``);
4. the verbs the C++ broker dispatches (``native/broker/broker.cpp`` —
   the ``cmd == "VERB"`` handler chain in ``serve()``).

Any verb present in one layer but missing from another is exactly the
"drifted wire protocol" glue failure the reference system kept hitting:
the client grows a verb the C++ broker answers with ``ERR unknown
command``, or a handler ships with no caller and rots.  The checker
extracts each layer's set (Python via AST, C++ via a tolerant regex
scanner — no C++ parser dependency) and cross-checks.

DLC101 guards the OTHER wire contract in cluster/contract.py: the
``to_message``/``from_message`` field sets.  A field written by
``to_message`` but never read back (or read but never written) is a
protocol key drifting out of sync between coordinator and workers.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from deeplearning_cfn_tpu.analysis.core import Violation, dotted_name

RULE_VERBS = "DLC100"
RULE_FIELDS = "DLC101"

REPO_ROOT = Path(__file__).resolve().parents[2]
CONTRACT_PY = REPO_ROOT / "deeplearning_cfn_tpu" / "cluster" / "contract.py"
CLIENT_PY = REPO_ROOT / "deeplearning_cfn_tpu" / "cluster" / "broker_client.py"
SERVICE_PY = REPO_ROOT / "deeplearning_cfn_tpu" / "cluster" / "broker_service.py"
BROKER_CPP = REPO_ROOT / "native" / "broker" / "broker.cpp"

# Envelope keys to_message stamps for queue-side filtering (bootstrap
# agents route on them) that from_message intentionally does not consume.
_ENVELOPE_FIELDS = {"event", "status"}

_VERB = re.compile(r"^[A-Z]{2,16}$")
# Tolerant C++ scanner: the dispatch chain in serve() compares the parsed
# command token against string literals.  Matches both `cmd == "SEND"`
# and `"SEND" == cmd` spellings, any whitespace.
_CPP_HANDLER = re.compile(
    r'(?:cmd\s*==\s*"([A-Z]{2,16})")|(?:"([A-Z]{2,16})"\s*==\s*cmd)'
)


def _parse(path: Path) -> ast.Module:
    return ast.parse(path.read_text(), filename=str(path))


# --- layer 1: the canonical set -------------------------------------------
def canonical_verbs(contract_py: Path = CONTRACT_PY) -> tuple[set[str], int]:
    """(verbs, lineno) from the BROKER_PROTOCOL_VERBS assignment."""
    tree = _parse(contract_py)
    for node in ast.walk(tree):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "BROKER_PROTOCOL_VERBS":
                verbs = {
                    e.value
                    for e in ast.walk(value)
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
                return verbs, node.lineno
    return set(), 1


# --- layer 2: what the client sends ---------------------------------------
def _leading_literal(expr: ast.AST) -> str | None:
    """The leading string literal of a wire-write expression.

    Handles the client's three shapes::

        b"PING\\n"
        f"SEND {queue} {len(body)}\\n".encode()
        f"RECV {q} {n} {v}\\n".encode() + body     (header + payload concat)
    """
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return _leading_literal(expr.left)
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "encode"
    ):
        return _leading_literal(expr.func.value)
    if isinstance(expr, ast.JoinedStr) and expr.values:
        first = expr.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, bytes):
            return expr.value.decode(errors="replace")
        if isinstance(expr.value, str):
            return expr.value
    return None


def client_verb_map(client_py: Path = CLIENT_PY) -> dict[str, set[str]]:
    """method name -> verbs that method writes to the socket, for every
    method of every class in broker_client.py (in practice:
    BrokerConnection).  The union of values is the client's wire set."""
    tree = _parse(client_py)
    out: dict[str, set[str]] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            verbs: set[str] = set()
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "sendall"
                    and node.args
                ):
                    lit = _leading_literal(node.args[0])
                    if lit:
                        token = lit.split()[0] if lit.split() else ""
                        if _VERB.fullmatch(token):
                            verbs.add(token)
            if verbs:
                out[fn.name] = verbs
    return out


def client_verbs(client_py: Path = CLIENT_PY) -> set[str]:
    return set().union(*client_verb_map(client_py).values() or [set()])


# --- layer 3: what the supervisor exercises -------------------------------
def service_verbs(
    service_py: Path = SERVICE_PY, client_py: Path = CLIENT_PY
) -> set[str]:
    """Verbs broker_service reaches through client-connection methods.

    Matching is receiver-anchored: only calls on names containing 'conn'
    count (``conn.ping()``), so dict ``.get()`` etc. cannot alias into
    protocol verbs."""
    verb_map = client_verb_map(client_py)
    tree = _parse(service_py)
    out: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr not in verb_map:
            continue
        receiver = dotted_name(node.func.value) or ""
        if "conn" in receiver.rsplit(".", 1)[-1].lower():
            out |= verb_map[node.func.attr]
    return out


# --- layer 4: what the C++ broker handles ---------------------------------
def cpp_verbs(broker_cpp: Path = BROKER_CPP) -> set[str]:
    text = broker_cpp.read_text(errors="replace")
    out = set()
    for m in _CPP_HANDLER.finditer(text):
        out.add(m.group(1) or m.group(2))
    return out


# --- the field contract (to_message / from_message) ------------------------
def _message_fields(contract_py: Path = CONTRACT_PY) -> tuple[set[str], set[str]]:
    """(written_by_to_message, read_by_from_message) key sets."""
    tree = _parse(contract_py)
    written: set[str] = set()
    read: set[str] = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, ast.FunctionDef):
            continue
        if fn.name == "to_message":
            for node in ast.walk(fn):
                if isinstance(node, ast.Dict):
                    written |= {
                        k.value
                        for k in node.keys
                        if isinstance(k, ast.Constant) and isinstance(k.value, str)
                    }
        elif fn.name == "from_message":
            for node in ast.walk(fn):
                # body["key"] subscripts
                if (
                    isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "body"
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                ):
                    read.add(node.slice.value)
                # body.get("key", ...) defaults
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "body"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    read.add(node.args[0].value)
    return written, read


# --- the check -------------------------------------------------------------
def check_contract(
    contract_py: Path = CONTRACT_PY,
    client_py: Path = CLIENT_PY,
    service_py: Path = SERVICE_PY,
    broker_cpp: Path = BROKER_CPP,
) -> list[Violation]:
    out: list[Violation] = []

    def v(rule: str, path: Path, line: int, msg: str) -> None:
        out.append(
            Violation(rule=rule, path=str(path), line=line, col=1, message=msg)
        )

    canon, canon_line = canonical_verbs(contract_py)
    if not canon:
        v(
            RULE_VERBS,
            contract_py,
            1,
            "BROKER_PROTOCOL_VERBS not found: the canonical verb set must "
            "live in cluster/contract.py",
        )
        return out

    client = client_verbs(client_py)
    cpp = cpp_verbs(broker_cpp)
    service = service_verbs(service_py, client_py)

    def diff(missing_from: str, path: Path, line: int, have: set[str], want: set[str]) -> None:
        for verb in sorted(want - have):
            v(
                RULE_VERBS,
                path,
                line,
                f"verb {verb!r} is in the canonical set "
                f"(cluster/contract.py) but missing from {missing_from}",
            )

    # canonical <-> client, both directions
    diff("the Python client (broker_client.py)", client_py, 1, client, canon)
    for verb in sorted(client - canon):
        v(
            RULE_VERBS,
            contract_py,
            canon_line,
            f"broker_client.py sends verb {verb!r} that is not in "
            "BROKER_PROTOCOL_VERBS — add it to the canonical set",
        )
    # canonical <-> C++ broker, both directions
    diff("the C++ handler chain (native/broker/broker.cpp)", broker_cpp, 1, cpp, canon)
    for verb in sorted(cpp - canon):
        v(
            RULE_VERBS,
            contract_py,
            canon_line,
            f"broker.cpp handles verb {verb!r} that is not in "
            "BROKER_PROTOCOL_VERBS — dead handler or missing canon entry",
        )
    # supervisor layer must stay inside the canon
    for verb in sorted(service - canon):
        v(
            RULE_VERBS,
            service_py,
            1,
            f"broker_service.py exercises verb {verb!r} that is not in "
            "BROKER_PROTOCOL_VERBS",
        )

    # field contract
    written, read = _message_fields(contract_py)
    if written or read:
        for key in sorted((written - _ENVELOPE_FIELDS) - read):
            v(
                RULE_FIELDS,
                contract_py,
                1,
                f"to_message writes field {key!r} that from_message never "
                "reads — receiver-side drift",
            )
        for key in sorted(read - written):
            v(
                RULE_FIELDS,
                contract_py,
                1,
                f"from_message reads field {key!r} that to_message never "
                "writes — sender-side drift",
            )
    return out
