"""The DLC4xx JAX/SPMD trace-safety rules (gated: ``dlcfn lint --sharding``).

Bench sat at ~0.30 MFU for three rounds with the multi-step path losing
to single-step, and none of DLC0xx/1xx/2xx/3xx could say why: the
classic step-loop killers — silent retraces, missing buffer donation,
host syncs inside the loop, impure traced code — live in the *JAX
dispatch layer*, invisible to lockset or protocol checks.  DLC4xx makes
that layer statically checkable, the way DLC2xx did for threads:

DLC400 traced-code impurity     DLC403 mesh-axis consistency
DLC401 undonated train-state jit DLC404 host sync in the step loop
DLC402 retrace hazards           DLC405 nested jit / device_put in trace

Like every gated pass the rules are conservative: each matcher anchors
on the specific shape of the bug.  The static half is paired with a
dynamic compile-audit sentinel (analysis/compile_audit.py) that runs the
real trainer and *proves* steady-state zero-retrace; its findings use
the reserved ids DLC410/DLC411 so both halves share one baseline
ratchet.

Scope: the compute tree (``train/``, ``models/``, ``ops/``, ``bench.py``)
— the only places jit/pjit/shard_map call sites live.

What "traced" means here
------------------------
A function is considered traced when the file shows it entering the JAX
tracer: jit/pjit/pmap-decorated, passed by name to a jit wrapper or to a
tracing transform (``lax.scan``/``while_loop``/``fori_loop``/``cond``,
``vmap``/``grad``/``checkpoint``/``shard_map``), nested inside a traced
function, or called by bare name from one.  This is a same-file closure
— deliberately: cross-module call graphs would need whole-program
resolution and the false-positive risk that comes with it.

DLC403's ground truth is cross-module, like the DLC1xx broker checker:
the canonical axis vocabulary is machine-read from ``AXIS_ORDER`` in
``parallel/mesh.py`` (itself validated against ``ClusterContract``
topology at mesh build time), so a spec axis that drifts from the
cluster contract fails lint, not a 3am pod run.
"""

from __future__ import annotations

import ast
from functools import lru_cache
from pathlib import Path
from typing import Iterator

from deeplearning_cfn_tpu.analysis.core import (
    FileContext,
    Rule,
    Violation,
    call_name,
    dotted_name,
    keyword,
    has_keyword,
    register,
    walk_skipping_nested_functions,
)

GATE = "sharding"
RULE_IDS = ("DLC400", "DLC401", "DLC402", "DLC403", "DLC404", "DLC405")

# Reserved for the dynamic compile-audit sentinel (analysis/compile_audit.py):
# same namespace, same baseline ratchet, but findings come from running the
# real trainer rather than from this AST pass.
AUDIT_RULE_RETRACE = "DLC410"
AUDIT_RULE_DONATION = "DLC411"
AUDIT_RULE_IDS = (AUDIT_RULE_RETRACE, AUDIT_RULE_DONATION)

_COMPUTE_DIRS = ("train", "models", "ops", "serve")


def _applies_compute_paths(path: Path) -> bool:
    return path.name == "bench.py" or any(d in path.parts for d in _COMPUTE_DIRS)


# --- traced-function discovery ---------------------------------------------

# Names that wrap a callable into a compiled function.  pmap counts for
# traced-ness even though the repo idiom is jit+shardings.
_JIT_WRAPPERS = (
    "jax.jit",
    "jit",
    "pjit",
    "pjit.pjit",
    "jax.experimental.pjit.pjit",
    "jax.pmap",
    "pmap",
)
# Core jit spellings for rules about the jit call itself (DLC401/402/405).
_JIT_CORE = ("jax.jit", "jit", "pjit", "pjit.pjit", "jax.experimental.pjit.pjit")

# transform dotted name -> positional indices holding traced callables.
_TRACED_CALLABLE_POSITIONS: dict[str, tuple[int, ...]] = {
    "jax.lax.scan": (0,),
    "lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "lax.cond": (1, 2),
    "jax.vmap": (0,),
    "vmap": (0,),
    "jax.grad": (0,),
    "grad": (0,),
    "jax.value_and_grad": (0,),
    "value_and_grad": (0,),
    "jax.checkpoint": (0,),
    "checkpoint": (0,),
    "jax.remat": (0,),
    "remat": (0,),
    "shard_map": (0,),
    "compat.shard_map": (0,),
    "jax.experimental.shard_map.shard_map": (0,),
}


def _is_jit_expr(expr: ast.AST) -> bool:
    name = dotted_name(expr)
    if name in _JIT_WRAPPERS:
        return True
    if isinstance(expr, ast.Call):
        fname = call_name(expr)
        if fname in _JIT_WRAPPERS:
            return True  # decorator factory form: @jax.jit(...)
        if fname in ("partial", "functools.partial") and expr.args:
            return _is_jit_expr(expr.args[0])
    return False


def _jit_decorated(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    return any(_is_jit_expr(d) for d in fn.decorator_list)


_FnDef = ast.FunctionDef | ast.AsyncFunctionDef


def _defs_by_name(tree: ast.Module) -> dict[str, list[_FnDef]]:
    out: dict[str, list[_FnDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def traced_functions(ctx: FileContext) -> dict[_FnDef, str]:
    """Every function def the file shows entering the tracer -> why.

    Cached on the FileContext so the six rules share one computation.
    """
    cached = getattr(ctx, "_dlc4_traced", None)
    if cached is not None:
        return cached
    defs = _defs_by_name(ctx.tree)
    traced: dict[_FnDef, str] = {}
    stack: list[_FnDef] = []

    def mark(fn: _FnDef, why: str) -> None:
        if fn not in traced:
            traced[fn] = why
            stack.append(fn)

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _jit_decorated(node):
                mark(node, "jit-decorated")
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if name in _JIT_WRAPPERS:
                positions: tuple[int, ...] = (0,)
            else:
                positions = _TRACED_CALLABLE_POSITIONS.get(name or "", ())
            for pos in positions:
                if pos < len(node.args) and isinstance(node.args[pos], ast.Name):
                    for fn in defs.get(node.args[pos].id, ()):
                        mark(fn, f"passed to {name}")

    # Transitive closure: nested defs and same-file bare-name calls from
    # traced code run under the same trace.
    while stack:
        fn = stack.pop()
        for node in ast.walk(fn):
            if (
                node is not fn
                and isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            ):
                mark(node, f"nested in traced {fn.name}")
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                for callee in defs.get(node.func.id, ()):
                    mark(callee, f"called from traced {fn.name}")

    ctx._dlc4_traced = traced  # type: ignore[attr-defined]
    return traced


# --- DLC400: traced-code impurity ------------------------------------------
# Host-side effects inside traced code do not "run every step" — they run
# ONCE, at trace time, and their results are baked into the compiled
# program as constants.  A wall-clock read becomes a frozen timestamp, an
# np.random draw becomes the same "random" numbers every step, and a
# `global` write silently never happens again.  All three have the same
# deadly property: the code *looks* like it works.

_WALL_CLOCK_CALLS = (
    "time.time",
    "time.time_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.process_time",
    "datetime.now",
    "datetime.utcnow",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
)
_HOST_RANDOM_PREFIXES = ("np.random.", "numpy.random.", "random.")


def _check_traced_impurity(tree: ast.Module, ctx: FileContext) -> Iterator[Violation]:
    for fn, why in traced_functions(ctx).items():
        for node in walk_skipping_nested_functions(fn.body):
            if isinstance(node, ast.Global):
                names = ", ".join(node.names)
                yield ctx.violation(
                    "DLC400",
                    node,
                    f"`global {names}` inside traced {fn.name}() ({why}): "
                    "the write happens once at trace time and silently "
                    "never again; thread values through arguments/returns",
                )
                continue
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            if name in _WALL_CLOCK_CALLS:
                yield ctx.violation(
                    "DLC400",
                    node,
                    f"{name}() inside traced {fn.name}() ({why}) is baked "
                    "in as a trace-time constant — every compiled step "
                    "replays the same timestamp; measure host-side around "
                    "the dispatch",
                )
            elif any(name.startswith(p) for p in _HOST_RANDOM_PREFIXES):
                yield ctx.violation(
                    "DLC400",
                    node,
                    f"{name}() inside traced {fn.name}() ({why}) freezes "
                    "host randomness into the compiled program (identical "
                    "draws every step); thread a jax.random key instead",
                )


register(
    Rule(
        id="DLC400",
        name="traced-impurity",
        doc="no wall-clock/np.random/global-write inside traced functions",
        check=_check_traced_impurity,
        applies=_applies_compute_paths,
        gate=GATE,
    )
)

# --- DLC401: train-state jit without donation ------------------------------
# DLC008 (ungated) catches the two exact trainer shapes it was written
# for: a jit-DECORATED fn whose first arg is literally named `state`, and
# the call form carrying both in_shardings and out_shardings.  DLC401
# widens to what slips past it: call-form `jax.jit(step_fn)` where
# `step_fn`'s def (resolved same-file) has a train-state-typed first
# parameter — by name (`state`/`train_state`) or by annotation ending in
# `State` — without donate_argnums/donate_argnames.  Eval-style sites are
# exempt by name: a read-only jit must NOT donate (it would delete the
# caller's state).

_DONATE_KWARGS = ("donate_argnums", "donate_argnames")
_STATE_PARAM_NAMES = ("state", "train_state")
_EVAL_NAME_MARKERS = ("eval", "infer", "predict")


def _annotation_is_state(arg: ast.arg) -> bool:
    ann = arg.annotation
    if ann is None:
        return False
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        text = ann.value
    else:
        text = dotted_name(ann) or ""
    return text.rsplit(".", 1)[-1].endswith("State")


def _state_typed_first_param(fn: _FnDef) -> ast.arg | None:
    args = fn.args.args
    if args and args[0].arg == "self":
        args = args[1:]
    if not args:
        return None
    first = args[0]
    if first.arg in _STATE_PARAM_NAMES or _annotation_is_state(first):
        return first
    return None


def _eval_like(name: str) -> bool:
    low = name.lower()
    return any(marker in low for marker in _EVAL_NAME_MARKERS)


def _check_undonated_state_jit(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            first = _state_typed_first_param(node)
            if first is None or _eval_like(node.name) or not _jit_decorated(node):
                continue
            if any(
                isinstance(d, ast.Call) and _is_jit_expr(d) and has_keyword(d, *_DONATE_KWARGS)
                for d in node.decorator_list
            ):
                continue
            if first.arg == "state":
                continue  # exact DLC008 decorator shape — one finding, not two
            yield ctx.violation(
                "DLC401",
                node,
                f"jit-decorated {node.name}() threads a train-state first "
                f"arg ({first.arg!r}) without donate_argnums: both state "
                "copies stay live across the update; donate the input "
                "state (read-only eval jits are exempt by name)",
            )
        elif isinstance(node, ast.Call) and call_name(node) in _JIT_CORE:
            if not node.args or not isinstance(node.args[0], ast.Name):
                continue
            if has_keyword(node, *_DONATE_KWARGS):
                continue
            if has_keyword(node, "in_shardings") and has_keyword(node, "out_shardings"):
                continue  # exact DLC008 call shape — one finding, not two
            fname = node.args[0].id
            if _eval_like(fname):
                continue
            enclosing = ctx.enclosing_function(node)
            if enclosing is not None and _eval_like(enclosing.name):
                continue
            for fn in _defs_by_name(tree).get(fname, ()):
                first = _state_typed_first_param(fn)
                if first is not None and not _eval_like(fn.name):
                    yield ctx.violation(
                        "DLC401",
                        node,
                        f"jax.jit({fname}) threads a train-state first arg "
                        f"({first.arg!r}) without donate_argnums: both "
                        "state copies stay live across the update; donate "
                        "the input state (read-only eval jits are exempt "
                        "by name)",
                    )
                    break


register(
    Rule(
        id="DLC401",
        name="undonated-train-state-jit",
        doc="train-state-typed jits must donate (eval sites exempt)",
        check=_check_undonated_state_jit,
        applies=_applies_compute_paths,
        gate=GATE,
    )
)

# --- DLC402: retrace hazards ------------------------------------------------
# jit keys its cache on the *Python value* of non-array arguments: a bool
# flag retraces on every flip, an int used in `if`/`range` retraces per
# distinct value — silently, per call, which is exactly the failure mode
# behind "multi-step loses to single-step".  The fix is one kwarg
# (static_argnums/static_argnames), so the rule insists on it.  It also
# flags branching on an f-string under trace: the string formats static
# shape info at trace time, so the branch is frozen forever.


def _jit_sites(tree: ast.Module) -> Iterator[tuple[_FnDef, ast.Call | None]]:
    """(function def, jit call carrying its kwargs) for every jit root."""
    defs = _defs_by_name(tree)
    seen: set[_FnDef] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                if _is_jit_expr(d):
                    if node not in seen:
                        seen.add(node)
                        yield node, d if isinstance(d, ast.Call) else None
                    break
        elif isinstance(node, ast.Call) and call_name(node) in _JIT_CORE:
            if node.args and isinstance(node.args[0], ast.Name):
                for fn in defs.get(node.args[0].id, ()):
                    if fn not in seen:
                        seen.add(fn)
                        yield fn, node


def _static_decls(call: ast.Call | None) -> tuple[set[str], set[int]]:
    names: set[str] = set()
    nums: set[int] = set()
    if call is None:
        return names, nums
    kw = keyword(call, "static_argnames")
    if kw is not None:
        for n in ast.walk(kw.value):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                names.add(n.value)
    kw = keyword(call, "static_argnums")
    if kw is not None:
        for n in ast.walk(kw.value):
            if isinstance(n, ast.Constant) and isinstance(n.value, int):
                nums.add(n.value)
    return names, nums


def _defaults_by_arg(fn: _FnDef) -> dict[str, ast.expr]:
    out: dict[str, ast.expr] = {}
    pos = fn.args.args
    for arg, default in zip(pos[len(pos) - len(fn.args.defaults) :], fn.args.defaults):
        out[arg.arg] = default
    for arg, default in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if default is not None:
            out[arg.arg] = default
    return out


def _annotation_terminal(arg: ast.arg) -> str | None:
    ann = arg.annotation
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.rsplit(".", 1)[-1]
    name = dotted_name(ann)
    return name.rsplit(".", 1)[-1] if name else None


def _used_in_python_control(fn: _FnDef, pname: str) -> bool:
    def names_param(sub: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Name) and n.id == pname for n in ast.walk(sub)
        )

    for node in walk_skipping_nested_functions(fn.body):
        if isinstance(node, (ast.If, ast.While)) and names_param(node.test):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "range"
            and any(names_param(a) for a in node.args)
        ):
            return True
    return False


def _check_retrace_hazards(tree: ast.Module, ctx: FileContext) -> Iterator[Violation]:
    for fn, call in _jit_sites(tree):
        static_names, static_nums = _static_decls(call)
        defaults = _defaults_by_arg(fn)
        args = fn.args.args
        if args and args[0].arg == "self":
            args = args[1:]
        for idx, arg in enumerate(args):
            if arg.arg in static_names or idx in static_nums:
                continue
            ann = _annotation_terminal(arg)
            default = defaults.get(arg.arg)
            is_bool = ann == "bool" or (
                isinstance(default, ast.Constant) and isinstance(default.value, bool)
            )
            is_int = not is_bool and (
                ann == "int"
                or (
                    isinstance(default, ast.Constant)
                    and type(default.value) is int
                )
            )
            if is_bool:
                yield ctx.violation(
                    "DLC402",
                    arg,
                    f"{fn.name}() parameter {arg.arg!r} is a Python bool "
                    "entering jit without static_argnums/static_argnames: "
                    "every flag flip retraces silently; declare it static",
                )
            elif is_int and _used_in_python_control(fn, arg.arg):
                yield ctx.violation(
                    "DLC402",
                    arg,
                    f"{fn.name}() parameter {arg.arg!r} is a Python int "
                    "driving `if`/`range` under trace without "
                    "static_argnums: each distinct value retraces "
                    "silently; declare it static (or lax-ify the loop)",
                )
    for fn, why in traced_functions(ctx).items():
        for node in walk_skipping_nested_functions(fn.body):
            if isinstance(node, ast.If) and any(
                isinstance(n, ast.JoinedStr) for n in ast.walk(node.test)
            ):
                yield ctx.violation(
                    "DLC402",
                    node,
                    f"if-test built from an f-string inside traced "
                    f"{fn.name}() ({why}): the string formats static "
                    "shape info at trace time, so the branch is frozen "
                    "into the compiled program; branch on the "
                    "values/shapes directly",
                )


register(
    Rule(
        id="DLC402",
        name="retrace-hazard",
        doc="python scalars/bools entering jit must be declared static",
        check=_check_retrace_hazards,
        applies=_applies_compute_paths,
        gate=GATE,
    )
)

# --- DLC403: mesh-axis consistency ------------------------------------------
# A PartitionSpec axis name is a stringly-typed foreign key into the mesh
# topology.  A typo ('fspd', 'data') does not error — jit treats the
# unknown axis as unsharded and the layout silently degrades to
# replication.  The canonical vocabulary is machine-read from AXIS_ORDER
# in parallel/mesh.py (validated against ClusterContract topology at mesh
# build), so this check is cross-module ground truth, not a hardcoded
# list in the linter.

_MESH_PY = Path(__file__).resolve().parents[1] / "parallel" / "mesh.py"
_AXIS_KWARGS = ("axis_name", "axis_names")


@lru_cache(maxsize=8)
def canonical_mesh_axes(mesh_py: str | None = None) -> tuple[str, ...]:
    """Extract AXIS_ORDER from parallel/mesh.py by AST, not import."""
    path = Path(mesh_py) if mesh_py is not None else _MESH_PY
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        for target in targets:
            if isinstance(target, ast.Name) and target.id == "AXIS_ORDER":
                value = node.value
                if isinstance(value, (ast.Tuple, ast.List)):
                    axes = tuple(
                        e.value
                        for e in value.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    )
                    if axes:
                        return axes
    raise ValueError(f"could not extract AXIS_ORDER from {path}")


def _spec_call(name: str | None) -> bool:
    if name is None:
        return False
    terminal = name.rsplit(".", 1)[-1]
    return terminal in ("P", "PartitionSpec")


def _str_constants(node: ast.AST) -> Iterator[ast.Constant]:
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n


def _check_mesh_axis_consistency(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    try:
        canonical = set(canonical_mesh_axes())
    except (OSError, ValueError, SyntaxError) as e:
        yield ctx.violation(
            "DLC403",
            tree,
            f"cannot machine-read AXIS_ORDER from parallel/mesh.py ({e}); "
            "the mesh-axis vocabulary must stay statically extractable",
        )
        return
    shown = "/".join(sorted(canonical))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        sources: list[ast.AST] = []
        if _spec_call(call_name(node)):
            sources.extend(node.args)
        sources.extend(
            kw.value for kw in node.keywords if kw.arg in _AXIS_KWARGS
        )
        for source in sources:
            for const in _str_constants(source):
                if const.value not in canonical:
                    yield ctx.violation(
                        "DLC403",
                        const,
                        f"axis {const.value!r} does not resolve against "
                        f"the mesh topology axes ({shown}) machine-read "
                        "from parallel/mesh.py AXIS_ORDER: an unknown "
                        "axis silently degrades the layout to replication",
                    )


register(
    Rule(
        id="DLC403",
        name="mesh-axis-consistency",
        doc="PartitionSpec/shard_map axis names must exist in AXIS_ORDER",
        check=_check_mesh_axis_consistency,
        applies=_applies_compute_paths,
        gate=GATE,
    )
)

# --- DLC404: host sync in the step loop -------------------------------------
# DLC003 guards the inside of jitted functions; this rule guards the HOST
# side: the loop that dispatches steps.  An unguarded .item()/float()/
# device_get/block_until_ready in the loop body serializes host and
# device every iteration — the async dispatch queue drains, MFU caps at
# whatever the host round-trip allows.  The repo idiom (train/trainer.py
# fit(), bench.py) is to batch readbacks behind a periodic `if` (sync
# boundary), so anything under an `if` inside the loop is deliberately
# exempt.

_SYNC_CALL_NAMES = (
    "jax.device_get",
    "device_get",
    "jax.block_until_ready",
    "block_until_ready",
)


def _is_step_loop(loop: ast.For | ast.While, ctx: FileContext) -> bool:
    fn = ctx.enclosing_function(loop)
    if fn is not None and fn.name == "fit":
        return True
    for node in walk_skipping_nested_functions(loop.body):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name is not None and "step" in name.rsplit(".", 1)[-1].lower():
                return True
    return False


def _guarded_or_rescoped(node: ast.AST, loop: ast.AST, ctx: FileContext) -> bool:
    cur = ctx.parents.get(node)
    while cur is not None and cur is not loop:
        if isinstance(cur, ast.If):
            return True  # periodic sync boundary — the sanctioned idiom
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return True  # different scope; not executed per iteration here
        cur = ctx.parents.get(cur)
    return False


def _sync_shape(node: ast.Call) -> str | None:
    name = call_name(node)
    if name in _SYNC_CALL_NAMES:
        return f"{name}()"
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "item"
        and not node.args
    ):
        return ".item()"
    if (
        isinstance(node.func, ast.Name)
        and node.func.id == "float"
        and len(node.args) == 1
        and not isinstance(node.args[0], ast.Constant)
    ):
        return "float(<device value>)"
    return None


def _check_step_loop_host_sync(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    reported: set[int] = set()
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        if not _is_step_loop(loop, ctx):
            continue
        for stmt in loop.body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call) or id(node) in reported:
                    continue
                what = _sync_shape(node)
                if what is None or _guarded_or_rescoped(node, loop, ctx):
                    continue
                reported.add(id(node))
                yield ctx.violation(
                    "DLC404",
                    node,
                    f"{what} runs unguarded on every iteration of a step "
                    "loop: it drains the async dispatch queue and "
                    "serializes host with device; batch readbacks behind "
                    "a periodic `if` sync boundary (fit()'s sync_every "
                    "idiom)",
                )


register(
    Rule(
        id="DLC404",
        name="step-loop-host-sync",
        doc="no unguarded host sync inside the step-dispatch loop",
        check=_check_step_loop_host_sync,
        applies=_applies_compute_paths,
        gate=GATE,
    )
)

# --- DLC405: nested jit / device_put under trace ----------------------------
# jit inside jit does not compose the way it reads: the inner wrapper
# re-traces on every outer trace and fragments the compilation cache
# (each outer variant compiles its own inner copy).  device_put under
# trace is a no-op at best (placement is the sharding system's job) and a
# host round-trip at worst.  Both are hoist-one-line fixes.

_DEVICE_PUT_CALLS = (
    "jax.device_put",
    "device_put",
    "device_put_tree",
    "jax.device_put_replicated",
    "jax.device_put_sharded",
)


def _check_nested_dispatch(tree: ast.Module, ctx: FileContext) -> Iterator[Violation]:
    for fn, why in traced_functions(ctx).items():
        for node in walk_skipping_nested_functions(fn.body):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and _jit_decorated(node)
            ):
                yield ctx.violation(
                    "DLC405",
                    node,
                    f"jit-decorated {node.name}() defined inside traced "
                    f"{fn.name}() ({why}): the inner jit re-traces per "
                    "outer trace and fragments the compilation cache; "
                    "hoist the wrapper out of the traced scope",
                )
                continue
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in _JIT_CORE:
                yield ctx.violation(
                    "DLC405",
                    node,
                    f"{name}() called inside traced {fn.name}() ({why}): "
                    "nested jit re-traces per outer trace and fragments "
                    "the compilation cache; hoist the wrapper to "
                    "module/init scope",
                )
            elif name in _DEVICE_PUT_CALLS:
                yield ctx.violation(
                    "DLC405",
                    node,
                    f"{name}() inside traced {fn.name}() ({why}) is a "
                    "no-op at best under trace (placement belongs to "
                    "shardings) and a host round-trip at worst; place "
                    "inputs before dispatch",
                )


register(
    Rule(
        id="DLC405",
        name="nested-dispatch-under-trace",
        doc="no jit()/device_put() inside already-traced code",
        check=_check_nested_dispatch,
        applies=_applies_compute_paths,
        gate=GATE,
    )
)
