"""The dynamic replay sentinel: byte-determinism, proven by running twice.

The DLC6xx static rules (analysis/determinism.py) catch the *source
patterns* that tend to break per-seed determinism; this module measures
the property itself.  It runs every registered chaos scenario and both
fleet soaks (``soak_failover``, ``soak_fleet``) **twice per seed,
in-process**, canonicalizes each report to sorted-key compact JSON, and
diffs the bytes.  Any mismatch becomes a DLC610 violation carrying the
first-divergence path (``$.details.rounds[3].detected`` style), flowing
through the same suppression-baseline ratchet as the DLC41x compile
audit and DLC51x comms audit (scripts/lint_baseline.json, namespace-
scoped via ``runner.apply_audit_baseline``), and results are journaled
to the flight recorder as ``replay_audit`` events.

Double-running in one process is deliberately the *weakest* replay (same
PYTHONHASHSEED, same import order, same allocator state): anything that
diverges here is unconditionally broken, with no environmental excuse —
the cheapest-to-debug form of the failure.  Cross-process and
cross-machine stability layer on top of this gate, not instead of it.

Canonicalization never sorts *data* — only dict keys, which Python
already guarantees an order for.  Sorting lists here would hide exactly
the enumeration-order bugs DLC600/DLC602 exist to catch; a list whose
order flips between runs must surface as a divergence.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Iterable, Sequence

from deeplearning_cfn_tpu.analysis.core import Violation
from deeplearning_cfn_tpu.analysis.determinism import AUDIT_RULE_REPLAY

REPO_ROOT = Path(__file__).resolve().parents[2]
# Findings anchor on the file that owns the replayed program (baseline
# key is (rule, repo-relative path, message) — same contract as DLC41x).
SCENARIO_AUDITED_FILE = (
    REPO_ROOT / "deeplearning_cfn_tpu" / "chaos" / "scenarios.py"
)
SOAK_AUDITED_FILE = (
    REPO_ROOT / "deeplearning_cfn_tpu" / "analysis" / "schedules.py"
)

DEFAULT_SEEDS = (0,)


def _jsonable(obj: Any) -> Any:
    """Canonical fallback for non-JSON leaves (numpy scalars, Paths).

    ``str()`` — not a sort, not a normalization: if a leaf's repr is
    unstable (a set, an object with a default repr carrying ``id()``),
    the instability must reach the byte diff, not be papered over.
    """
    item = getattr(obj, "item", None)
    if callable(item):
        return item()
    return str(obj)


def canonicalize(report: Any) -> bytes:
    """One report -> canonical bytes: sorted keys, compact separators.

    Two calls on equal structures always agree, so every byte of
    difference between two runs is a difference in the *data*.
    """
    return json.dumps(
        report,
        sort_keys=True,
        separators=(",", ":"),
        default=_jsonable,
    ).encode()


def first_divergence(a: Any, b: Any, path: str = "$") -> str | None:
    """JSONPath-ish pointer to the first leaf where two structures differ.

    Dicts are walked in sorted-key order (matching :func:`canonicalize`),
    lists positionally; a missing key or a length mismatch is itself the
    divergence.  Returns None when the structures are equal.
    """
    if type(a) is not type(b) and not (
        isinstance(a, (int, float)) and isinstance(b, (int, float))
    ):
        return path
    if isinstance(a, dict):
        for k in sorted(set(a) | set(b)):
            sub = f"{path}.{k}"
            if k not in a or k not in b:
                return sub
            hit = first_divergence(a[k], b[k], sub)
            if hit is not None:
                return hit
        return None
    if isinstance(a, list):
        for i, (x, y) in enumerate(zip(a, b)):
            hit = first_divergence(x, y, f"{path}[{i}]")
            if hit is not None:
                return hit
        if len(a) != len(b):
            return f"{path}[{min(len(a), len(b))}]"
        return None
    return None if a == b else path


@dataclass(frozen=True)
class ReplayCase:
    """One replayable program: a name, a kind, and seed -> report."""

    name: str
    kind: str  # "scenario" | "soak"
    run: Callable[[int], Any]
    audited_file: str


def default_cases(
    scenarios: Iterable[str] | None = None, soaks: bool = True
) -> list[ReplayCase]:
    """Every registered chaos scenario (sorted) plus both fleet soaks."""
    from deeplearning_cfn_tpu.chaos.scenarios import SCENARIOS, run_scenario

    names = sorted(SCENARIOS) if scenarios is None else list(scenarios)

    def _scenario_case(name: str) -> ReplayCase:
        return ReplayCase(
            name=name,
            kind="scenario",
            run=lambda seed: run_scenario(name, seed).to_dict(),
            audited_file=str(SCENARIO_AUDITED_FILE),
        )

    cases = [_scenario_case(n) for n in names]
    if soaks:
        from deeplearning_cfn_tpu.analysis.schedules import (
            soak_failover,
            soak_fleet,
        )

        cases.append(
            ReplayCase(
                name="soak_failover",
                kind="soak",
                run=lambda seed: soak_failover(seed=seed),
                audited_file=str(SOAK_AUDITED_FILE),
            )
        )
        cases.append(
            ReplayCase(
                name="soak_fleet",
                kind="soak",
                run=lambda seed: soak_fleet(seed=seed),
                audited_file=str(SOAK_AUDITED_FILE),
            )
        )
    return cases


@dataclass(frozen=True)
class CaseReplay:
    """One (case, seed) double-run outcome."""

    name: str
    kind: str
    seed: int
    identical: bool
    nbytes: int
    divergence: str | None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "seed": self.seed,
            "identical": self.identical,
            "nbytes": self.nbytes,
            "divergence": self.divergence,
        }


@dataclass
class ReplayAuditReport:
    replays: list[CaseReplay]
    violations: list[Violation]
    seeds: tuple[int, ...]

    def to_dict(self) -> dict:
        return {
            "replays": [r.to_dict() for r in self.replays],
            "violations": [v.to_dict() for v in self.violations],
            "seeds": list(self.seeds),
            "cases": len({r.name for r in self.replays}),
            "divergent": sorted(
                {r.name for r in self.replays if not r.identical}
            ),
            "clean": not self.violations,
        }


def _violation_for(case: ReplayCase, replay: CaseReplay) -> Violation:
    return Violation(
        rule=AUDIT_RULE_REPLAY,
        path=case.audited_file,
        line=1,
        col=1,
        message=(
            f"replay divergence: {case.kind} '{case.name}' at seed "
            f"{replay.seed} produced different report bytes across two "
            "in-process runs (first divergence at "
            f"{replay.divergence}) — the per-seed determinism contract "
            "every chaos gate and soak asserts is broken (replay-audit "
            "sentinel; see docs/STATIC_ANALYSIS.md replay runbook)"
        ),
    )


def run_replay_audit(
    cases: Sequence[ReplayCase] | None = None,
    seeds: Sequence[int] = DEFAULT_SEEDS,
    journal: bool = True,
) -> ReplayAuditReport:
    """Double-run every case at every seed and diff canonical bytes.

    Pure in-process re-execution — the scenarios and soaks all run on
    virtual clocks and seeded RNGs, so the audit's wall time is just two
    passes of the programs themselves.
    """
    case_list = default_cases() if cases is None else list(cases)
    replays: list[CaseReplay] = []
    violations: list[Violation] = []
    for case in case_list:
        for seed in seeds:
            first = canonicalize(case.run(seed))
            second = canonicalize(case.run(seed))
            identical = first == second
            divergence = None
            if not identical:
                divergence = (
                    first_divergence(json.loads(first), json.loads(second))
                    or "$"
                )
            replay = CaseReplay(
                name=case.name,
                kind=case.kind,
                seed=int(seed),
                identical=identical,
                nbytes=len(first),
                divergence=divergence,
            )
            replays.append(replay)
            if not identical:
                violations.append(_violation_for(case, replay))
    report = ReplayAuditReport(
        replays=replays, violations=violations, seeds=tuple(seeds)
    )
    if journal:
        from deeplearning_cfn_tpu.obs.recorder import get_recorder

        get_recorder().record(
            "replay_audit",
            clean=not violations,
            cases=len(case_list),
            seeds=[int(s) for s in seeds],
            divergent=sorted({r.name for r in replays if not r.identical}),
        )
    return report
