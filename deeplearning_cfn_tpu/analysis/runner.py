"""File discovery + orchestration for ``python -m deeplearning_cfn_tpu.cli lint``."""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, Iterator

# Importing rule modules registers them in core.FILE_RULES.
import deeplearning_cfn_tpu.analysis.collectives as collectives_rules
import deeplearning_cfn_tpu.analysis.concurrency as concurrency_rules
import deeplearning_cfn_tpu.analysis.determinism as determinism_rules
import deeplearning_cfn_tpu.analysis.rules  # noqa: F401
import deeplearning_cfn_tpu.analysis.sharding as sharding_rules
from deeplearning_cfn_tpu.analysis import contract_check, protocol
from deeplearning_cfn_tpu.analysis.core import FILE_RULES, Violation, lint_source

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_TARGETS = ("deeplearning_cfn_tpu", "scripts", "bench.py")
DEFAULT_BASELINE = REPO_ROOT / "scripts" / "lint_baseline.json"
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist"}

PROTOCOL_RULE_IDS = (
    protocol.RULE_REQUEST,
    protocol.RULE_REPLY,
    protocol.RULE_FRAME,
    protocol.RULE_LIFECYCLE,
)

# Rules only the dynamic sentinel stages (scripts/compile_audit.py,
# scripts/comms_audit.py, scripts/replay_audit.py) can produce.  Their
# baseline entries share scripts/lint_baseline.json with the static
# pass, so static lint must never call them stale — it cannot observe
# their findings at all.
DYNAMIC_AUDIT_RULE_IDS = (
    tuple(sharding_rules.AUDIT_RULE_IDS)
    + tuple(collectives_rules.AUDIT_RULE_IDS)
    + tuple(determinism_rules.AUDIT_RULE_IDS)
)


def discover(targets: Iterable[str | Path], root: Path = REPO_ROOT) -> Iterator[Path]:
    for target in targets:
        p = Path(target)
        if not p.is_absolute():
            p = root / p
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            yield from sorted(
                f
                for f in p.rglob("*.py")
                if not any(part in _SKIP_DIRS for part in f.parts)
            )


def run_lint(
    targets: Iterable[str | Path] | None = None,
    select: set[str] | None = None,
    root: Path = REPO_ROOT,
    contract: bool = True,
    concurrency: bool = False,
    protocol_pass: bool = False,
    sharding: bool = False,
    comms: bool = False,
    determinism: bool = False,
) -> list[Violation]:
    """Lint the given targets (repo defaults when None).

    ``select`` limits per-file rules to specific ids; the DLC1xx contract
    checker runs unless ``contract=False`` or a ``select`` set excludes
    both DLC100 and DLC101.

    The DLC2xx concurrency rules are gated: they run when
    ``concurrency=True`` or a ``select`` names them, never implicitly.
    Likewise the DLC3xx protocol/lifecycle checkers run when
    ``protocol_pass=True`` or selected, the DLC4xx trace-safety rules
    when ``sharding=True`` or selected, the DLC5xx comms/memory rules
    when ``comms=True`` or selected, and the DLC6xx determinism rules
    when ``determinism=True`` or selected.
    """
    effective_select = select
    gated_ids: set[str] = set()
    if concurrency:
        gated_ids |= set(concurrency_rules.RULE_IDS)
    if sharding:
        gated_ids |= set(sharding_rules.RULE_IDS)
    if comms:
        gated_ids |= set(collectives_rules.RULE_IDS)
    if determinism:
        gated_ids |= set(determinism_rules.RULE_IDS)
    if select is None and gated_ids:
        # Widen the per-file selection to "every ungated rule plus the
        # requested gated passes" — an explicit select is what lets gated
        # rules through core.lint_source.
        effective_select = {
            rule.id for rule in FILE_RULES.values() if rule.gate is None
        } | gated_ids

    out: list[Violation] = []
    for path in discover(targets if targets is not None else DEFAULT_TARGETS, root):
        out.extend(lint_source(path, select=effective_select))

    run_contract = contract and (
        select is None or select & {contract_check.RULE_VERBS, contract_check.RULE_FIELDS}
    )
    if run_contract:
        contract_violations = contract_check.check_contract()
        if select is not None:
            contract_violations = [v for v in contract_violations if v.rule in select]
        out.extend(contract_violations)

    run_protocol = protocol_pass or (
        select is not None and bool(select & set(PROTOCOL_RULE_IDS))
    )
    if run_protocol:
        protocol_violations = protocol.check_protocol() + protocol.check_lifecycle()
        if select is not None:
            protocol_violations = [
                v for v in protocol_violations if v.rule in select
            ]
        out.extend(protocol_violations)

    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


# --- suppression baseline (ratchet) ------------------------------------------
#
# The baseline is a committed JSON file of (rule, repo-relative path,
# message) triples.  Findings matching an entry are suppressed; anything
# NEW fails the build; entries that no longer match anything are reported
# as stale so the file only ever shrinks (a ratchet, not a flag-flood).
# Keys deliberately omit line numbers: unrelated edits above a finding
# must not churn the baseline.


def baseline_key(violation: Violation, root: Path = REPO_ROOT) -> tuple[str, str, str]:
    p = Path(violation.path)
    try:
        rel = p.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        rel = p.as_posix()
    return (violation.rule, rel, violation.message)


def load_baseline(path: Path) -> set[tuple[str, str, str]]:
    data = json.loads(Path(path).read_text())
    entries = data.get("entries", data) if isinstance(data, dict) else data
    out: set[tuple[str, str, str]] = set()
    for entry in entries:
        out.add((entry["rule"], entry["path"], entry["message"]))
    return out


def apply_baseline(
    violations: list[Violation],
    baseline: set[tuple[str, str, str]],
    root: Path = REPO_ROOT,
) -> tuple[list[Violation], list[tuple[str, str, str]]]:
    """Split into (new findings, stale baseline entries)."""
    matched: set[tuple[str, str, str]] = set()
    fresh: list[Violation] = []
    for v in violations:
        key = baseline_key(v, root)
        if key in baseline:
            matched.add(key)
        else:
            fresh.append(v)
    stale = sorted(baseline - matched)
    return fresh, stale


def write_baseline(
    violations: list[Violation],
    path: Path,
    root: Path = REPO_ROOT,
) -> None:
    entries = sorted({baseline_key(v, root) for v in violations})
    payload = {
        "entries": [
            {"rule": rule, "path": rel, "message": message}
            for rule, rel, message in entries
        ],
    }
    Path(path).write_text(json.dumps(payload, indent=2) + "\n")


def apply_audit_baseline(
    violations: list[Violation],
    baseline_path: Path | str | None,
    rule_ids: Iterable[str],
    root: Path = REPO_ROOT,
) -> tuple[list[Violation], list[tuple[str, str, str]]]:
    """Namespace-scoped ratchet for the dynamic sentinels.

    A sentinel stage (compile-audit's DLC41x, comms-audit's DLC51x) owns
    only its own rule namespace inside the shared baseline file: entries
    for other rules belong to ``dlcfn lint`` and must be invisible here
    — otherwise every sentinel would nag about every other pass's
    suppressions as "stale".  Filters the baseline down to ``rule_ids``
    and returns the usual (fresh findings, stale entries) split.
    """
    ids = set(rule_ids)
    path = Path(baseline_path) if baseline_path is not None else DEFAULT_BASELINE
    baseline = load_baseline(path) if path.exists() else set()
    scoped = {entry for entry in baseline if entry[0] in ids}
    return apply_baseline(violations, scoped, root)


def render_text(violations: list[Violation]) -> str:
    lines = [v.format() for v in violations]
    lines.append(
        f"{len(violations)} violation(s)" if violations else "dlcfn-lint: clean"
    )
    return "\n".join(lines)


def render_json(violations: list[Violation]) -> str:
    return json.dumps(
        {"violations": [v.to_dict() for v in violations], "count": len(violations)},
        indent=2,
        allow_nan=False,
    )
