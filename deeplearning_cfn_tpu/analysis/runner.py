"""File discovery + orchestration for ``python -m deeplearning_cfn_tpu.cli lint``."""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, Iterator

# Importing rules registers them in core.FILE_RULES.
import deeplearning_cfn_tpu.analysis.rules  # noqa: F401
from deeplearning_cfn_tpu.analysis import contract_check
from deeplearning_cfn_tpu.analysis.core import Violation, lint_source

REPO_ROOT = Path(__file__).resolve().parents[2]
DEFAULT_TARGETS = ("deeplearning_cfn_tpu", "scripts", "bench.py")
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist"}


def discover(targets: Iterable[str | Path], root: Path = REPO_ROOT) -> Iterator[Path]:
    for target in targets:
        p = Path(target)
        if not p.is_absolute():
            p = root / p
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            yield from sorted(
                f
                for f in p.rglob("*.py")
                if not any(part in _SKIP_DIRS for part in f.parts)
            )


def run_lint(
    targets: Iterable[str | Path] | None = None,
    select: set[str] | None = None,
    root: Path = REPO_ROOT,
    contract: bool = True,
) -> list[Violation]:
    """Lint the given targets (repo defaults when None).

    ``select`` limits per-file rules to specific ids; the DLC1xx contract
    checker runs unless ``contract=False`` or a ``select`` set excludes
    both DLC100 and DLC101.
    """
    out: list[Violation] = []
    for path in discover(targets if targets is not None else DEFAULT_TARGETS, root):
        out.extend(lint_source(path, select=select))
    run_contract = contract and (
        select is None or select & {contract_check.RULE_VERBS, contract_check.RULE_FIELDS}
    )
    if run_contract:
        contract_violations = contract_check.check_contract()
        if select is not None:
            contract_violations = [v for v in contract_violations if v.rule in select]
        out.extend(contract_violations)
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


def render_text(violations: list[Violation]) -> str:
    lines = [v.format() for v in violations]
    lines.append(
        f"{len(violations)} violation(s)" if violations else "dlcfn-lint: clean"
    )
    return "\n".join(lines)


def render_json(violations: list[Violation]) -> str:
    return json.dumps(
        {"violations": [v.to_dict() for v in violations], "count": len(violations)},
        indent=2,
        allow_nan=False,
    )
