"""The DLC5xx comms/memory discipline rules (gated: ``dlcfn lint --comms``).

PR 7/8 made retraces and step phases visible; this pass makes the other
two MFU killers statically checkable — unintended collectives and HBM
pressure introduced by sharding mistakes.  The MLPerf-at-pod-scale
result (arxiv 1909.09756) and the CUDA-aware-MPI characterization
(arxiv 1810.11112) agree on the mechanism: communication *volume*
discipline, not kernel speed, separates flat scaling from linear.  Each
rule anchors on a concrete accidental-collective shape:

DLC500 spec-axis drift / in-out mismatch   DLC503 cross-mesh leakage
DLC501 unconstrained large intermediate    DLC504 unsummed shard_map reduce
DLC502 host materialization of sharded     DLC505 donated buffer read after
       arrays                                     the donating call

Scope: everywhere shardings are authored or consumed — ``train/``,
``parallel/``, ``models/``, ``ops/``, ``serve/``, and ``bench.py``
(``parallel/`` is new relative to DLC4xx: the sharding-rule tables and
mesh builders are where axis vocabularies drift first).

The static half is paired with a dynamic comms-audit sentinel
(analysis/comms_audit.py) that lowers the real train/serve programs and
machine-reads their HLO for collectives; its findings use the reserved
ids DLC510 (comms-budget regression), DLC511 (unpredicted fsdp
all-gather), and DLC512 (serialized collective the bucketed overlap
schedule should hide — overlap_score ratchet) so all halves share one
baseline ratchet (scripts/lint_baseline.json).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator

from deeplearning_cfn_tpu.analysis.core import (
    FileContext,
    Rule,
    Violation,
    call_name,
    dotted_name,
    has_keyword,
    keyword,
    register,
    walk_skipping_nested_functions,
)
from deeplearning_cfn_tpu.analysis.sharding import (
    _JIT_CORE,
    _FnDef,
    canonical_mesh_axes,
    traced_functions,
)

GATE = "comms"
RULE_IDS = ("DLC500", "DLC501", "DLC502", "DLC503", "DLC504", "DLC505")

# Reserved for the dynamic comms-audit sentinel (analysis/comms_audit.py):
# same namespace, same baseline ratchet, but findings come from lowering
# the real programs and reading their HLO rather than from this AST pass.
AUDIT_RULE_BUDGET = "DLC510"
AUDIT_RULE_UNPREDICTED = "DLC511"
AUDIT_RULE_OVERLAP = "DLC512"
AUDIT_RULE_IDS = (
    AUDIT_RULE_BUDGET,
    AUDIT_RULE_UNPREDICTED,
    AUDIT_RULE_OVERLAP,
)

# DLC4xx covers the compute tree; comms adds parallel/ — the sharding
# rule tables and mesh builders author the axis vocabulary everything
# else consumes.
_COMMS_DIRS = ("train", "parallel", "models", "ops", "serve")


def _applies_comms_paths(path: Path) -> bool:
    return path.name == "bench.py" or any(d in path.parts for d in _COMMS_DIRS)


# --- shared matchers ---------------------------------------------------------

_SHARDING_KWARGS = ("in_shardings", "out_shardings")
_CONSTRAINT_CALLS = (
    "with_sharding_constraint",
    "jax.lax.with_sharding_constraint",
    "lax.with_sharding_constraint",
    "maybe_shard",
    "sharding.maybe_shard",
)


def _spec_call(name: str | None) -> bool:
    if name is None:
        return False
    return name.rsplit(".", 1)[-1] in ("P", "PartitionSpec")


def _literal_specs(node: ast.AST) -> list[ast.Call]:
    """P(...)/PartitionSpec(...) calls under node."""
    return [
        n
        for n in ast.walk(node)
        if isinstance(n, ast.Call) and _spec_call(call_name(n))
    ]


def _spec_axes(node: ast.AST) -> Iterator[ast.Constant]:
    """String constants inside P(...)/PartitionSpec(...) calls under node."""
    for spec in _literal_specs(node):
        for sub in ast.walk(spec):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                yield sub


# --- DLC500: spec axes across a pjit in/out pair -----------------------------
# in_shardings and out_shardings are two halves of ONE layout contract.
# An axis that appears on the way in but not on the way out (or vice
# versa) makes XLA reshard at the program boundary — an all-gather or
# all-to-all on EVERY call that no line of user code shows.  And an axis
# name outside AXIS_ORDER (machine-read from parallel/mesh.py, like
# DLC403) silently degrades that side to replication.  Only literal
# P(...) specs are compared: passing the same shardings object for both
# kwargs (the trainer idiom) is by construction consistent.


def _check_inout_spec_consistency(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    try:
        canonical = set(canonical_mesh_axes())
    except (OSError, ValueError, SyntaxError):
        canonical = None  # DLC403 owns reporting extraction failure
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node) not in _JIT_CORE and not has_keyword(
            node, *_SHARDING_KWARGS
        ):
            continue
        kw_in = keyword(node, "in_shardings")
        kw_out = keyword(node, "out_shardings")
        if kw_in is None or kw_out is None:
            continue
        axes_in = list(_spec_axes(kw_in.value))
        axes_out = list(_spec_axes(kw_out.value))
        if canonical is not None:
            for const in axes_in + axes_out:
                if const.value not in canonical:
                    shown = "/".join(sorted(canonical))
                    yield ctx.violation(
                        "DLC500",
                        const,
                        f"axis {const.value!r} in a pjit sharding spec does "
                        f"not resolve against the mesh axes ({shown}) "
                        "machine-read from parallel/mesh.py AXIS_ORDER: "
                        "that side of the layout contract silently "
                        "degrades to replication",
                    )
        # Compare the two halves only when both carry literal specs: a
        # bare name (state_shardings passed to both kwargs) is
        # consistent by construction.  P(None, ...) counts as a literal
        # spec — dropping every axis on the way out IS the mismatch.
        if not _literal_specs(kw_in.value) or not _literal_specs(kw_out.value):
            continue
        set_in = {c.value for c in axes_in}
        set_out = {c.value for c in axes_out}
        for missing in sorted(set_in - set_out):
            yield ctx.violation(
                "DLC500",
                kw_out.value,
                f"axis {missing!r} is sharded by in_shardings but absent "
                "from this literal out_shardings spec: XLA inserts an "
                "all-gather over that axis at the program boundary on "
                "every call; carry the axis through (or spell the "
                "resharding explicitly)",
            )
        for extra in sorted(set_out - set_in):
            yield ctx.violation(
                "DLC500",
                kw_out.value,
                f"axis {extra!r} appears only in out_shardings of this "
                "pjit in/out pair: the output is resharded onto an axis "
                "the inputs never occupied — a per-call all-to-all no "
                "line of user code shows; shard the inputs to match",
            )


register(
    Rule(
        id="DLC500",
        name="pjit-inout-spec-consistency",
        doc="pjit in/out literal specs must use known axes and agree",
        check=_check_inout_spec_consistency,
        applies=_applies_comms_paths,
        gate=GATE,
    )
)

# --- DLC501: large intermediate feeding compute without a constraint ---------
# Inside sharded traced code, a matmul/attention output that directly
# feeds another matmul-family op with no with_sharding_constraint /
# maybe_shard between them leaves the intermediate's layout to GSPMD
# inference — which, at a propagation conflict, resolves to REPLICATED:
# the classic accidental all-gather of the largest activation in the
# model.  The rule is deliberately shape-anchored: it fires only on a
# direct producer->consumer chain of matmul-family calls inside a traced
# function, and only in files that author shardings at all (a file with
# no constraint/in_shardings anywhere is single-device code where layout
# inference has nothing to get wrong).

_MATMUL_CALLS = (
    "jnp.matmul",
    "jnp.dot",
    "jnp.einsum",
    "jax.numpy.matmul",
    "jax.numpy.dot",
    "jax.numpy.einsum",
    "lax.dot_general",
    "jax.lax.dot_general",
    "dot_product_attention",
    "jax.nn.dot_product_attention",
)


def _is_matmul_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
        return True
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in _MATMUL_CALLS:
            return True
        if name is not None and name.rsplit(".", 1)[-1] == "einsum":
            return True
    return False


def _file_authors_shardings(ctx: FileContext) -> bool:
    cached = getattr(ctx, "_dlc501_authors", None)
    if cached is not None:
        return cached
    found = False
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in _CONSTRAINT_CALLS or has_keyword(node, *_SHARDING_KWARGS):
                found = True
                break
    ctx._dlc501_authors = found  # type: ignore[attr-defined]
    return found


def _names_loaded(node: ast.AST) -> set[str]:
    return {
        n.id
        for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _check_unconstrained_intermediate(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    if not _file_authors_shardings(ctx):
        return
    for fn, why in traced_functions(ctx).items():
        # Producer names: name -> assignment statement, in body order.
        statements = list(walk_skipping_nested_functions(fn.body))
        # Nested matmul: consumer wraps producer in one expression —
        # there is nowhere a constraint could even have been applied.
        for node in statements:
            if not _is_matmul_expr(node):
                continue
            inner = (
                [node.left, node.right]
                if isinstance(node, ast.BinOp)
                else list(getattr(node, "args", []))
            )
            for operand in inner:
                if _is_matmul_expr(operand):
                    yield ctx.violation(
                        "DLC501",
                        operand,
                        f"matmul/attention output feeds another matmul "
                        f"directly inside traced {fn.name}() ({why}) with "
                        "no with_sharding_constraint on the intermediate: "
                        "GSPMD resolves propagation conflicts to "
                        "REPLICATED — the accidental all-gather of the "
                        "largest activation; name the intermediate and "
                        "constrain it (parallel.sharding.maybe_shard)",
                    )
        # Named chain: walk_skipping is stack-order, so producer /
        # kill (rebind or constraint) / consumer events are resolved by
        # line number, not visit order.
        produced: dict[str, list[int]] = {}
        killed: dict[str, list[int]] = {}
        for stmt in statements:
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            target = stmt.targets[0]
            if not isinstance(target, ast.Name):
                continue
            if _is_matmul_expr(stmt.value):
                produced.setdefault(target.id, []).append(stmt.lineno)
            else:
                # Any rebinding — through a constraint call or otherwise
                # — launders the name for lines below it.
                killed.setdefault(target.id, []).append(stmt.lineno)
        if not produced:
            continue
        for node in statements:
            if not _is_matmul_expr(node):
                continue
            operands = (
                [node.left, node.right]
                if isinstance(node, ast.BinOp)
                else list(getattr(node, "args", []))
            )
            for op in operands:
                if not (isinstance(op, ast.Name) and op.id in produced):
                    continue
                use_line = getattr(node, "lineno", 0)
                producer_line = max(
                    (ln for ln in produced[op.id] if ln < use_line),
                    default=None,
                )
                if producer_line is None or any(
                    producer_line < ln < use_line
                    for ln in killed.get(op.id, ())
                ):
                    continue
                yield ctx.violation(
                    "DLC501",
                    node,
                    f"matmul/attention output {op.id!r} feeds another "
                    f"matmul inside traced {fn.name}() ({why}) with no "
                    "with_sharding_constraint between producer and "
                    "consumer: GSPMD resolves propagation conflicts "
                    "to REPLICATED — the accidental all-gather shape; "
                    "constrain the intermediate "
                    "(parallel.sharding.maybe_shard)",
                )
                break


register(
    Rule(
        id="DLC501",
        name="unconstrained-large-intermediate",
        doc="matmul chains in sharded traced code need a layout constraint",
        check=_check_unconstrained_intermediate,
        applies=_applies_comms_paths,
        gate=GATE,
    )
)

# --- DLC502: host materialization of a sharded array -------------------------
# device_get / np.asarray / .item() on an array the SAME scope placed
# with a NamedSharding (device_put with a sharding, or a constraint
# call) is a full all-gather PLUS a device->host copy of the assembled
# global array — on a pod, gigabytes through one host NIC.  The rule
# tracks only scope-local evidence: a name is "known sharded" when this
# scope assigned it from device_put(x, <sharding>) or a constraint call.

_HOST_MATERIALIZE = (
    "jax.device_get",
    "device_get",
    "np.asarray",
    "numpy.asarray",
    "np.array",
    "numpy.array",
)
_DEVICE_PUT = ("jax.device_put", "device_put")


def _scopes(tree: ast.Module) -> Iterator[_FnDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _check_sharded_host_materialization(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for fn in _scopes(tree):
        sharded: dict[str, int] = {}  # name -> line it became sharded
        for stmt in walk_skipping_nested_functions(fn.body):
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            target = stmt.targets[0]
            if not isinstance(target, ast.Name) or not isinstance(
                stmt.value, ast.Call
            ):
                continue
            name = call_name(stmt.value)
            if name in _DEVICE_PUT and len(stmt.value.args) >= 2:
                sharded[target.id] = stmt.lineno
            elif name in _CONSTRAINT_CALLS:
                sharded[target.id] = stmt.lineno
        if not sharded:
            continue
        for node in walk_skipping_nested_functions(fn.body):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            victim: str | None = None
            if (
                cname in _HOST_MATERIALIZE
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in sharded
                and node.lineno > sharded[node.args[0].id]
            ):
                victim = node.args[0].id
                what = f"{cname}({victim})"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in sharded
                and node.lineno > sharded[node.func.value.id]
            ):
                victim = node.func.value.id
                what = f"{victim}.item()"
            if victim is not None:
                yield ctx.violation(
                    "DLC502",
                    node,
                    f"{what} materializes an array this scope placed with "
                    "a sharding: the host assembles the full global array "
                    "(an implicit all-gather through one host's NIC); "
                    "read per-shard via addressable_shards, or reduce "
                    "on-device first",
                )


register(
    Rule(
        id="DLC502",
        name="sharded-host-materialization",
        doc="no device_get/np.asarray/.item() on scope-local sharded arrays",
        check=_check_sharded_host_materialization,
        applies=_applies_comms_paths,
        gate=GATE,
    )
)

# --- DLC503: cross-mesh leakage ----------------------------------------------
# The ambient mesh is part of the jit dispatch-cache key.  A compiled
# callable warmed under ``with set_mesh(A)`` and then dispatched bare —
# or under a different mesh — misses its own cache entry and compiles
# the whole program a second time (the PR 7 bench double-compile,
# generalized).  Worse than the compile bill: the two executables can
# carry different collective schedules.  The rule is per-scope: every
# dispatch of a compiled callable in one function must run under the
# same set_mesh expression.

_SET_MESH_CALLS = ("set_mesh", "compat.set_mesh", "jax.sharding.use_mesh")


def _mesh_ctx_expr(stmt: ast.With) -> ast.expr | None:
    for item in stmt.items:
        call = item.context_expr
        if isinstance(call, ast.Call) and call_name(call) in _SET_MESH_CALLS:
            return call.args[0] if call.args else None
    return None


def _compiled_callable_names(fn: _FnDef) -> set[str]:
    """Names this scope binds to compiled callables: jit wrappers, AOT
    ``.lower(...).compile()`` results, and the trainer's ``step_fn`` /
    ``multi_step_fn`` family."""
    from deeplearning_cfn_tpu.analysis.sharding import _is_jit_expr

    out: set[str] = set()
    for stmt in walk_skipping_nested_functions(fn.body):
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        target = stmt.targets[0]
        if not isinstance(target, ast.Name):
            continue
        value = stmt.value
        terminal: str | None = None
        if isinstance(value, ast.Call):
            terminal = (call_name(value) or "").rsplit(".", 1)[-1]
            if _is_jit_expr(value) or _is_jit_expr(value.func):
                out.add(target.id)
                continue
        elif isinstance(value, ast.Attribute):
            terminal = value.attr
        if terminal is not None and (
            terminal == "compile" or terminal.endswith("step_fn")
        ):
            out.add(target.id)
    return out


def _check_cross_mesh_leakage(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for fn in _scopes(tree):
        compiled = _compiled_callable_names(fn)
        if not compiled:
            continue
        # name -> {mesh expression dump or None (bare)} -> first call node
        dispatches: dict[str, dict[str | None, ast.Call]] = {}
        for node in walk_skipping_nested_functions(fn.body):
            if not isinstance(node, ast.Call):
                continue
            if not (
                isinstance(node.func, ast.Name) and node.func.id in compiled
            ):
                continue
            mesh_key: str | None = None
            cur = ctx.parents.get(node)
            while cur is not None and cur is not fn:
                if isinstance(cur, ast.With):
                    expr = _mesh_ctx_expr(cur)
                    if expr is not None:
                        mesh_key = ast.dump(expr)
                        break
                cur = ctx.parents.get(cur)
            dispatches.setdefault(node.func.id, {}).setdefault(mesh_key, node)
        for name, by_mesh in dispatches.items():
            if len(by_mesh) < 2:
                continue
            meshes = sorted(k for k in by_mesh if k is not None)
            if not meshes:
                continue  # never dispatched under set_mesh: out of scope
            for mesh_key, node in sorted(
                by_mesh.items(), key=lambda kv: kv[1].lineno
            ):
                if mesh_key == meshes[0]:
                    continue
                how = (
                    "bare (no ambient mesh)"
                    if mesh_key is None
                    else "under a different set_mesh"
                )
                yield ctx.violation(
                    "DLC503",
                    node,
                    f"compiled callable {name}() is dispatched {how} here "
                    "but under set_mesh elsewhere in this scope: the "
                    "ambient mesh is part of the jit cache key, so the "
                    "two dispatches compile two executables with "
                    "independent collective schedules (the bench "
                    "double-compile, generalized); dispatch every call "
                    "under the same mesh",
                )


register(
    Rule(
        id="DLC503",
        name="cross-mesh-leakage",
        doc="every dispatch of a compiled callable must use one ambient mesh",
        check=_check_cross_mesh_leakage,
        applies=_applies_comms_paths,
        gate=GATE,
    )
)

# --- DLC504: shard_map reduction without a named collective ------------------
# Inside shard_map every array is the LOCAL shard.  jnp.sum/mean over a
# sharded axis without a psum/pmean over the mesh axis returns the
# partial reduction of one shard, silently treated as the global value —
# a loss that is 1/N of the truth, gradients that never see the other
# shards.  The lockset-style anchor: a shard_map body that reduces but
# never names a collective over any mesh axis.

_REDUCE_CALLS = ("sum", "mean", "prod", "max", "min")
_COLLECTIVE_CALLS = (
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "all_gather",
    "psum_scatter",
    "ppermute",
    "all_to_all",
)


def _shard_map_bodies(tree: ast.Module) -> Iterator[_FnDef]:
    from deeplearning_cfn_tpu.analysis.sharding import _defs_by_name

    defs = _defs_by_name(tree)
    seen: set[_FnDef] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name is None or name.rsplit(".", 1)[-1] != "shard_map":
            continue
        if node.args and isinstance(node.args[0], ast.Name):
            for fn in defs.get(node.args[0].id, ()):
                if fn not in seen:
                    seen.add(fn)
                    yield fn


def _reduce_call(node: ast.Call) -> str | None:
    name = call_name(node)
    if name is None:
        return None
    head, _, terminal = name.rpartition(".")
    if terminal in _REDUCE_CALLS and head in ("jnp", "jax.numpy", "np", "numpy"):
        return name
    return None


def _check_shard_map_reduction(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for fn in _shard_map_bodies(tree):
        has_collective = any(
            isinstance(n, ast.Call)
            and (call_name(n) or "").rsplit(".", 1)[-1] in _COLLECTIVE_CALLS
            for n in ast.walk(fn)
        )
        if has_collective:
            continue  # the body is axis-aware; trust its reductions
        for node in walk_skipping_nested_functions(fn.body):
            if not isinstance(node, ast.Call):
                continue
            name = _reduce_call(node)
            if name is None:
                continue
            yield ctx.violation(
                "DLC504",
                node,
                f"{name}() inside shard_map body {fn.name}() with no "
                "psum/pmean anywhere in the body: arrays here are LOCAL "
                "shards, so this reduces one shard and silently treats "
                "it as the global value; follow the reduction with "
                "lax.psum/pmean over the mesh axis",
            )


register(
    Rule(
        id="DLC504",
        name="shard-map-partial-reduction",
        doc="reductions in shard_map bodies need a named collective",
        check=_check_shard_map_reduction,
        applies=_applies_comms_paths,
        gate=GATE,
    )
)

# --- DLC505: donated buffer read after the donating call ---------------------
# donate_argnums hands the input buffer to XLA: after the call the
# Python name still exists but its buffer is deleted — touching it
# raises at best, and at worst (when dispatch is still in flight) reads
# freed device memory on some backends.  The repo idiom rebinds the name
# through the call (``state, _ = step(state, ...)``); the rule flags the
# other shape: a donated argument read again below the call without
# rebinding.


def _donated_positions(tree: ast.Module) -> dict[str, set[int]]:
    """Callable name -> positional indices its jit donates (same-file)."""
    from deeplearning_cfn_tpu.analysis.sharding import _is_jit_expr

    out: dict[str, set[int]] = {}

    def positions(call: ast.Call) -> set[int]:
        kw = keyword(call, "donate_argnums")
        nums: set[int] = set()
        if kw is not None:
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and type(n.value) is int:
                    nums.add(n.value)
        return nums

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                if isinstance(d, ast.Call) and _is_jit_expr(d):
                    nums = positions(d)
                    if nums:
                        out.setdefault(node.name, set()).update(nums)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
            if (
                isinstance(target, ast.Name)
                and isinstance(value, ast.Call)
                and _is_jit_expr(value.func)
            ):
                nums = positions(value)
                if nums:
                    out.setdefault(target.id, set()).update(nums)
    return out


def _assigned_names(stmt: ast.stmt) -> set[str]:
    out: set[str] = set()
    targets: list[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign, ast.For)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.With):
        targets = [
            item.optional_vars
            for item in stmt.items
            if item.optional_vars is not None
        ]
    for t in targets:
        for n in ast.walk(t):
            name = dotted_name(n)
            if name is not None:
                out.add(name)
    return out


def _statement_chain(ctx: FileContext, node: ast.AST, scope: _FnDef):
    """The statement of ``scope.body`` (or a nested body list) holding
    ``node``, plus that body list — where "after the call" is defined."""
    cur = node
    parent = ctx.parents.get(cur)
    while parent is not None and parent is not scope:
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None, None  # different scope
        cur = parent
        parent = ctx.parents.get(cur)
    if parent is None:
        return None, None
    body = scope.body
    if cur in body:
        return cur, body
    return None, None


def _check_donated_read_after_call(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    donors = _donated_positions(tree)
    if not donors:
        return
    for fn in _scopes(tree):
        for node in walk_skipping_nested_functions(fn.body):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node)
            terminal = (cname or "").rsplit(".", 1)[-1]
            if terminal not in donors:
                continue
            stmt, body = _statement_chain(ctx, node, fn)
            if stmt is None or body is None:
                continue
            rebound = _assigned_names(stmt)
            for pos in sorted(donors[terminal]):
                if pos >= len(node.args):
                    continue
                donated = dotted_name(node.args[pos])
                if donated is None or donated in rebound:
                    continue
                for later in body[body.index(stmt) + 1 :]:
                    if donated in _assigned_names(later):
                        break
                    read = next(
                        (
                            n
                            for n in ast.walk(later)
                            if isinstance(n, (ast.Name, ast.Attribute))
                            and isinstance(
                                getattr(n, "ctx", ast.Load()), ast.Load
                            )
                            and dotted_name(n) == donated
                        ),
                        None,
                    )
                    if read is not None:
                        yield ctx.violation(
                            "DLC505",
                            read,
                            f"{donated!r} is read after {terminal}() donated "
                            f"it (donate_argnums position {pos}): the "
                            "buffer is deleted the moment the compiled "
                            "program consumes it, so this read races "
                            "dispatch at best and raises at worst; rebind "
                            "the name through the call "
                            "(`x, ... = f(x, ...)`)",
                        )
                        break


register(
    Rule(
        id="DLC505",
        name="donated-read-after-call",
        doc="donated arguments must not be read after the donating call",
        check=_check_donated_read_after_call,
        applies=_applies_comms_paths,
        gate=GATE,
    )
)
