"""The dynamic compile-audit sentinel: prove steady-state zero-retrace.

The DLC4xx static rules (analysis/sharding.py) catch retrace *hazards*;
this module catches retraces that actually happen.  It runs the real
``Trainer.fit()`` single-step path and the bench multi-step path for a
few steps on CPU, watching JAX's own compilation machinery:

- per-function trace and compile counts, read from the
  ``jax_log_compiles`` log stream (the only per-function signal JAX
  exposes; ``jax.monitoring``'s ``backend_compile`` events carry
  durations but no names, so they are kept as an aggregate cross-check);
- the jit dispatch-cache size of each audited wrapper
  (``fn._cache_size()``) — a second, independent retrace witness;
- donation effectiveness, observed directly: after one step, every
  donated input buffer reports ``is_deleted()`` — so "someone dropped
  ``donate_argnums``" shows up as ``donated_bytes == 0``, not as an OOM
  three weeks later on a 16 GiB chip.

After a warmup phase the watcher marks steady state; any function whose
compile count then grows is a finding (DLC410), and a step whose state
donation is completely ineffective is a finding (DLC411).  Findings are
ordinary :class:`Violation`\\ s against the audited source file, flowing
through the same suppression-baseline ratchet as every other DLC rule
(scripts/lint_baseline.json) — a future PR that introduces a retrace or
drops a donation fails ``scripts/check.sh``, it does not get a warning.

Results are journaled to the flight recorder as a ``compile_audit``
event so retrace history rides the same JSONL stream as heartbeats and
reshard events.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

import jax
import numpy as np

from deeplearning_cfn_tpu.analysis.core import Violation
from deeplearning_cfn_tpu.analysis.sharding import (
    AUDIT_RULE_DONATION,
    AUDIT_RULE_RETRACE,
)

REPO_ROOT = Path(__file__).resolve().parents[2]
# Findings anchor on the file that owns the audited step loop: the
# baseline key is (rule, repo-relative path, message).
AUDITED_FILE = REPO_ROOT / "deeplearning_cfn_tpu" / "train" / "trainer.py"
SERVE_AUDITED_FILE = REPO_ROOT / "deeplearning_cfn_tpu" / "serve" / "engine.py"

# jax_log_compiles emits exactly two shapes (jax 0.4.x):
#   "Finished tracing + transforming {name} for pjit in {t} sec"
#     (logger jax._src.dispatch)
#   "Compiling {name} with global shapes and types [...]"
#     (logger jax._src.interpreters.pxla)
_TRACE_RE = re.compile(r"Finished tracing \+ transforming (.+?) for pjit")
_COMPILE_RE = re.compile(r"^Compiling (.+?) with global shapes")
_COMPILE_LOGGERS = ("jax._src.dispatch", "jax._src.interpreters.pxla")

_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_ACTIVE_WATCHERS: list["CompileWatcher"] = []
_MONITORING_INSTALLED = False


def _install_monitoring_listener() -> None:
    """One process-wide listener fanning out to active watchers (the
    monitoring API has no unregister, so never register per-watcher)."""
    global _MONITORING_INSTALLED
    if _MONITORING_INSTALLED:
        return
    try:

        def _on_event(event: str, duration: float, **_kw: Any) -> None:
            if event == _BACKEND_COMPILE_EVENT:
                for w in _ACTIVE_WATCHERS:
                    w.backend_compiles += 1

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _MONITORING_INSTALLED = True
    except Exception:  # pragma: no cover - monitoring API drift
        _MONITORING_INSTALLED = True  # don't retry every watcher


class CompileWatcher(logging.Handler):
    """Context manager counting per-function traces/compiles while active.

    ``mark_steady()`` snapshots the counters; ``new_compiles_since_mark``
    is then the retrace report: any function compiled after the mark.
    """

    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.traces: dict[str, int] = {}
        self.compiles: dict[str, int] = {}
        self.backend_compiles = 0
        self._mark_traces: dict[str, int] = {}
        self._mark_compiles: dict[str, int] = {}
        self._saved_flag: bool | None = None
        self._saved_propagate: dict[str, bool] = {}

    # --- logging.Handler ------------------------------------------------
    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # pragma: no cover - malformed record
            return
        m = _TRACE_RE.search(msg)
        if m:
            self.traces[m.group(1)] = self.traces.get(m.group(1), 0) + 1
            return
        m = _COMPILE_RE.search(msg)
        if m:
            self.compiles[m.group(1)] = self.compiles.get(m.group(1), 0) + 1

    # --- context --------------------------------------------------------
    def __enter__(self) -> "CompileWatcher":
        self._saved_flag = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        for name in _COMPILE_LOGGERS:
            logger = logging.getLogger(name)
            self._saved_propagate[name] = logger.propagate
            # Handlers attached to the logger fire regardless of
            # propagate; cutting propagation keeps N-steps-worth of
            # "Compiling ..." noise out of the operator's console.
            logger.propagate = False
            logger.addHandler(self)
        _install_monitoring_listener()
        _ACTIVE_WATCHERS.append(self)
        return self

    def __exit__(self, *exc: Any) -> None:
        if self in _ACTIVE_WATCHERS:
            _ACTIVE_WATCHERS.remove(self)
        for name in _COMPILE_LOGGERS:
            logger = logging.getLogger(name)
            logger.removeHandler(self)
            logger.propagate = self._saved_propagate.get(name, True)
        if self._saved_flag is not None:
            jax.config.update("jax_log_compiles", self._saved_flag)

    # --- counters -------------------------------------------------------
    def mark_steady(self) -> None:
        self._mark_traces = dict(self.traces)
        self._mark_compiles = dict(self.compiles)

    def _delta(self, now: dict[str, int], mark: dict[str, int]) -> dict[str, int]:
        out = {}
        for fn, count in now.items():
            grew = count - mark.get(fn, 0)
            if grew > 0:
                out[fn] = grew
        return out

    def new_compiles_since_mark(self) -> dict[str, int]:
        return self._delta(self.compiles, self._mark_compiles)

    def new_traces_since_mark(self) -> dict[str, int]:
        return self._delta(self.traces, self._mark_traces)

    @property
    def compile_count(self) -> int:
        return sum(self.compiles.values())

    @property
    def retrace_count(self) -> int:
        """Compiles beyond the first per function — 0 in a healthy run."""
        return sum(c - 1 for c in self.compiles.values() if c > 1)

    def snapshot(self) -> dict:
        return {
            "traces": dict(sorted(self.traces.items())),
            "compiles": dict(sorted(self.compiles.items())),
            "compile_count": self.compile_count,
            "retrace_count": self.retrace_count,
            "backend_compiles": self.backend_compiles,
        }


# --- donation ---------------------------------------------------------------


@dataclass(frozen=True)
class DonationReport:
    donated_bytes: int
    retained_bytes: int
    donated_leaves: int
    retained_leaves: int

    @property
    def effective(self) -> bool:
        return self.donated_bytes > 0

    def to_dict(self) -> dict:
        return {
            "donated_bytes": self.donated_bytes,
            "retained_bytes": self.retained_bytes,
            "donated_leaves": self.donated_leaves,
            "retained_leaves": self.retained_leaves,
            "effective": self.effective,
        }


def measure_donation(fn: Callable, state: Any, *args: Any) -> tuple[Any, DonationReport]:
    """Call ``fn(state, *args)`` and report how much of ``state`` the
    compiled program actually donated (buffer deleted after dispatch).

    Works because donation is observable from the host: a donated jax
    Array's buffer is invalidated the moment the computation consumes
    it, and ``is_deleted()`` says so — on CPU just as on TPU.
    """
    leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves(state)
        if hasattr(leaf, "is_deleted")
    ]
    sizes = [(leaf, int(getattr(leaf, "nbytes", 0))) for leaf in leaves]
    out = fn(state, *args)
    jax.block_until_ready(out)
    donated_bytes = retained_bytes = donated_leaves = retained_leaves = 0
    for leaf, nbytes in sizes:
        if leaf.is_deleted():
            donated_bytes += nbytes
            donated_leaves += 1
        else:
            retained_bytes += nbytes
            retained_leaves += 1
    return out, DonationReport(
        donated_bytes=donated_bytes,
        retained_bytes=retained_bytes,
        donated_leaves=donated_leaves,
        retained_leaves=retained_leaves,
    )


# --- the audit itself -------------------------------------------------------


@dataclass
class PathAudit:
    """One audited dispatch path (single_step / multi_step)."""

    name: str
    steady_steps: int
    new_compiles: dict[str, int] = field(default_factory=dict)
    new_traces: dict[str, int] = field(default_factory=dict)
    cache_size: int | None = None
    donation: DonationReport | None = None
    # Which source file findings anchor on (the baseline key's path);
    # None -> the trainer (the pre-serve audits' anchor).
    audited_file: str | None = None

    @property
    def clean(self) -> bool:
        return not self.new_compiles and (
            self.donation is None or self.donation.effective
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "steady_steps": self.steady_steps,
            "new_compiles": dict(sorted(self.new_compiles.items())),
            "new_traces": dict(sorted(self.new_traces.items())),
            "cache_size": self.cache_size,
            "donation": self.donation.to_dict() if self.donation else None,
            "clean": self.clean,
        }


@dataclass
class CompileAuditReport:
    paths: list[PathAudit]
    watcher: dict
    violations: list[Violation]

    def to_dict(self) -> dict:
        return {
            "paths": [p.to_dict() for p in self.paths],
            "watcher": self.watcher,
            "violations": [v.to_dict() for v in self.violations],
            "clean": not self.violations,
        }


def violations_for(paths: list[PathAudit]) -> list[Violation]:
    """Fold path audits into baseline-ratchet findings.

    Messages are deliberately count-free: the baseline keys on
    (rule, path, message), and a retrace that fires 3 times vs 4 times
    across runs is the same finding.
    """
    out: list[Violation] = []
    for p in paths:
        anchor = p.audited_file or str(AUDITED_FILE)
        if p.new_compiles:
            fns = ", ".join(sorted(p.new_compiles))
            out.append(
                Violation(
                    rule=AUDIT_RULE_RETRACE,
                    path=anchor,
                    line=1,
                    col=1,
                    message=(
                        f"steady-state retrace on the {p.name} path: "
                        f"{fns} recompiled after warmup (compile-audit "
                        "sentinel; see docs/STATIC_ANALYSIS.md retrace "
                        "runbook)"
                    ),
                )
            )
        if p.donation is not None and not p.donation.effective:
            out.append(
                Violation(
                    rule=AUDIT_RULE_DONATION,
                    path=anchor,
                    line=1,
                    col=1,
                    message=(
                        f"state donation ineffective on the {p.name} "
                        "path: no input buffer was deleted by the step "
                        "(donate_argnums dropped or aliasing declined; "
                        "compile-audit sentinel)"
                    ),
                )
            )
    return out


def _cache_size(jitted: Any) -> int | None:
    try:
        return int(jitted._cache_size())
    except Exception:  # pragma: no cover - private API drift
        return None


def run_compile_audit(
    steady_steps: int = 4,
    warmup_steps: int = 2,
    k: int = 2,
    batch_size: int = 8,
    journal: bool = True,
) -> CompileAuditReport:
    """Run the real trainer on CPU and assert steady-state zero-retrace.

    Small on purpose (tiny MLP, a handful of steps): the sentinel's
    question is "does the dispatch layer reach a fixed point", which is
    shape-independent — the production model would answer it identically
    at 1000x the compile bill.
    """
    import flax.linen as nn

    from deeplearning_cfn_tpu.parallel.mesh import MeshSpec, build_mesh
    from deeplearning_cfn_tpu.train.data import SyntheticDataset
    from deeplearning_cfn_tpu.train.trainer import Trainer, TrainerConfig

    class _AuditMLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(32)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x)

    devices = jax.devices()
    n = 2 if len(devices) >= 2 else 1
    mesh = build_mesh(MeshSpec.data_parallel(n), devices[:n])
    ds = SyntheticDataset(
        shape=(8, 8, 1), num_classes=4, batch_size=batch_size, seed=0
    )
    trainer = Trainer(
        _AuditMLP(), mesh, TrainerConfig(learning_rate=0.05, optimizer="sgd")
    )
    sample = next(iter(ds.batches(1)))
    paths: list[PathAudit] = []
    with CompileWatcher() as watcher:
        state = trainer.init(jax.random.PRNGKey(0), sample.x)

        # --- single-step fit path (the production loop) ----------------
        state, _ = trainer.fit(
            state, ds.batches(warmup_steps), steps=warmup_steps, prefetch=0
        )
        watcher.mark_steady()
        state, losses = trainer.fit(
            state, ds.batches(steady_steps), steps=steady_steps, prefetch=0
        )
        assert len(losses) == steady_steps
        single = PathAudit(
            name="single_step",
            steady_steps=steady_steps,
            new_compiles=watcher.new_compiles_since_mark(),
            new_traces=watcher.new_traces_since_mark(),
            cache_size=_cache_size(trainer.step_fn),
        )
        x = jax.device_put(sample.x, trainer.batch_sharding)
        y = jax.device_put(sample.y, trainer.batch_sharding)
        (state, _metrics), single.donation = measure_donation(
            trainer.train_step, state, x, y
        )
        paths.append(single)

        # --- multi-step bench path -------------------------------------
        # One wrapper, many calls: multi_step_fn() constructs a NEW jit
        # object per invocation (its own cache), so the audited idiom —
        # and bench.py's — is build-once-call-many.
        kfn = trainer.multi_step_fn(k)
        stack = list(ds.batches(2 * k))
        xs = np.stack([b.x for b in stack[:k]])
        ys = np.stack([b.y for b in stack[:k]])
        state, _ = kfn(state, xs, ys)  # compile
        watcher.mark_steady()
        multi = PathAudit(name="multi_step", steady_steps=steady_steps)
        for i in range(steady_steps):
            xs2 = np.stack([b.x for b in stack[k:]])
            ys2 = np.stack([b.y for b in stack[k:]])
            if i == steady_steps - 1:
                (state, _losses), multi.donation = measure_donation(
                    kfn, state, xs2, ys2
                )
            else:
                state, _losses = kfn(state, xs2, ys2)
        multi.new_compiles = watcher.new_compiles_since_mark()
        multi.new_traces = watcher.new_traces_since_mark()
        multi.cache_size = _cache_size(kfn)
        paths.append(multi)
        jax.block_until_ready(state.params)
        snapshot = watcher.snapshot()

    violations = violations_for(paths)
    if journal:
        from deeplearning_cfn_tpu.obs.recorder import get_recorder

        get_recorder().record(
            "compile_audit",
            clean=not violations,
            compile_count=snapshot["compile_count"],
            retrace_count=snapshot["retrace_count"],
            backend_compiles=snapshot["backend_compiles"],
            paths={p.name: p.to_dict() for p in paths},
        )
    return CompileAuditReport(paths=paths, watcher=snapshot, violations=violations)


def run_serve_audit(
    steady_requests: int = 24,
    journal: bool = True,
) -> CompileAuditReport:
    """The serving-plane sentinel: continuous batching must reach ONE
    compiled decode step and stay there.

    Warms a tiny engine (one request through prefill + decode compiles
    both jits), marks steady, then pushes ``steady_requests`` requests of
    MIXED prompt/output lengths through the scheduler — every admission,
    every occupancy pattern, every page placement must hit the same two
    executables.  Any post-warmup compile is a DLC410 finding anchored on
    serve/engine.py; a decode step that stops donating the paged pool
    (two pool-sized buffers resident per step) is a DLC411 finding.
    """
    import dataclasses

    import jax.numpy as jnp

    from deeplearning_cfn_tpu.models.llama import LlamaConfig, init_params
    from deeplearning_cfn_tpu.serve.engine import (
        ContinuousBatchingEngine,
        ServeConfig,
        ServeRequest,
        paged_decode_step,
    )

    cfg = dataclasses.replace(
        LlamaConfig.tiny(vocab_size=64, seq_len=64), dtype=jnp.float32
    )
    params = init_params(cfg, jax.random.key(0))
    scfg = ServeConfig(
        num_slots=4, block_size=4, blocks_per_slot=8, prefill_len=16
    )
    engine = ContinuousBatchingEngine(
        cfg, params, scfg, clock=lambda: 0.0, journal=False
    )
    rng = np.random.default_rng(0)

    def make_request(i: int) -> ServeRequest:
        prompt = rng.integers(0, 64, size=int(rng.integers(1, 17)))
        return ServeRequest(
            f"audit-{i}", prompt.astype(np.int32), int(rng.integers(1, 17))
        )

    paths: list[PathAudit] = []
    with CompileWatcher() as watcher:
        engine.submit(make_request(0))
        while engine.pending():
            engine.step()
        watcher.mark_steady()

        decode_steps = 0
        for i in range(1, steady_requests + 1):
            engine.submit(make_request(i))
        while engine.pending():
            engine.step()
            decode_steps += 1

        audit = PathAudit(
            name="serve_decode",
            steady_steps=decode_steps,
            new_compiles=watcher.new_compiles_since_mark(),
            new_traces=watcher.new_traces_since_mark(),
            cache_size=_cache_size(paged_decode_step),
            audited_file=str(SERVE_AUDITED_FILE),
        )
        # Donation check on the real steady-state call: the paged pool
        # must be consumed (deleted), not copied, by the decode step.
        scfg_t = engine.serve_cfg
        tokens = np.zeros(scfg_t.num_slots, np.int32)
        lengths = np.zeros(scfg_t.num_slots, np.int32)
        tables = np.zeros(
            (scfg_t.num_slots, scfg_t.blocks_per_slot), np.int32
        )
        active = np.zeros(scfg_t.num_slots, bool)
        (_, engine.cache), audit.donation = measure_donation(
            lambda cache: paged_decode_step(
                cfg,
                engine.params,
                cache,
                tokens,
                lengths,
                tables,
                active,
                engine._key,
                temperature=scfg_t.temperature,
            ),
            engine.cache,
        )
        paths.append(audit)
        snapshot = watcher.snapshot()

    violations = violations_for(paths)
    if journal:
        from deeplearning_cfn_tpu.obs.recorder import get_recorder

        get_recorder().record(
            "compile_audit",
            clean=not violations,
            compile_count=snapshot["compile_count"],
            retrace_count=snapshot["retrace_count"],
            backend_compiles=snapshot["backend_compiles"],
            paths={p.name: p.to_dict() for p in paths},
        )
    return CompileAuditReport(paths=paths, watcher=snapshot, violations=violations)
