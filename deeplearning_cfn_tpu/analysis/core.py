"""The lint framework: rule registry, AST context, noqa suppression.

A rule is a function ``check(tree, ctx) -> iterable[Violation]`` plus
metadata, registered in :data:`FILE_RULES`.  :func:`lint_source` parses
one file, builds a :class:`FileContext` (parent links, per-line
suppressions), runs every applicable rule, and filters suppressed
findings.

Suppression is per physical line, flake8-style but namespaced so it can
never collide with other linters' noqa semantics::

    proc.wait()  # dlcfn: noqa[DLC001] build step is externally supervised

The rule list in brackets is mandatory (a blanket ``noqa`` suppressing
every rule hides future findings on the line); the trailing free text is
the required human reason.
"""

from __future__ import annotations

import ast
import re
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Iterator

_NOQA = re.compile(r"#\s*dlcfn:\s*noqa\[([A-Z0-9,\s]+)\]")


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass(frozen=True)
class Rule:
    """One registered check.

    ``applies(path)`` scopes path-specific rules (e.g. DLC002 only
    guards bench/metrics emitters); the default is every file.

    ``gate`` names an opt-in pass ("concurrency"): gated rules run only
    when explicitly selected (``--select DLC2xx`` or the pass flag), so
    growing the rule set never changes what a plain ``dlcfn lint``
    reports out from under the baseline.
    """

    id: str
    name: str
    doc: str
    check: Callable[[ast.Module, "FileContext"], Iterable[Violation]]
    applies: Callable[[Path], bool] = field(default=lambda _p: True)
    gate: str | None = None


FILE_RULES: dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.id in FILE_RULES:
        raise ValueError(f"duplicate rule id {rule.id}")
    FILE_RULES[rule.id] = rule
    return rule


class FileContext:
    """Shared per-file state handed to every rule."""

    def __init__(self, path: Path, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.suppressions = self._parse_noqa()

    def _parse_noqa(self) -> dict[int, set[str]]:
        out: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _NOQA.search(line)
            if m:
                out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
        return out

    def violation(self, rule_id: str, node: ast.AST, message: str) -> Violation:
        return Violation(
            rule=rule_id,
            path=str(self.path),
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
        )

    def suppressed(self, v: Violation) -> bool:
        return v.rule in self.suppressions.get(v.line, set())

    # --- rule helpers -----------------------------------------------------
    def enclosing(self, node: ast.AST, *types: type) -> ast.AST | None:
        """Nearest ancestor of one of ``types`` (not the node itself)."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, types):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_function(
        self, node: ast.AST
    ) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
        fn = self.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef)
        return fn  # type: ignore[return-value]


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def keyword(call: ast.Call, name: str) -> ast.keyword | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


def has_keyword(call: ast.Call, *names: str) -> bool:
    return any(kw.arg in names for kw in call.keywords)


def walk_skipping_nested_functions(body: Iterable[ast.AST]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested def/class scopes —
    for rules whose question is "does THIS scope do X"."""
    nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, nested):
            # A def/class that is itself a statement of the walked scope:
            # yield it (so callers can see the boundary) but do not
            # descend — its body is a different scope.
            continue
        stack.extend(ast.iter_child_nodes(node))


def lint_source(
    path: Path | str,
    source: str | None = None,
    select: set[str] | None = None,
) -> list[Violation]:
    """Lint one Python file.  ``select`` limits to specific rule ids."""
    path = Path(path)
    if source is None:
        source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [
            Violation(
                rule="DLC000",
                path=str(path),
                line=e.lineno or 1,
                col=(e.offset or 0) + 1,
                message=f"file does not parse: {e.msg}",
            )
        ]
    ctx = FileContext(path, source, tree)
    out: list[Violation] = []
    for rule in FILE_RULES.values():
        if select is not None and rule.id not in select:
            continue
        if select is None and rule.gate is not None:
            continue  # gated passes are opt-in (runner/CLI selects them)
        if not rule.applies(path):
            continue
        for v in rule.check(tree, ctx):
            if not ctx.suppressed(v):
                out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out
