"""DLC6xx: the determinism verifier — static nondeterminism rules.

Every proof this stack offers — the 10k-agent fleet soak, the chaos
gates, the scheduler's ledger crash-resume — is an assertion of
*byte-determinism per seed*: the same seed must produce the same
report, byte for byte, run over run (ROADMAP items 3 and 4 make that
the acceptance criterion for the federated sim and the composed
gauntlet).  These rules encode the hazards that silently break the
contract, scoped to the determinism-bearing packages (chaos/, sched/,
cluster/, obs/, train/datastream/, serve/loadgen.py,
analysis/schedules.py, parallel/overlap.py — the bucket planner's
output order is an SPMD contract, so it is held to the same bar):

DLC600 unsorted-fs-enumeration  os.listdir/glob/Path.iterdir results
                                feeding iteration, a subscript, or a
                                return value without sorted() — the OS
                                hands back directory entries in
                                filesystem order, which differs across
                                machines and reruns
DLC601 ambient-entropy          random.*/uuid1/uuid4/secrets/time.time
                                in deterministic scope, outside the
                                injected-clock / seeded-RNG seams —
                                widens DLC205's wall-clock rule from
                                liveness to entropy
DLC602 set-order-fold           iterating a set without a sort key —
                                str hashes are salted per process
                                (PYTHONHASHSEED), so the fold order
                                differs run over run
DLC603 hash-escape              hash()/id() escaping into persisted or
                                compared values — the exact bug class
                                ``cluster.shards.shard_for_key`` dodged
                                by using crc32
DLC604 seed-plumbing-break      a function that takes seed/rng but
                                constructs an unseeded RNG: the seed
                                never reaches the entropy source

Like every DLC pass, matchers anchor on the bug's *shape*, not a
keyword: DLC600 only fires where enumeration order can reach output
(truthiness, len(), membership stay legal); DLC601 exempts ts-named
record metadata (``"started_ts": time.time()`` stays legal, same
carve-out DLC205 made) and default-clock adapters whose entire body is
the call; DLC602 tracks set-typed bindings per scope, not names that
merely sound set-ish; time.monotonic()/perf_counter() remain DLC205's
domain — interval math is a liveness question, not an entropy one.

All five are gated behind ``dlcfn lint --determinism`` (or an explicit
``--select``) and ratchet via the committed baseline.  DLC610 is
*reserved* here for the dynamic replay sentinel
(analysis/replay_audit.py — double-run every chaos scenario and fleet
soak, diff bytes); no static rule may ever register it.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator

from deeplearning_cfn_tpu.analysis.core import (
    FileContext,
    Rule,
    Violation,
    call_name,
    dotted_name,
    register,
    walk_skipping_nested_functions,
)

GATE = "determinism"
RULE_IDS = ("DLC600", "DLC601", "DLC602", "DLC603", "DLC604")

# Reserved for the dynamic replay sentinel (analysis/replay_audit.py /
# scripts/replay_audit.py): a chaos scenario or fleet soak whose two
# same-seed in-process runs produce different report bytes.  Only the
# sentinel may emit it; registering a static rule under this id is a
# bug (tests pin the reservation).
AUDIT_RULE_REPLAY = "DLC610"
AUDIT_RULE_IDS = (AUDIT_RULE_REPLAY,)


def _applies_determinism_paths(path: Path) -> bool:
    parts = path.parts
    if {"chaos", "sched", "cluster", "obs"} & set(parts):
        return True
    if "datastream" in parts:
        return True
    if path.name == "loadgen.py" and "serve" in parts:
        return True
    if path.name == "schedules.py" and "analysis" in parts:
        return True
    # The bucket planner must emit the same bucket order on every host or
    # the fused collectives deadlock — replay-critical like the rest.
    if path.name == "overlap.py" and "parallel" in parts:
        return True
    return False


# --- DLC600: unsorted filesystem enumeration --------------------------------

_ENUM_CALLS = {"os.listdir", "os.scandir", "glob.glob", "glob.iglob"}
_ENUM_METHODS = {"glob", "rglob", "iterdir"}
# Wrappers that preserve order without consuming it — climb through.
_TRANSPARENT_CALLS = {"list", "tuple"}
# Consumers for which enumeration order cannot reach the result.
_ORDER_FREE_CALLS = {
    "sorted",
    "len",
    "set",
    "frozenset",
    "any",
    "all",
    "sum",
    "min",
    "max",
    "bool",
}


def _is_enum_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    if call_name(node) in _ENUM_CALLS:
        return True
    return (
        isinstance(node.func, ast.Attribute) and node.func.attr in _ENUM_METHODS
    )


def _enum_display(node: ast.Call) -> str:
    name = call_name(node)
    if name is not None:
        return f"{name}()"
    assert isinstance(node.func, ast.Attribute)
    return f".{node.func.attr}()"


def _climb_transparent(
    node: ast.AST, ctx: FileContext
) -> tuple[ast.AST, ast.AST | None]:
    """Skip list()/tuple() shells: they keep the order problem intact."""
    cur = node
    parent = ctx.parents.get(cur)
    while (
        isinstance(parent, ast.Call)
        and call_name(parent) in _TRANSPARENT_CALLS
        and cur in parent.args
    ):
        cur = parent
        parent = ctx.parents.get(parent)
    return cur, parent


def _order_sensitive_context(cur: ast.AST, parent: ast.AST | None) -> bool:
    """Can enumeration order reach output from this expression position?

    Anchored on the escape shapes: iteration, subscripts, return/yield,
    containment in a built value, or feeding an arbitrary consumer.
    Truthiness, len(), set()-folding, and membership tests stay legal.
    """
    if isinstance(parent, ast.Call):
        if cur in parent.args and call_name(parent) in _ORDER_FREE_CALLS:
            return False
        return True
    if isinstance(parent, ast.Compare):
        return not (
            cur in parent.comparators
            and all(isinstance(op, (ast.In, ast.NotIn)) for op in parent.ops)
        )
    if isinstance(parent, (ast.If, ast.While)) and parent.test is cur:
        return False
    if isinstance(parent, ast.BoolOp):
        return False
    if isinstance(parent, ast.UnaryOp) and isinstance(parent.op, ast.Not):
        return False
    if isinstance(parent, ast.For) and parent.iter is cur:
        return True
    if isinstance(parent, ast.comprehension) and parent.iter is cur:
        return True
    if isinstance(
        parent,
        (
            ast.Return,
            ast.Yield,
            ast.YieldFrom,
            ast.Subscript,
            ast.Starred,
            ast.Dict,
            ast.List,
            ast.Tuple,
            ast.Set,
            ast.JoinedStr,
            ast.FormattedValue,
            ast.BinOp,
        ),
    ):
        return True
    return False


def _scope_of(node: ast.AST, ctx: FileContext) -> ast.AST:
    return ctx.enclosing_function(node) or ctx.tree


def _first_sensitive_load(
    scope: ast.AST, name: str, ctx: FileContext
) -> ast.AST | None:
    for n in ast.walk(scope):
        if (
            isinstance(n, ast.Name)
            and n.id == name
            and isinstance(n.ctx, ast.Load)
        ):
            cur, parent = _climb_transparent(n, ctx)
            if _order_sensitive_context(cur, parent):
                return n
    return None


def _check_unsorted_enumeration(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not _is_enum_call(node):
            continue
        assert isinstance(node, ast.Call)
        what = _enum_display(node)
        cur, parent = _climb_transparent(node, ctx)
        if isinstance(parent, ast.Assign):
            targets = parent.targets
            if len(targets) == 1 and isinstance(targets[0], ast.Name):
                name = targets[0].id
                load = _first_sensitive_load(_scope_of(parent, ctx), name, ctx)
                if load is not None:
                    yield ctx.violation(
                        "DLC600",
                        node,
                        f"{what} result `{name}` is used order-sensitively "
                        f"(line {load.lineno}) without sorted(): the OS "
                        "returns entries in filesystem order, which differs "
                        "across machines and reruns; sort at the "
                        "enumeration site",
                    )
                continue
            yield ctx.violation(
                "DLC600",
                node,
                f"{what} result is stored without sorted() where its uses "
                "cannot be tracked: the OS returns entries in filesystem "
                "order, which differs across machines and reruns; sort at "
                "the enumeration site",
            )
            continue
        if _order_sensitive_context(cur, parent):
            yield ctx.violation(
                "DLC600",
                node,
                f"{what} feeds iteration or output in filesystem order, "
                "which differs across machines and reruns; wrap the "
                "enumeration in sorted(...)",
            )


register(
    Rule(
        id="DLC600",
        name="unsorted-fs-enumeration",
        doc="listdir/glob/iterdir results must be sorted before order can escape",
        check=_check_unsorted_enumeration,
        applies=_applies_determinism_paths,
        gate=GATE,
    )
)

# --- DLC601: ambient entropy in deterministic scope -------------------------

_AMBIENT_ENTROPY = {
    "random.random",
    "random.randint",
    "random.randrange",
    "random.uniform",
    "random.gauss",
    "random.normalvariate",
    "random.expovariate",
    "random.betavariate",
    "random.choice",
    "random.choices",
    "random.sample",
    "random.shuffle",
    "random.getrandbits",
    "random.seed",
    "uuid.uuid1",
    "uuid.uuid4",
    "os.urandom",
}
_WALL_CLOCK = {"time.time", "time.time_ns"}
_ALWAYS_AMBIENT_CTORS = {"random.SystemRandom", "SystemRandom"}
_NP_RANDOM_PREFIXES = ("np.random.", "numpy.random.")
# numpy.random members that are constructors of *seedable* state, not
# draws from the hidden global generator.
_NP_SEEDED_MEMBERS = {
    "default_rng",
    "Generator",
    "RandomState",
    "SeedSequence",
    "PCG64",
    "Philox",
}
# Seedable RNG constructors: zero-arg means "seed from the OS".
_SEEDED_CTORS = {
    "random.Random",
    "Random",
    "np.random.default_rng",
    "numpy.random.default_rng",
    "default_rng",
    "np.random.RandomState",
    "numpy.random.RandomState",
    "RandomState",
}
_TS_MARKERS = ("ts", "time", "at", "when", "date", "timestamp")
_NUMERIC_WRAPPERS = {"round", "int", "float"}
_SEED_PARAM_TERMINALS = ("seed", "rng")


def _ts_named(name: str) -> bool:
    return name.lower().endswith(_TS_MARKERS)


def _is_record_metadata(node: ast.AST, ctx: FileContext) -> bool:
    """``"started_ts": time.time()`` and kin: a timestamp *recorded*, not
    a timestamp *decided on* — the same carve-out DLC205 makes."""
    cur = node
    parent = ctx.parents.get(cur)
    while (
        isinstance(parent, ast.Call)
        and cur in parent.args
        and (
            call_name(parent) in _NUMERIC_WRAPPERS
            # A record-read fallback — ``standby.get("started_ts",
            # time.time())`` — is still the recorded-metadata shape.
            or (
                isinstance(parent.func, ast.Attribute)
                and parent.func.attr == "get"
            )
        )
    ):
        cur = parent
        parent = ctx.parents.get(parent)
    if isinstance(parent, ast.Dict):
        for key, value in zip(parent.keys, parent.values):
            if (
                value is cur
                and isinstance(key, ast.Constant)
                and isinstance(key.value, str)
                and _ts_named(key.value)
            ):
                return True
        return False
    if isinstance(parent, ast.keyword):
        return parent.arg is not None and _ts_named(parent.arg)
    if isinstance(parent, ast.Assign):
        return any(_ts_named(dotted_name(t) or "") for t in parent.targets)
    return False


def _is_clock_adapter(node: ast.AST, ctx: FileContext) -> bool:
    """A function whose whole body is ``return time.time()`` is the
    injectable default of a clock seam, not ambient use."""
    fn = ctx.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    if isinstance(fn, ast.Lambda):
        return fn.body is node
    if fn is None:
        return False
    assert isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
    body = [
        s
        for s in fn.body
        if not (
            isinstance(s, ast.Expr)
            and isinstance(s.value, ast.Constant)
            and isinstance(s.value.value, str)
        )
    ]
    return (
        len(body) == 1
        and isinstance(body[0], ast.Return)
        and body[0].value is node
    )


def _seed_params(fn: ast.AST) -> list[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return []
    out = []
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        t = a.arg.lower()
        if t in _SEED_PARAM_TERMINALS or t.endswith(
            tuple("_" + m for m in _SEED_PARAM_TERMINALS)
        ):
            out.append(a.arg)
    return out


def _check_ambient_entropy(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node) or ""
        terminal = name.rsplit(".", 1)[-1]
        if name in _WALL_CLOCK:
            if _is_record_metadata(node, ctx) or _is_clock_adapter(node, ctx):
                continue
            yield ctx.violation(
                "DLC601",
                node,
                f"{name}() in a determinism-scoped path: wall-clock reads "
                "differ every run; thread the injected clock (VirtualClock "
                "or a clock callable) through instead — record metadata "
                'like `"started_ts": time.time()` stays legal',
            )
            continue
        if (
            name in _AMBIENT_ENTROPY
            or name in _ALWAYS_AMBIENT_CTORS
            or name.startswith("secrets.")
            or (
                name.startswith(_NP_RANDOM_PREFIXES)
                and terminal not in _NP_SEEDED_MEMBERS
            )
        ):
            yield ctx.violation(
                "DLC601",
                node,
                f"{name}() draws ambient process entropy in a "
                "determinism-scoped path; plumb a seeded RNG "
                "(random.Random(seed) / np.random.default_rng(seed)) or an "
                "injected id factory through the call path",
            )
            continue
        if name in _SEEDED_CTORS and not node.args and not node.keywords:
            fn = ctx.enclosing_function(node)
            if fn is not None and _seed_params(fn):
                continue  # the seed exists but is not plumbed: DLC604's find
            yield ctx.violation(
                "DLC601",
                node,
                f"{name}() with no seed falls back to OS entropy; construct "
                "it from an explicit seed",
            )


register(
    Rule(
        id="DLC601",
        name="ambient-entropy",
        doc="no random/uuid/secrets/wall-clock outside injected seams",
        check=_check_ambient_entropy,
        applies=_applies_determinism_paths,
        gate=GATE,
    )
)

# --- DLC602: order-sensitive folds over sets --------------------------------


def _is_set_expr(expr: ast.AST) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    return isinstance(expr, ast.Call) and call_name(expr) in {
        "set",
        "frozenset",
    }


def _set_typed_names(scope: ast.AST) -> set[str]:
    """Names bound to sets in this scope — and *only* ever to sets, so a
    rebinding to sorted(...) downstream clears the name."""
    sets: set[str] = set()
    dropped: set[str] = set()
    for n in walk_skipping_nested_functions(scope.body):
        target = None
        value = None
        if (
            isinstance(n, ast.Assign)
            and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Name)
        ):
            target, value = n.targets[0].id, n.value
        elif (
            isinstance(n, ast.AnnAssign)
            and isinstance(n.target, ast.Name)
            and n.value is not None
        ):
            target, value = n.target.id, n.value
        if target is None or value is None:
            continue
        (sets if _is_set_expr(value) else dropped).add(target)
    return sets - dropped


def _unordered_iter(it: ast.AST, set_names: set[str]) -> bool:
    if _is_set_expr(it):
        return True
    return isinstance(it, ast.Name) and it.id in set_names


def _check_set_order_fold(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    scopes: list[ast.AST] = [tree]
    scopes.extend(
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    )
    for scope in scopes:
        set_names = _set_typed_names(scope)
        for n in walk_skipping_nested_functions(scope.body):
            iters: list[ast.AST] = []
            if isinstance(n, ast.For):
                iters.append(n.iter)
            elif isinstance(
                n, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                iters.extend(gen.iter for gen in n.generators)
            for it in iters:
                if _unordered_iter(it, set_names):
                    yield ctx.violation(
                        "DLC602",
                        it,
                        "iterating a set folds in hash order, which is "
                        "salted per process (PYTHONHASHSEED) — a journal, "
                        "report, or ledger built from it differs run over "
                        "run; iterate sorted(...) with an explicit key",
                    )


register(
    Rule(
        id="DLC602",
        name="set-order-fold",
        doc="sets must be sorted before order-sensitive iteration",
        check=_check_set_order_fold,
        applies=_applies_determinism_paths,
        gate=GATE,
    )
)

# --- DLC603: hash()/id() escaping into persisted/compared values ------------


def _check_hash_escape(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name not in {"hash", "id"} or len(node.args) != 1:
            continue
        fn = ctx.enclosing_function(node)
        if fn is not None and fn.name == "__hash__":
            continue  # defining an object's hash is the one legal producer
        why = (
            "salted per process (PYTHONHASHSEED)"
            if name == "hash"
            else "a memory address, unique only within one process"
        )
        yield ctx.violation(
            "DLC603",
            node,
            f"{name}() is {why}; any persisted or compared value built on "
            "it differs across runs — use a stable digest (zlib.crc32 / "
            "hashlib) the way cluster.shards.shard_for_key does",
        )


register(
    Rule(
        id="DLC603",
        name="hash-escape",
        doc="hash()/id() must not reach persisted or compared values",
        check=_check_hash_escape,
        applies=_applies_determinism_paths,
        gate=GATE,
    )
)

# --- DLC604: seed-plumbing breaks -------------------------------------------


def _check_seed_plumbing(
    tree: ast.Module, ctx: FileContext
) -> Iterator[Violation]:
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        seedish = _seed_params(fn)
        if not seedish:
            continue
        for node in walk_skipping_nested_functions(fn.body):
            if (
                isinstance(node, ast.Call)
                and call_name(node) in _SEEDED_CTORS
                and not node.args
                and not node.keywords
            ):
                yield ctx.violation(
                    "DLC604",
                    node,
                    f"{fn.name}() takes `{seedish[0]}` but constructs an "
                    f"unseeded {call_name(node)}(): the seed never reaches "
                    "this RNG, so two same-seed runs diverge; pass the seed "
                    "(or a derived child seed) to the constructor",
                )


register(
    Rule(
        id="DLC604",
        name="seed-plumbing-break",
        doc="a function taking seed/rng must seed the RNGs it constructs",
        check=_check_seed_plumbing,
        applies=_applies_determinism_paths,
        gate=GATE,
    )
)
