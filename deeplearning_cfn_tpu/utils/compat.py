"""JAX version-compatibility shims.

The repo pins jax 0.4.37 (the TPU image's toolchain) but is written
against the modern mesh-context API: ``jax.set_mesh`` only exists from
jax 0.6 on.  On 0.4.x the equivalent is entering the :class:`Mesh`
itself as a context manager — semantically what every call site here
needs (make bare ``PartitionSpec`` sharding hints resolvable during
tracing).  One shim, used by every call site, so the version split
lives in exactly one place.
"""

from __future__ import annotations

from typing import ContextManager

import jax
from jax.sharding import Mesh


def set_mesh(mesh: Mesh) -> ContextManager:
    """``with set_mesh(mesh): ...`` — the mesh context on any jax.

    Prefers ``jax.set_mesh`` (jax >= 0.6, where it doubles as a context
    manager); falls back to the ``Mesh`` context manager on older jax
    (0.4.x), where ``with mesh:`` installs the same ambient mesh that
    in-model ``with_sharding_constraint`` hints resolve against.
    """
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def get_abstract_mesh() -> Mesh | None:
    """The ambient mesh context, or None when no mesh is active.

    jax >= 0.6: ``jax.sharding.get_abstract_mesh()`` (an AbstractMesh —
    empty when no context).  0.4.x: the ``with mesh:`` context lands in
    the thread-local resource env as the physical mesh.  Both carry the
    ``axis_names`` / ``shape`` surface the callers probe; the empty mesh
    normalizes to None so callers get one sentinel on every version.
    """
    if hasattr(jax.sharding, "get_abstract_mesh"):
        mesh = jax.sharding.get_abstract_mesh()
        return None if mesh is None or not mesh.axis_names else mesh
    from jax._src.mesh import thread_resources

    mesh = thread_resources.env.physical_mesh
    return None if mesh.empty else mesh


def shard_map(
    f,
    *,
    mesh: Mesh,
    in_specs,
    out_specs,
    axis_names=None,
    check_vma: bool | None = None,
):
    """``jax.shard_map`` with the modern keyword surface on any jax.

    jax >= 0.6 exposes ``jax.shard_map(f, mesh=..., axis_names=...,
    check_vma=...)``; 0.4.x has ``jax.experimental.shard_map.shard_map``
    with the older spelling — ``check_rep`` instead of ``check_vma``
    (same meaning: verify per-device values are replicated where specs
    claim), and ``auto=`` (the *complement* of ``axis_names``: mesh axes
    left to GSPMD instead of manual collectives).  Call sites write the
    modern form; this shim translates down when needed.
    """
    if hasattr(jax, "shard_map"):
        kwargs = {}
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    kwargs = {}
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
