"""One resilience policy for every retry loop in the stack.

Before this module the repo had three divergent retry idioms: the GCP
transport's unjittered ``2**attempt`` wall-clock loop, broker_client's
bare ``time.sleep(0.05)`` readiness poll, and recovery's give-up
counter.  Each one re-derived backoff, deadline, and error-classification
logic, and none was testable without real sleeps.  :class:`RetryPolicy`
is the single replacement:

* **decorrelated jitter** (``sleep = min(cap, uniform(base, prev * 3))``)
  instead of synchronized exponential waves — the classic thundering-herd
  fix, seeded so chaos soaks replay byte-for-byte;
* **monotonic deadlines** via :class:`~.timeouts.TimeoutBudget` — a retry
  loop inside a bootstrap phase draws from the same budget as everything
  else in that phase and raises the budget's typed error when starved;
* **typed classification** — exceptions are Retryable, Fatal, or
  classified by a callback; fatal errors propagate on the first throw
  instead of burning the whole attempt budget.

:class:`CircuitBreaker` layers on top for callers that talk to one
dependency repeatedly: after ``failure_threshold`` consecutive failures
the circuit opens, calls fail fast with :class:`CircuitOpen`, and a
``degraded`` event lands in the flight recorder so ``dlcfn events`` shows
the outage.  After ``reset_after_s`` the breaker half-opens and admits a
single probe.

Everything takes an injectable :class:`~.timeouts.Clock`; nothing in this
module reads the wall clock directly.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

from deeplearning_cfn_tpu.utils.logging import get_logger
from deeplearning_cfn_tpu.utils.timeouts import (
    BudgetExhausted,
    Clock,
    MonotonicClock,
    TimeoutBudget,
)

log = get_logger("dlcfn.resilience")


class Retryable(Exception):
    """Marker: an operation failed transiently and may be re-attempted."""


class Fatal(Exception):
    """Marker: an operation failed permanently; retrying cannot help."""


class RetryExhausted(RuntimeError):
    """Every attempt failed; carries the count and the final cause."""

    def __init__(self, attempts: int, last: BaseException | None):
        super().__init__(f"retries exhausted after {attempts} attempts: {last}")
        self.attempts = attempts
        self.last = last


class CircuitOpen(RuntimeError):
    """The circuit breaker is open; the call was refused without trying."""

    def __init__(self, name: str, failures: int):
        super().__init__(
            f"circuit {name!r} is open after {failures} consecutive failures"
        )
        self.name = name
        self.failures = failures


# Exception types that are transient by nature, used when a policy is
# built without an explicit classification.  TimeoutError is retryable
# here, but BudgetExhausted (a TimeoutError subclass) always propagates:
# the budget IS the deadline, retrying against it is self-defeating.
DEFAULT_RETRYABLE: tuple[type[BaseException], ...] = (
    Retryable,
    ConnectionError,
    TimeoutError,
    OSError,
)


@dataclass
class RetryPolicy:
    """Seeded decorrelated-jitter retry with typed classification.

    ``classify(exc)`` (when given) is consulted first and may return
    ``True`` (retry), ``False`` (fatal), or ``None`` (fall through to the
    ``fatal`` / ``retryable`` type tuples).  ``Fatal`` beats ``Retryable``
    when both match.  :class:`~.timeouts.BudgetExhausted` is never
    swallowed regardless of classification.
    """

    max_attempts: int = 4
    base_s: float = 0.05
    cap_s: float = 5.0
    clock: Clock = field(default_factory=MonotonicClock)
    seed: int | None = None
    retryable: tuple[type[BaseException], ...] = DEFAULT_RETRYABLE
    fatal: tuple[type[BaseException], ...] = (Fatal,)
    classify: Callable[[BaseException], bool | None] | None = None

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1: {self.max_attempts}")
        if self.base_s < 0 or self.cap_s < self.base_s:
            raise ValueError(
                f"need 0 <= base_s <= cap_s: base={self.base_s} cap={self.cap_s}"
            )
        self._rng = random.Random(self.seed)

    # -- backoff ---------------------------------------------------------
    def delays(self) -> Iterator[float]:
        """The (unbounded) jittered delay sequence this policy would sleep.

        Decorrelated jitter: each delay is uniform on ``[base, prev * 3]``
        clamped to ``cap_s``, so waits spread out instead of synchronizing
        into retry waves.  Every yielded value is in ``[base_s, cap_s]``.
        """
        prev = self.base_s
        while True:
            prev = min(self.cap_s, self._rng.uniform(self.base_s, prev * 3))
            yield prev

    # -- classification --------------------------------------------------
    def is_retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, BudgetExhausted):
            return False
        if self.classify is not None:
            verdict = self.classify(exc)
            if verdict is not None:
                return bool(verdict)
        if isinstance(exc, self.fatal):
            return False
        return isinstance(exc, self.retryable)

    # -- the loop --------------------------------------------------------
    def call(
        self,
        fn: Callable[[], Any],
        *,
        budget: TimeoutBudget | None = None,
        phase: str = "retry",
        on_retry: Callable[[int, float, BaseException], None] | None = None,
    ) -> Any:
        """Run ``fn`` under this policy; return its first successful value.

        Fatal errors propagate immediately; retryable ones are re-attempted
        up to ``max_attempts`` with jittered sleeps against the injected
        clock (or ``budget``, which raises its own typed error when the
        shared deadline runs out).  Exhaustion raises
        :class:`RetryExhausted` chained to the final cause.
        """
        delays = self.delays()
        last: BaseException | None = None
        for attempt in range(1, self.max_attempts + 1):
            if budget is not None:
                budget.check(phase)
            try:
                return fn()
            except BaseException as exc:
                if not self.is_retryable(exc):
                    raise
                last = exc
                if attempt >= self.max_attempts:
                    break
                delay = next(delays)
                if on_retry is not None:
                    on_retry(attempt, delay, exc)
                log.debug(
                    "retry %d/%d in %.3fs (%s): %s",
                    attempt,
                    self.max_attempts,
                    delay,
                    phase,
                    exc,
                )
                if budget is not None:
                    budget.sleep(delay, phase)
                else:
                    self.clock.sleep(delay)
        raise RetryExhausted(self.max_attempts, last) from last

    def wrap(self, fn: Callable[..., Any], **call_kwargs: Any) -> Callable[..., Any]:
        """``fn`` bound to this policy: the decorator form of :meth:`call`."""

        def _wrapped(*args: Any, **kwargs: Any) -> Any:
            return self.call(lambda: fn(*args, **kwargs), **call_kwargs)

        _wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return _wrapped


CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


@dataclass
class CircuitBreaker:
    """Trip after N consecutive failures; fail fast until a cooldown probe.

    State machine: CLOSED -> (threshold failures) -> OPEN -> (after
    ``reset_after_s`` on the injected clock) -> HALF_OPEN, which admits
    exactly one probe call — success closes the circuit, failure re-opens
    it for another cooldown.  Tripping records a ``degraded`` event to the
    flight recorder; recovery records ``degraded_recovered``.

    Thread-safe; the flight-recorder write happens outside the lock.
    """

    name: str = "dependency"
    failure_threshold: int = 5
    reset_after_s: float = 30.0
    clock: Clock = field(default_factory=MonotonicClock)

    def __post_init__(self) -> None:
        if self.failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1: {self.failure_threshold}"
            )
        self._lock = threading.Lock()
        self._failures = 0
        self._state = CLOSED
        self._opened_at = 0.0
        self._probing = False

    # -- observation -----------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state_locked()

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._failures

    def _effective_state_locked(self) -> str:
        if self._state == OPEN and (
            self.clock.now() - self._opened_at >= self.reset_after_s
        ):
            return HALF_OPEN
        return self._state

    # -- transitions -----------------------------------------------------
    def allow(self) -> bool:
        """Whether a call may proceed right now (claims the half-open probe)."""
        with self._lock:
            state = self._effective_state_locked()
            if state == CLOSED:
                return True
            if state == HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            was_open = self._state == OPEN
            self._failures = 0
            self._state = CLOSED
            self._probing = False
        if was_open:
            self._record("degraded_recovered")

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == OPEN:
                # A failed half-open probe: restart the cooldown.
                self._opened_at = self.clock.now()
            elif self._failures >= self.failure_threshold:
                self._state = OPEN
                self._opened_at = self.clock.now()
                tripped = True
        if tripped:
            self._record("degraded")

    def call(self, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` through the breaker; refused calls raise CircuitOpen."""
        if not self.allow():
            raise CircuitOpen(self.name, self.consecutive_failures)
        try:
            result = fn()
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return result

    def _record(self, kind: str) -> None:
        # Lazy import: utils must stay importable without the obs layer.
        try:
            from deeplearning_cfn_tpu.obs.recorder import get_recorder

            get_recorder().record(
                kind,
                breaker=self.name,
                failures=self.consecutive_failures,
                threshold=self.failure_threshold,
            )
        except Exception:  # pragma: no cover - journaling must never break callers
            log.debug("flight-recorder write failed for breaker %s", self.name)
        if kind == "degraded":
            log.warning(
                "circuit %r opened after %d consecutive failures",
                self.name,
                self.failure_threshold,
            )
        else:
            log.info("circuit %r recovered", self.name)
