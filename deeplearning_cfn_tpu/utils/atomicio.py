"""Atomic small-file writes: write-temp -> fsync -> rename.

Control-plane records (cluster contract, storage binding, checkpoints)
are read by *other* processes, possibly while the writer is being
killed — a torn ``write_text`` would hand the reader half a JSON
document.  ``os.replace`` on the same filesystem is atomic, so the
reader sees either the old complete file or the new complete file,
never a prefix.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from pathlib import Path
from typing import BinaryIO, Iterator


def atomic_write_bytes(path: str | Path, data: bytes) -> Path:
    path = Path(path)
    tmp = path.parent / f".{path.name}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    finally:
        if tmp.exists():
            tmp.unlink(missing_ok=True)
    return path


def atomic_write_text(path: str | Path, text: str) -> Path:
    return atomic_write_bytes(path, text.encode())


@contextmanager
def atomic_writer(path: str | Path) -> Iterator[BinaryIO]:
    """Streaming variant for writers too large (or too seek-happy) for
    one ``atomic_write_bytes`` buffer: yields a binary handle onto the
    temp file, and only a clean exit fsyncs + renames it into place.
    Any exception unlinks the temp — the destination is never touched,
    so readers see the old complete file or the new complete file,
    never a torn prefix (record shards: train/records.write_records)."""
    path = Path(path)
    tmp = path.parent / f".{path.name}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            yield fh
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    finally:
        if tmp.exists():
            tmp.unlink(missing_ok=True)
