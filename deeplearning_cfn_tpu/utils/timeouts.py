"""Timeout budgets for multi-phase bootstrap.

The reference threads a single wallclock budget through its bootstrap phases:
``setup_timeout = WAITCONDITION_TIMEOUT - MASTERLAUNCH_TIMEOUT`` and each
polling phase decrements what the previous one consumed
(dl_cfn_setup_v2.py:411-415, 322-323).  ``TimeoutBudget`` makes that
discipline an object: every phase draws from the same budget, and exhaustion
raises a typed error naming the phase that starved.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


class BudgetExhausted(TimeoutError):
    """Raised when a phase asks for time the budget no longer has."""

    def __init__(self, phase: str, total: float):
        super().__init__(
            f"timeout budget ({total:.0f}s total) exhausted during phase {phase!r}"
        )
        self.phase = phase


@dataclass
class TimeoutBudget:
    """A decrementing wallclock budget shared across bootstrap phases.

    ``clock`` is injectable so the choreography unit tests can run the full
    multi-phase protocol (with simulated 30 s polling sleeps) in microseconds.
    """

    total_s: float
    clock: "Clock" = field(default_factory=lambda: MonotonicClock())

    def __post_init__(self) -> None:
        self._start = self.clock.now()

    @property
    def remaining_s(self) -> float:
        return self.total_s - (self.clock.now() - self._start)

    @property
    def elapsed_s(self) -> float:
        return self.clock.now() - self._start

    def check(self, phase: str) -> None:
        if self.remaining_s <= 0:
            raise BudgetExhausted(phase, self.total_s)

    def sleep(self, seconds: float, phase: str) -> None:
        """Sleep (against the injected clock), then verify the budget."""
        self.clock.sleep(min(seconds, max(self.remaining_s, 0.0)))
        self.check(phase)


class Clock:
    def now(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class MonotonicClock(Clock):
    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    """Deterministic clock for tests: sleep() advances instantly."""

    def __init__(self, start: float = 0.0):
        self._t = start

    def now(self) -> float:
        return self._t

    def sleep(self, seconds: float) -> None:
        self._t += max(seconds, 0.0)

    def advance(self, seconds: float) -> None:
        self._t += seconds
