from deeplearning_cfn_tpu.utils.logging import get_logger  # noqa: F401
from deeplearning_cfn_tpu.utils.timeouts import TimeoutBudget  # noqa: F401
