"""Structured logging for the cluster control plane.

Keeps the reference's `time level file:line msg` line format
(dl_cfn_setup_v2.py:56-70 wrote to both /var/log/dl_cfn_setup.log and the
console with '%(asctime)s %(levelname)s %(filename)s:%(lineno)s %(message)s')
so operators migrating from the CFN stack see familiar logs.  Credentials are
scrubbed before logging, as the reference did for IAM role info
(dl_cfn_setup_v2.py:370-373).
"""

from __future__ import annotations

import logging
import os
import re
import sys

_FORMAT = "%(asctime)s %(levelname)s %(filename)s:%(lineno)s %(message)s"

_SECRET_RE = re.compile(
    r"(token|secret|password|credential|authorization)[\"']?\s*[:=]\s*[\"']?([^\s\"',}]+)",
    re.IGNORECASE,
)


def scrub(text: str) -> str:
    """Redact credential-looking values from a string before logging."""
    return _SECRET_RE.sub(lambda m: f"{m.group(1)}=<redacted>", text)


class _ScrubFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        # Scrub the fully rendered message, not just the format string —
        # secrets usually arrive via %-args (e.g. a cloud error detail
        # echoing request context).
        try:
            rendered = record.getMessage()
        except Exception:
            return True
        scrubbed = scrub(rendered)
        if scrubbed != rendered:
            record.msg = scrubbed
            record.args = ()
        return True


_configured: set[str] = set()


def get_logger(name: str = "dlcfn", log_file: str | None = None) -> logging.Logger:
    """Return a logger writing `time level file:line msg` lines.

    If ``log_file`` (or $DLCFN_LOG_FILE) is set, logs are duplicated there,
    mirroring the reference's dual console + /var/log/dl_cfn_setup.log sink.
    """
    logger = logging.getLogger(name)
    if name in _configured:
        return logger
    _configured.add(name)
    logger.setLevel(os.environ.get("DLCFN_LOG_LEVEL", "INFO").upper())
    logger.propagate = False
    fmt = logging.Formatter(_FORMAT)
    stream = logging.StreamHandler(sys.stderr)
    stream.setFormatter(fmt)
    stream.addFilter(_ScrubFilter())
    logger.addHandler(stream)
    log_file = log_file or os.environ.get("DLCFN_LOG_FILE")
    if log_file:
        fileh = logging.FileHandler(log_file)
        fileh.setFormatter(fmt)
        fileh.addFilter(_ScrubFilter())
        logger.addHandler(fileh)
    return logger
