"""Structured logging for the cluster control plane.

Keeps the reference's `time level file:line msg` line format
(dl_cfn_setup_v2.py:56-70 wrote to both /var/log/dl_cfn_setup.log and the
console with '%(asctime)s %(levelname)s %(filename)s:%(lineno)s %(message)s')
so operators migrating from the CFN stack see familiar logs.  Credentials are
scrubbed before logging, as the reference did for IAM role info
(dl_cfn_setup_v2.py:370-373).
"""

from __future__ import annotations

import logging
import os
import re
import sys

_FORMAT = "%(asctime)s %(levelname)s %(filename)s:%(lineno)s %(message)s"

_SECRET_RE = re.compile(
    r"(token|secret|password|credential|authorization)[\"']?\s*[:=]\s*[\"']?([^\s\"',}]+)",
    re.IGNORECASE,
)


def scrub(text: str) -> str:
    """Redact credential-looking values from a string before logging."""
    return _SECRET_RE.sub(lambda m: f"{m.group(1)}=<redacted>", text)


class _ScrubFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        # Scrub the fully rendered message, not just the format string —
        # secrets usually arrive via %-args (e.g. a cloud error detail
        # echoing request context).
        try:
            rendered = record.getMessage()
        except Exception:
            return True
        scrubbed = scrub(rendered)
        if scrubbed != rendered:
            record.msg = scrubbed
            record.args = ()
        return True


# name -> absolute paths of file sinks already attached.  Tracking the
# sinks (not just the name) is what lets a later get_logger(name,
# log_file=...) ATTACH the new file instead of silently ignoring it —
# the old early-return-on-configured bug dropped, e.g., the per-run log
# an agent requested after import-time get_logger() calls had already
# claimed the name.
_configured: dict[str, set[str]] = {}


def _add_file_sink(logger: logging.Logger, log_file: str) -> None:
    fmt = logging.Formatter(_FORMAT)
    fileh = logging.FileHandler(log_file)
    fileh.setFormatter(fmt)
    fileh.addFilter(_ScrubFilter())
    logger.addHandler(fileh)


def get_logger(name: str = "dlcfn", log_file: str | None = None) -> logging.Logger:
    """Return a logger writing `time level file:line msg` lines.

    If ``log_file`` (or $DLCFN_LOG_FILE on first configuration) is set,
    logs are duplicated there, mirroring the reference's dual console +
    /var/log/dl_cfn_setup.log sink.  Calling again with a *different*
    ``log_file`` attaches the new sink too (each file attaches once);
    it never silently drops the request.
    """
    logger = logging.getLogger(name)
    sinks = _configured.get(name)
    if sinks is None:
        sinks = _configured[name] = set()
        logger.setLevel(os.environ.get("DLCFN_LOG_LEVEL", "INFO").upper())
        logger.propagate = False
        fmt = logging.Formatter(_FORMAT)
        stream = logging.StreamHandler(sys.stderr)
        stream.setFormatter(fmt)
        stream.addFilter(_ScrubFilter())
        logger.addHandler(stream)
        # The env fallback applies only at first configuration: it is a
        # process-level default, not a per-call request.
        log_file = log_file or os.environ.get("DLCFN_LOG_FILE")
    if log_file:
        resolved = os.path.abspath(log_file)
        if resolved not in sinks:
            sinks.add(resolved)
            _add_file_sink(logger, log_file)
    return logger
