"""Named chaos scenarios: real components + seeded faults + invariants.

Each scenario drives PRODUCTION objects (Heartbeater,
BrokerLivenessWatcher, GoogleAuthTransport, StateCheckpointer,
ResilientSink, InMemoryQueue) through seeded fault schedules on virtual
clocks — no real sleeps, no wall-clock dependence — and records which
recovery invariants held.  ``run_scenario(name, seed)`` returns a
:class:`ScenarioReport` whose ``to_dict()`` is byte-identical across
runs with the same seed, which is what the regression tests and the
``dlcfn chaos`` CLI assert.

Catalog:

* ``silent-death`` — a worker stops beating under shuffled schedules;
  exactly-once termination + recovery (the PR-2 acceptance path, now
  fault-injected across many interleavings per seed).
* ``partition``   — short cuts must NOT kill anyone; long cuts must kill
  exactly once; healed workers resurrect; the metrics plane buffers
  through the outage (grace window) and message chaos cannot break
  at-least-once consumers.
* ``flaky-rpc``   — error bursts against the retry policy (jitter-bounded
  backoff on a fake clock) and a hard-down outage against the circuit
  breaker (fail-fast, half-open probe, re-trip).
* ``slow-disk``   — torn and slow checkpoint writes against the atomic
  write protocol and the local -> objectstore fallback chain.
* ``broker-failover`` — the primary broker dies under 1,000 heartbeating
  agents; the warm standby is promoted with zero lost INSTANCE_TERMINATE
  events and zero duplicate side effects (idempotent replay + re-send).
* ``split-brain``  — a partition isolates the primary; epoch fencing
  rejects every stale-leader write and the deposed node stands down.
* ``alert-storm``  — ~200 agents ship TELEM snapshots on their beats
  while the shipped SLO rules evaluate the fleet merge: silent deaths
  and stragglers each fire exactly once, firing alerts hold (no flap)
  through a broker failover whose telemetry loss is bounded by the
  unshipped journal tail, and healing resolves each alert exactly once.
* ``slice-loss-live`` — a whole slice dies mid-run under a REAL 2-slice
  SPMD trainer (8 virtual CPU devices): the debounced terminate burst
  must trigger exactly one live reshard onto the survivors with zero
  restarts, no lost steps, preserved global batch (grad-accum rescale)
  and loss continuity against an uninterrupted run; the forced-fallback
  variant must degrade to the checkpoint/restore path and still line up.
* ``sched-flash-crowd`` — multi-tenancy: a flash crowd pages the serve
  SLO while a replica dies mid-crowd; the fleet arbiter preempts the
  train job's non-anchor slice (live reshard, grad-accum rescale) and
  lends it to the serve pool, then reclaims and re-grows bit-safely
  when the page resolves — train loss continuity, exactly-once
  fire/resolve, zero lost requests, and a crash mid-preemption resumes
  from the journaled ledger without repeating the preemption.
* ``data-reshard-live`` — the data plane's turn: four hosts stream real
  DLC1 record shards, a slice dies mid-epoch, and the live reshard must
  hand the unfinished work to the survivors with every record consumed
  exactly once and byte-deterministic order per seed; a run stopped and
  resumed from the async sharded checkpointer's v3 envelope (state +
  stream cursor) must reproduce the unbroken run's loss sequence
  bit-identically, and a writer crashed at the manifest commit point
  must leave the previous checkpoint fully restorable.
* ``gauntlet`` — the composed incident (chaos/gauntlet.py): slice loss
  + broker shard failover in the SAME reshard pause + a writer crash
  at the manifest commit point, against ONE end-to-end workload, with
  the cross-subsystem invariants (exactly-once records, loss
  continuity, zero restarts, torn-write restorability, exactly-once
  alert transitions) checked together.
"""

from __future__ import annotations

import shutil
import tempfile
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from deeplearning_cfn_tpu.chaos.injectors import (
    ChaosQueue,
    FlakyOpener,
    RecordingClock,
    SlowDisk,
    TornDisk,
)
from deeplearning_cfn_tpu.utils.timeouts import FakeClock


#: Bump when the report wire shape changes.  v1 had no version field;
#: v2 added ``schema_version`` + the ``faults`` block, so gauntlet and
#: legacy scenario reports stay machine-diffable.
REPORT_SCHEMA_VERSION = 2


@dataclass
class ScenarioReport:
    """What a scenario proved (and what it could not).

    ``faults`` is the declarative fault block: one dict per injected
    fault (``{"kind", "at_step", ...}``), empty for legacy scenarios
    whose faults are implicit in the scenario body.
    """

    name: str
    seed: int
    passed: bool = True
    invariants: list[str] = field(default_factory=list)
    violations: list[str] = field(default_factory=list)
    details: dict[str, Any] = field(default_factory=dict)
    faults: list[dict[str, Any]] = field(default_factory=list)
    schema_version: int = REPORT_SCHEMA_VERSION

    def check(self, condition: bool, description: str) -> None:
        if condition:
            self.invariants.append(description)
        else:
            self.violations.append(description)
            self.passed = False

    def to_dict(self) -> dict[str, Any]:
        return {
            "schema_version": self.schema_version,
            "scenario": self.name,
            "seed": self.seed,
            "passed": self.passed,
            "invariants": list(self.invariants),
            "violations": list(self.violations),
            "details": dict(self.details),
            "faults": [dict(f) for f in self.faults],
        }


def _degraded_event_count() -> int:
    from deeplearning_cfn_tpu.obs.recorder import get_recorder

    return sum(
        1 for e in get_recorder().tail(4096) if e.get("kind") == "degraded"
    )


# --- silent-death ------------------------------------------------------------

_SD_PREFIX = ["beat:w0", "beat:w1", "poll"]
_SD_MIDDLE = (
    "beat:w0",
    "beat:w1",
    "beat:w1",
    "tick",
    "tick",
    "poll",
    "kill:w0",
    "poll",
)
_SD_DRAIN = ["beat:w1", "tick"] * 13 + ["poll"]


def silent_death(seed: int) -> ScenarioReport:
    """A worker dies silently under several seeded interleavings; the
    liveness plane must terminate it exactly once and recovery must
    replace it, with the survivor untouched."""
    from deeplearning_cfn_tpu.analysis.schedules import (
        HeartbeatChoreography,
        InvariantViolation,
        interleavings,
    )
    from deeplearning_cfn_tpu.obs.liveness import LivenessConfig, WorkerState

    report = ScenarioReport("silent-death", seed)
    schedules = interleavings(_SD_MIDDLE, count=6, seed=seed)
    terminations = 0
    for middle in schedules:
        choreo = HeartbeatChoreography(
            ["w0", "w1"],
            config=LivenessConfig(suspect_after_s=15.0, dead_after_s=60.0),
            tick_s=5.0,
        )
        try:
            choreo.run(_SD_PREFIX + list(middle) + _SD_DRAIN + ["recover", "poll"])
        except InvariantViolation as violation:
            report.check(False, f"ground-truth invariant: {violation}")
            continue
        states = choreo.states()
        report.check(
            states.get("w0") == WorkerState.DEAD.value,
            "silently-dead worker classified DEAD",
        )
        w0_terminations = choreo.terminated_workers().count("w0")
        terminations += w0_terminations
        report.check(
            w0_terminations == 1, "exactly one INSTANCE_TERMINATE for the victim"
        )
        report.check(
            states.get("w1") == WorkerState.ALIVE.value
            and "w1" not in choreo.terminated_workers(),
            "survivor stayed ALIVE and was never terminated",
        )
        report.check(
            choreo.recovered == {"w0": "w0+1"}
            and states.get("w0+1") == WorkerState.ALIVE.value,
            "recovery replaced the victim; replacement is beating",
        )
    report.details.update(
        schedules=len(schedules), terminations=terminations
    )
    return report


# --- partition ---------------------------------------------------------------


class _FlappingSink:
    """A metrics sink that raises OSError while ``down``."""

    def __init__(self) -> None:
        self.down = False
        self.records: list[dict] = []

    def write(self, record: dict) -> None:
        if self.down:
            raise OSError("sink unreachable (partition)")
        self.records.append(record)

    def close(self) -> None:
        pass


def partition(seed: int) -> ScenarioReport:
    """Network cuts: short ones must not kill, long ones must kill
    exactly once, healing resurrects; meanwhile the metrics plane rides
    out the outage inside its grace window and queue-level chaos cannot
    break the at-least-once consumer contract."""
    from deeplearning_cfn_tpu.analysis.schedules import (
        HeartbeatChoreography,
        InvariantViolation,
        interleavings,
    )
    from deeplearning_cfn_tpu.cluster.queue import InMemoryQueue
    from deeplearning_cfn_tpu.obs.liveness import LivenessConfig, WorkerState
    from deeplearning_cfn_tpu.train.metrics import MetricsOutage, ResilientSink

    report = ScenarioReport("partition", seed)

    # -- liveness under cut/heal ----------------------------------------
    short_cut = ("beat:w0", "beat:w1", "tick", "tick", "poll")
    for middle in interleavings(short_cut, count=4, seed=seed):
        choreo = HeartbeatChoreography(
            ["w0", "w1"],
            config=LivenessConfig(suspect_after_s=15.0, dead_after_s=60.0),
            tick_s=5.0,
        )
        try:
            # Short partition (10 virtual seconds < suspect threshold),
            # then heal: nobody may be terminated.
            choreo.run(
                _SD_PREFIX
                + ["cut:w0"]
                + list(middle)
                + ["heal:w0", "beat:w0", "poll"]
            )
            report.check(
                choreo.terminated_workers() == []
                and choreo.states().get("w0") == WorkerState.ALIVE.value,
                "short partition: no termination, worker ALIVE after heal",
            )
            # Long partition: w0 cut past dead_after (65 virtual s) while
            # w1 keeps beating -> exactly one terminate, then recovery,
            # then heal resurrects the original.
            choreo.run(
                ["cut:w0"]
                + ["beat:w0", "beat:w1", "tick"] * 13
                + ["poll", "recover", "heal:w0", "beat:w0", "poll"]
            )
        except InvariantViolation as violation:
            report.check(False, f"ground-truth invariant: {violation}")
            continue
        states = choreo.states()
        report.check(
            choreo.terminated_workers().count("w0") == 1,
            "long partition: exactly one INSTANCE_TERMINATE",
        )
        report.check(
            "w1" not in choreo.terminated_workers()
            and states.get("w1") == WorkerState.ALIVE.value,
            "worker on the healthy side never terminated",
        )
        report.check(
            states.get("w0") == WorkerState.ALIVE.value,
            "healed worker resurrected to ALIVE",
        )
        report.check(
            choreo.recovered.get("w0") == "w0+1"
            and states.get("w0+1") == WorkerState.ALIVE.value,
            "recovery brought up a replacement during the cut",
        )

    # -- trainer grace window -------------------------------------------
    clock = FakeClock()
    inner = _FlappingSink()
    sink = ResilientSink(inner, grace_s=120.0, clock=clock)
    sink.write({"step": 0})
    inner.down = True
    buffered = 0
    for step in range(1, 6):  # 5 writes over 50 virtual s of outage
        clock.advance(10.0)
        sink.write({"step": step})
        buffered = sink.buffered
    report.check(
        buffered == 5 and sink.degraded,
        "metrics outage inside grace window: writes buffered, no raise",
    )
    inner.down = False
    sink.write({"step": 6})
    report.check(
        sink.buffered == 0
        and not sink.degraded
        and [r["step"] for r in inner.records] == list(range(7)),
        "sink recovery flushed the buffer in order, nothing lost",
    )
    inner.down = True
    outage_raised = False
    try:
        for step in range(7, 30):
            clock.advance(30.0)
            sink.write({"step": step})
    except MetricsOutage:
        outage_raised = True
    report.check(
        outage_raised, "outage past the grace window raises typed MetricsOutage"
    )

    # -- message chaos vs at-least-once consumers -----------------------
    chaos_q = ChaosQueue(
        InMemoryQueue("chaos", clock=clock),
        seed=seed,
        drop_rate=0.1,
        delay_rate=0.2,
        delay_ops=2,
        duplicate_rate=0.2,
        reorder=True,
    )
    sent = 30
    for i in range(sent):
        chaos_q.send({"event": "worker-setup", "id": i})
    seen: set[int] = set()
    deliveries = 0
    for _sweep in range(50):
        messages = chaos_q.receive(max_messages=10, visibility_timeout_s=60.0)
        if not messages and not chaos_q._held:
            break
        for msg in messages:
            deliveries += 1
            seen.add(int(msg.body["id"]))
            chaos_q.delete(msg.receipt)
    chaos_q.flush_held()
    for _sweep in range(10):
        messages = chaos_q.receive(max_messages=10, visibility_timeout_s=60.0)
        if not messages:
            break
        for msg in messages:
            deliveries += 1
            seen.add(int(msg.body["id"]))
            chaos_q.delete(msg.receipt)
    report.check(
        len(seen) == sent - chaos_q.dropped,
        "every non-dropped message delivered despite delay/dup/reorder",
    )
    report.check(
        deliveries >= len(seen), "duplicates deduplicated by consumers"
    )
    report.details.update(
        dropped=chaos_q.dropped,
        delayed=chaos_q.delayed,
        duplicated=chaos_q.duplicated,
        deliveries=deliveries,
    )
    return report


# --- flaky-rpc ---------------------------------------------------------------


def flaky_rpc(seed: int) -> ScenarioReport:
    """Retryable error bursts against the unified RetryPolicy (jittered,
    clock-injected, deadline-safe) and a hard outage against the circuit
    breaker wired into GoogleAuthTransport."""
    from deeplearning_cfn_tpu.provision.gcp_transport import (
        GCPAPIError,
        GoogleAuthTransport,
    )
    from deeplearning_cfn_tpu.utils.resilience import CircuitBreaker, CircuitOpen

    report = ScenarioReport("flaky-rpc", seed)

    # -- burst phase: every call must eventually succeed ----------------
    clock = RecordingClock()
    opener = FlakyOpener(seed=seed, error_rate=0.45, reset_rate=0.15)
    transport = GoogleAuthTransport(
        project="chaos",
        token_provider=lambda: ("tok", 1e18),
        opener=opener,
        max_retries=8,
        backoff_s=0.05,
        clock=clock,
        seed=seed,
    )
    calls = 20
    successes = 0
    for i in range(calls):
        try:
            out = transport("GET", f"projects/p/locations/z/nodes/n{i}", None)
            successes += 1 if out == {"ok": True} else 0
        except GCPAPIError:
            pass
    report.check(
        successes == calls,
        "all calls succeeded through seeded 429/500/503/reset bursts",
    )
    base, cap = 0.05, 0.05 * 2**8
    report.check(
        all(base <= s <= cap for s in clock.sleeps),
        "every backoff sleep within jitter bounds [base_s, cap_s]",
    )
    report.check(
        len(set(round(s, 6) for s in clock.sleeps)) > 1
        if len(clock.sleeps) > 4
        else True,
        "backoff is jittered (not a fixed exponential ladder)",
    )
    report.check(
        clock.now() == sum(clock.sleeps),
        "all waiting happened on the injected clock (no real sleeps)",
    )

    # -- hard-down phase: the breaker must fail fast --------------------
    degraded_before = _degraded_event_count()
    hard_opener = FlakyOpener(seed=seed + 1, hard_down=True)
    breaker = CircuitBreaker(
        name="gcp-control-plane",
        failure_threshold=3,
        reset_after_s=60.0,
        clock=clock,
    )
    down = GoogleAuthTransport(
        project="chaos",
        token_provider=lambda: ("tok", 1e18),
        opener=hard_opener,
        max_retries=1,
        backoff_s=0.01,
        clock=clock,
        seed=seed,
        breaker=breaker,
    )
    outcomes: list[str] = []
    for i in range(6):
        try:
            down("GET", f"projects/p/locations/z/nodes/d{i}", None)
            outcomes.append("ok")
        except CircuitOpen:
            outcomes.append("circuit-open")
        except GCPAPIError:
            outcomes.append("api-error")
    requests_when_open = len(hard_opener.requests)
    report.check(
        outcomes == ["api-error"] * 3 + ["circuit-open"] * 3,
        "breaker tripped after 3 consecutive outages, then failed fast",
    )
    report.check(
        requests_when_open == 3 * 2,
        "no HTTP requests issued while the circuit is open",
    )
    report.check(
        _degraded_event_count() == degraded_before + 1,
        "breaker trip published a degraded event to the obs plane",
    )
    # -- half-open probe ------------------------------------------------
    clock.advance(61.0)
    try:
        down("GET", "projects/p/locations/z/nodes/probe", None)
        probe_outcome = "ok"
    except GCPAPIError:
        probe_outcome = "api-error"
    except CircuitOpen:
        probe_outcome = "circuit-open"
    report.check(
        probe_outcome == "api-error"
        and len(hard_opener.requests) == requests_when_open + 2
        and breaker.state == "open",
        "after cooldown exactly one probe ran, failed, and re-opened the circuit",
    )
    report.details.update(
        burst_requests=len(opener.requests),
        retries=len(opener.requests) - calls,
        backoff_sleeps=len(clock.sleeps),
        virtual_wait_s=round(sum(clock.sleeps), 6),
        hard_down_requests=len(hard_opener.requests),
    )
    return report


# --- slow-disk ---------------------------------------------------------------


def slow_disk(seed: int) -> ScenarioReport:
    """Torn and slow checkpoint writes: the atomic protocol must make
    torn writes unobservable, and the fallback chain must keep absorbing
    checkpoints (degrading local -> objectstore) instead of failing."""
    from deeplearning_cfn_tpu.provision.objectstore import LocalObjectStore
    from deeplearning_cfn_tpu.train.checkpoint import (
        FallbackCheckpointer,
        ObjectStoreCheckpointer,
        StateCheckpointer,
    )

    report = ScenarioReport("slow-disk", seed)
    root = Path(tempfile.mkdtemp(prefix="dlcfn-chaos-"))
    try:
        clock = FakeClock()
        torn = TornDisk(seed=seed, fail_rate=0.6)
        local = StateCheckpointer(root / "local", io=torn)
        remote = ObjectStoreCheckpointer(
            store=LocalObjectStore(root=root / "bucket")
        )
        degraded_before = _degraded_event_count()
        chain = FallbackCheckpointer(
            tiers=[("local", local), ("objectstore", remote)],
            failure_threshold=3,
            reset_after_s=1_000.0,
            clock=clock,
        )
        tiers_used: list[str] = []
        steps = 12
        for step in range(1, steps + 1):
            tiers_used.append(chain.save(step, {"step": step, "loss": 0.5 / step}))
        report.check(
            len(tiers_used) == steps,
            "every checkpoint landed on some tier (no failed saves escaped)",
        )
        report.check(torn.torn > 0, "torn writes actually injected")
        restored = chain.restore_latest()
        report.check(
            restored is not None and restored[1] == steps,
            "restore_latest returns the newest checkpoint across tiers",
        )
        report.check(
            restored is not None and restored[0]["step"] == steps,
            "restored state is intact (content hash verified)",
        )
        # Every checkpoint visible on the local tier must verify: torn
        # writes may only ever leave temp files, never half a committed
        # checkpoint.
        local_ok = all(
            local.io.read_bytes(local._file(s)) and local.restore_latest()
            for s in local.steps()
        )
        committed = list((root / "local").glob("state-*.json"))
        temps = list((root / "local").glob(".state-*"))
        report.check(
            local_ok and not temps,
            "no torn bytes observable: committed files verify, temps cleaned",
        )
        # Accounting invariant: the local tier's save count equals its
        # successful writes (attempted minus torn), and everything else
        # fell through to the objectstore — fallback fires exactly when
        # the local tier failed or its breaker quarantined it, never
        # spuriously.
        report.check(
            tiers_used.count("local") == torn.writes - torn.torn
            and tiers_used.count("objectstore")
            == steps - tiers_used.count("local"),
            "fallback engaged exactly when the local tier failed or was quarantined",
        )
        if chain.breaker("local").state != "closed":
            report.check(
                _degraded_event_count() > degraded_before,
                "local-tier breaker trip published a degraded event",
            )

        # -- slow disk: latency consumes virtual, not wall, time --------
        slow = SlowDisk(clock=clock, latency_s=7.0)
        slow_ck = StateCheckpointer(root / "slow", io=slow)
        t0 = clock.now()
        for step in (1, 2, 3):
            slow_ck.save(step, {"step": step})
        report.check(
            clock.now() - t0 == 21.0,
            "slow-disk latency consumed injected-clock time only",
        )
        report.check(
            slow_ck.restore_latest() == ({"step": 3}, 3),
            "slow writes still commit atomically and restore cleanly",
        )
        report.details.update(
            tiers_used=tiers_used,
            torn_writes=torn.torn,
            total_writes=torn.writes,
            local_steps=local.steps(),
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return report


# --- slice-loss-live ---------------------------------------------------------


def _journal_count(kind: str) -> int:
    from deeplearning_cfn_tpu.obs.recorder import get_recorder

    return sum(1 for e in get_recorder().tail(4096) if e.get("kind") == kind)


def slice_loss_live(seed: int) -> ScenarioReport:
    """A slice dies mid-run; training must survive WITHOUT a restart.

    Drives the real stack end-to-end on 8 virtual CPU devices: an SPMD
    FSDP trainer on a 2-slice hybrid mesh, the elasticity controller's
    terminate debouncer on a virtual clock, the LiveReshardManager's
    surviving-topology derivation, and the device-to-device reshard in
    ``Trainer.fit``'s pause seam.  Invariants: the 3-event terminate
    burst (with a duplicate) coalesces into exactly ONE reshard; the
    step count is monotone with no step lost or repeated; grad
    accumulation rescales 1 -> 2 so the global batch is preserved on
    half the devices; the loss curve matches an uninterrupted 8-device
    run within tolerance.  A second pass forces the fallback: the
    coordinator must journal ``reshard_fallback``, stop the episode
    cleanly, and the checkpoint/restore path onto the surviving mesh
    must line up with the same straight run.
    """
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # Must land before the backend first initializes; under pytest
        # conftest already set it, and `dlcfn chaos` reaches here before
        # any device query.
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax
    import numpy as np
    import flax.linen as nn

    from deeplearning_cfn_tpu.analysis.schedules import VirtualClock, interleavings
    from deeplearning_cfn_tpu.cluster.contract import ClusterContract
    from deeplearning_cfn_tpu.cluster.elasticity import (
        ElasticityController,
        GroupPolicy,
    )
    from deeplearning_cfn_tpu.cluster.recovery import LiveReshardManager
    from deeplearning_cfn_tpu.parallel.mesh import (
        MeshSpec,
        hybrid_mesh_for_slices,
        virtual_cpu_devices,
    )
    from deeplearning_cfn_tpu.provision.events import (
        EventBus,
        EventKind,
        LifecycleEvent,
    )
    from deeplearning_cfn_tpu.train.checkpoint import Checkpointer
    from deeplearning_cfn_tpu.train.data import SyntheticDataset
    from deeplearning_cfn_tpu.train.reshard import (
        LiveReshardCoordinator,
        mesh_topology,
        rescale_grad_accum,
    )
    from deeplearning_cfn_tpu.train.trainer import Trainer, TrainerConfig

    report = ScenarioReport("slice-loss-live", seed)
    devices = virtual_cpu_devices(8)

    class _MLP(nn.Module):
        # fc2's 256x256 kernel (65536 elems) clears the FSDP heuristic's
        # min_shard_elems, so the reshard moves genuinely sharded arrays.
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(256, name="fc1")(x))
            x = nn.relu(nn.Dense(256, name="fc2")(x))
            return nn.Dense(10, name="head")(x)

    def make_contract() -> ClusterContract:
        return ClusterContract.build(
            cluster_name="chaos-live",
            coordinator_ip="10.0.0.1",
            other_worker_ips=["10.0.0.2", "10.0.0.3", "10.0.0.4"],
            chips_per_worker=2,
            storage_mount="/mnt/none",
            slices={
                "s0": ["10.0.0.1", "10.0.0.2"],
                "s1": ["10.0.0.3", "10.0.0.4"],
            },
        )

    def mesh_for(contract: ClusterContract):
        n = contract.slices_count
        per_slice = contract.total_chips // max(n, 1)
        return hybrid_mesh_for_slices(
            n,
            ici_spec=MeshSpec.fsdp_parallel(per_slice),
            dcn_axis="dp",
            devices=devices[: contract.total_chips],
        )

    def make_config() -> TrainerConfig:
        return TrainerConfig(
            optimizer="adamw",
            learning_rate=1e-3,
            strategy="fsdp",
            matmul_precision="float32",
            log_every=1,
            grad_accum_steps=1,
        )

    total_steps = 8
    die_at = 3 + seed % 3  # the step boundary where the loss is visible
    dataset = lambda: SyntheticDataset(  # noqa: E731 - fresh iterator per run
        shape=(8, 8, 1), num_classes=10, batch_size=32, seed=seed
    )
    sample = next(iter(dataset().batches(1))).x

    class _Backend:
        """Event-plane-only backend: terminate handling never touches
        describe/launch, so the bus is all the controller needs here."""

        def __init__(self):
            self.events = EventBus()

    burst = ["10.0.0.3", "10.0.0.4", "10.0.0.3"]  # dup on purpose
    order = list(interleavings(burst, count=1, seed=seed)[0])

    def make_cluster(vclock):
        backend = _Backend()
        controller = ElasticityController(
            backend=backend,
            coordinator_queue_name="coord",
            slice_loss_window_s=10.0,
            clock=vclock,
        )
        controller.register(GroupPolicy("s0", 1, "sig-s0", coordinator=True))
        controller.register(GroupPolicy("s1", 1, "sig-s1"))
        controller.attach()
        manager = LiveReshardManager(make_contract())
        manager.attach(controller)
        return backend, controller, manager

    def eventful(src, backend, vclock):
        """Publish the slice-s1 terminate burst while batch ``die_at`` is
        being produced, then advance past the debounce window so the NEXT
        step boundary sees one coalesced loss."""
        for i, b in enumerate(src):
            if i == die_at:
                for ip in order:
                    backend.events.publish(
                        LifecycleEvent(
                            kind=EventKind.INSTANCE_TERMINATE,
                            group="s1",
                            instance_id=ip,
                            detail={"reason": "preempted"},
                        )
                    )
                    vclock.advance(0.5)
                vclock.advance(11.0)
            yield b

    def run_straight() -> list[float]:
        trainer = Trainer(_MLP(), mesh_for(make_contract()), make_config())
        state = trainer.init(jax.random.PRNGKey(seed), sample)
        _, losses = trainer.fit(
            state, dataset().batches(total_steps), steps=total_steps, prefetch=0
        )
        return losses

    straight = run_straight()

    # --- phase 1: live reshard ------------------------------------------
    vclock = VirtualClock()
    backend, controller, manager = make_cluster(vclock)
    coordinator = LiveReshardCoordinator(
        manager=manager,
        mesh_for=mesh_for,
        flush=controller.flush_slice_losses,
        clock=vclock,
    )
    trainer = Trainer(_MLP(), mesh_for(manager.contract), make_config())
    state = trainer.init(jax.random.PRNGKey(seed), sample)
    coalesced_before = _journal_count("slice_loss_coalesced")
    reshard_before = _journal_count("reshard")
    rescale_before = _journal_count("grad_accum_rescaled")
    state, live_losses = trainer.fit(
        state,
        eventful(dataset().batches(total_steps), backend, vclock),
        steps=total_steps,
        prefetch=0,
        reshard=coordinator,
    )
    report.check(
        len(live_losses) == total_steps
        and int(jax.device_get(state.step)) == total_steps,
        "no restart, no lost step: one fit() call trained every step "
        "through the slice death (monotone step count)",
    )
    report.check(
        coordinator.live_total == 1 and coordinator.fallback_total == 0,
        "the 3-event terminate burst (incl. a duplicate) coalesced into "
        "exactly one live reshard and zero fallbacks",
    )
    report.check(
        _journal_count("slice_loss_coalesced") - coalesced_before == 1
        and _journal_count("reshard") - reshard_before == 1,
        "journal shows one coalesced slice loss and one reshard event",
    )
    report.check(
        mesh_topology(trainer.mesh) == {"devices": 4, "axes": {"fsdp": 4}}
        and manager.contract.slices_count == 1
        and manager.contract.degraded,
        "trainer rebound to the surviving 4-device fsdp mesh and the "
        "contract degraded to the single surviving slice",
    )
    report.check(
        trainer.config.grad_accum_steps
        == rescale_grad_accum(1, 8, 4)
        == 2
        and _journal_count("grad_accum_rescaled") - rescale_before == 1,
        "grad accumulation rescaled 1 -> 2 (journaled), preserving the "
        "global batch of 32 on half the devices",
    )
    report.check(
        np.allclose(live_losses[:die_at], straight[:die_at], rtol=1e-5, atol=1e-6),
        "pre-loss losses identical to the uninterrupted run",
    )
    report.check(
        bool(
            np.allclose(live_losses, straight, rtol=5e-3, atol=1e-4)
        ),
        "loss continuity across the reshard: full curve matches the "
        "uninterrupted 8-device run within tolerance",
    )

    # --- phase 2: forced fallback to the checkpoint path ----------------
    root = Path(tempfile.mkdtemp(prefix="dlcfn-chaos-live-"))
    fallback_losses: list[float] = []
    restore_step = -1
    try:
        vclock2 = VirtualClock()
        backend2, controller2, manager2 = make_cluster(vclock2)
        forced = LiveReshardCoordinator(
            manager=manager2,
            mesh_for=mesh_for,
            flush=controller2.flush_slice_losses,
            clock=vclock2,
            force_fallback=True,
        )
        ck = Checkpointer(
            root / "ckpt", interval_s=None, every_steps=1, async_save=False
        )
        trainer1 = Trainer(_MLP(), mesh_for(manager2.contract), make_config())
        state1 = trainer1.init(jax.random.PRNGKey(seed), sample)
        fallback_before = _journal_count("reshard_fallback")
        state1, losses1 = trainer1.fit(
            state1,
            eventful(dataset().batches(total_steps), backend2, vclock2),
            steps=total_steps,
            prefetch=0,
            checkpointer=ck,
            reshard=forced,
        )
        report.check(
            forced.fallback_pending
            and forced.fallback_total == 1
            and _journal_count("reshard_fallback") - fallback_before == 1,
            "forced fallback journaled reshard_fallback and stopped the "
            "episode cleanly at the pause boundary",
        )
        report.check(
            len(losses1) == die_at,
            "fallback episode kept every loss up to the pause (graceful "
            "stop, not an exception)",
        )
        # The existing restore path, on the topology the coordinator
        # derived: a fresh trainer on the surviving mesh, orbax restoring
        # the 8-device checkpoint onto 4-device shardings.
        cfg2 = make_config()
        cfg2.grad_accum_steps = rescale_grad_accum(
            1, 8, mesh_for(forced.fallback_contract).size
        )
        trainer2 = Trainer(_MLP(), mesh_for(forced.fallback_contract), cfg2)
        template = trainer2.init(jax.random.PRNGKey(seed), sample)
        restored = ck.restore_latest(template)
        assert restored is not None
        state2, restore_step = restored
        report.check(
            restore_step == die_at,
            "checkpoint tier held the pause step: no training step lost "
            "across the fallback",
        )
        import itertools as _it

        remaining = total_steps - restore_step
        state2, losses2 = trainer2.fit(
            state2,
            _it.islice(dataset().batches(total_steps), restore_step, None),
            steps=remaining,
            prefetch=0,
        )
        fallback_losses = losses1 + losses2
        report.check(
            len(fallback_losses) == total_steps
            and int(jax.device_get(state2.step)) == total_steps,
            "fallback path completed the run: restore episode finished "
            "the remaining steps with a monotone step count",
        )
        report.check(
            bool(np.allclose(fallback_losses, straight, rtol=5e-3, atol=1e-4)),
            "loss continuity across the fallback: combined curve matches "
            "the uninterrupted run within tolerance",
        )
        ck.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    report.details.update(
        die_at_step=die_at,
        burst_order=order,
        grad_accum_after=trainer.config.grad_accum_steps,
        post_mesh=mesh_topology(trainer.mesh),
        straight_losses=[round(v, 6) for v in straight],
        live_losses=[round(v, 6) for v in live_losses],
        fallback_losses=[round(v, 6) for v in fallback_losses],
        fallback_restore_step=restore_step,
    )
    return report


# --- data-reshard-live -------------------------------------------------------


def _datastream_event_count(event: str) -> int:
    from deeplearning_cfn_tpu.obs.recorder import get_recorder

    return sum(
        1
        for e in get_recorder().tail(8192)
        if e.get("kind") == "datastream" and e.get("event") == event
    )


def data_reshard_live(seed: int) -> ScenarioReport:
    """The data plane survives a mid-epoch slice loss exactly-once, and a
    run resumed from a v3 envelope reproduces the unbroken loss sequence
    bit-identically.

    Phase 1 drives :class:`~deeplearning_cfn_tpu.train.datastream.
    DataStreamPlane` over REAL DLC1 shard files: four hosts (two slices)
    interleave batches, slice s1 dies mid-epoch, and
    ``plane.reshard(contract.surviving(["s1"]))`` redistributes the
    epoch's unfinished work over the survivors.  Invariants: every
    record is consumed exactly once (zero dropped, zero duplicated —
    asserted on record ids baked into the shards), the per-host shard
    assignment is an exact partition, and the whole consumption order is
    byte-deterministic per seed (the run replays identically).

    Phase 2 trains a real FSDP model (8 virtual CPU devices) from the
    record stream with :class:`~deeplearning_cfn_tpu.train.datastream.
    AsyncShardedCheckpointer` capturing the stream cursor in the v3
    envelope every step (``prefetch=0``, the bit-exact-resume mode).
    A run stopped at step K and restored — state from the sharded JSON
    codec, stream from ``last_stream_state`` — must reproduce the
    uninterrupted run's loss sequence EXACTLY, float for float.  A
    writer crashed at the manifest commit point (ManifestCrashDisk)
    must leave shard litter but no manifest, the previous checkpoint
    fully restorable, and the recorded v3 topology must gate a
    cross-topology restore.
    """
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax
    import numpy as np
    import flax.linen as nn

    from deeplearning_cfn_tpu.chaos.injectors import ManifestCrashDisk
    from deeplearning_cfn_tpu.cluster.contract import ClusterContract
    from deeplearning_cfn_tpu.parallel.mesh import (
        MeshSpec,
        hybrid_mesh_for_slices,
        virtual_cpu_devices,
    )
    from deeplearning_cfn_tpu.train.checkpoint import TopologyMismatch
    from deeplearning_cfn_tpu.train.data import SyntheticDataset
    from deeplearning_cfn_tpu.train.datastream import (
        AsyncShardedCheckpointer,
        DataStreamPlane,
        HostShardStream,
        assign_shards,
    )
    from deeplearning_cfn_tpu.train.records import (
        Field,
        RecordSpec,
        write_dataset,
        write_records,
    )
    from deeplearning_cfn_tpu.train.reshard import mesh_topology
    from deeplearning_cfn_tpu.train.trainer import Trainer, TrainerConfig

    report = ScenarioReport("data-reshard-live", seed)
    devices = virtual_cpu_devices(8)
    root = Path(tempfile.mkdtemp(prefix="dlcfn-chaos-data-"))
    try:
        # --- phase 1: exactly-once over a live reshard -------------------
        # Records carry their global id in ``y``, so "every record exactly
        # once" is literally ``sorted(seen) == range(total)``.
        spec = RecordSpec((Field("x", "uint8", (2,)), Field("y", "int32", ())))
        sizes = [17 + (3 * sid + seed) % 7 for sid in range(6)]  # uneven
        paths: list[Path] = []
        gid = 0
        for sid, n in enumerate(sizes):
            recs = []
            for _ in range(n):
                recs.append(
                    spec.encode(
                        x=np.array([gid % 251, gid % 7], dtype=np.uint8),
                        y=np.int32(gid),
                    )
                )
                gid += 1
            p = root / f"shard-{sid:02d}.dlc"
            write_records(p, spec, recs)
            paths.append(p)
        total = gid

        def make_contract() -> ClusterContract:
            return ClusterContract.build(
                cluster_name="chaos-data",
                coordinator_ip="10.0.0.1",
                other_worker_ips=["10.0.0.2", "10.0.0.3", "10.0.0.4"],
                chips_per_worker=2,
                storage_mount="/mnt/none",
                slices={
                    "s0": ["10.0.0.1", "10.0.0.2"],
                    "s1": ["10.0.0.3", "10.0.0.4"],
                },
            )

        def run_plane() -> tuple[dict[str, list[int]], dict]:
            contract = make_contract()
            plane = DataStreamPlane(
                contract, paths, spec, batch_size=5, seed=seed, loop=False
            )
            ids: dict[str, list[int]] = {h: [] for h in plane.hosts}
            iters = {h: plane.stream(h).batches() for h in plane.hosts}
            # Two interleaved rounds across all four hosts, then s1 dies
            # mid-epoch with partially-read shards on both sides.
            for _ in range(2):
                for h in list(plane.hosts):
                    b = next(iters[h], None)
                    if b is not None:
                        ids[h].extend(int(v) for v in b.y)
            plane.reshard(contract.surviving(["s1"]))
            for h in tuple(plane.hosts):  # survivors drain the epoch
                for b in iters[h]:
                    ids[h].extend(int(v) for v in b.y)
            snap = plane.journal_progress()
            return ids, snap

        hosts4 = make_contract().datastream_hosts()
        assigned = assign_shards(hosts4, len(paths), seed, 0)
        report.check(
            sorted(s for w in assigned.values() for s in w)
            == list(range(len(paths))),
            "per-host shard assignment is an exact partition of the "
            "shard set (every shard owned by exactly one host)",
        )
        reshard_before = _datastream_event_count("reshard")
        ids1, snap1 = run_plane()
        ids2, _snap2 = run_plane()
        seen = sorted(v for host_ids in ids1.values() for v in host_ids)
        report.check(
            seen == list(range(total)),
            "every record consumed exactly once across the live reshard "
            "(zero dropped, zero duplicated, including the lost hosts' "
            "pre-loss reads)",
        )
        report.check(
            ids1 == ids2,
            "consumption order is byte-deterministic per seed: the full "
            "run (including the reshard splice) replays identically",
        )
        report.check(
            _datastream_event_count("reshard") - reshard_before == 2,
            "each reshard journaled exactly one datastream reshard event",
        )
        report.check(
            snap1["records_total"] == total
            and snap1["hosts"] == 2
            and snap1["reshards"] == 1,
            "plane snapshot agrees with ground truth: all records "
            "counted, two survivors, one reshard",
        )

        # --- phase 2: bit-identical resume from the v3 envelope ----------
        class _Net(nn.Module):
            # fc2's 256x256 kernel clears the FSDP heuristic's
            # min_shard_elems, so the codec round-trips sharded arrays.
            @nn.compact
            def __call__(self, x):
                x = x.reshape((x.shape[0], -1))
                x = nn.relu(nn.Dense(256, name="fc1")(x))
                x = nn.relu(nn.Dense(256, name="fc2")(x))
                return nn.Dense(10, name="head")(x)

        mesh = hybrid_mesh_for_slices(
            2,
            ici_spec=MeshSpec.fsdp_parallel(4),
            dcn_axis="dp",
            devices=devices[:8],
        )

        def make_config() -> TrainerConfig:
            return TrainerConfig(
                optimizer="adamw",
                learning_rate=1e-3,
                strategy="fsdp",
                matmul_precision="float32",
                log_every=1,
                grad_accum_steps=1,
            )

        # 2 shards x 128 records = 256 = exactly 8 batches of 32: the
        # stop/resume seam lands mid-epoch, the run ends on the boundary.
        spec2 = RecordSpec.classification((8, 8, 1), "float32")
        tpaths: list[Path] = []
        for i in range(2):
            ds = SyntheticDataset(
                shape=(8, 8, 1), num_classes=10, batch_size=32, seed=seed * 7 + i
            )
            p = root / f"train-{i}.dlc"
            write_dataset(p, spec2, ds.batches(4), 4)
            tpaths.append(p)

        def train_stream(state=None) -> HostShardStream:
            return HostShardStream(
                tpaths,
                spec2,
                32,
                host="10.0.0.1",
                hosts=("10.0.0.1",),
                seed=seed,
                loop=True,
                state=state,
            )

        total_steps = 8
        stop = 3 + seed % 3
        sample = next(train_stream().batches(1)).x

        trainer_a = Trainer(_Net(), mesh, make_config())
        state_a = trainer_a.init(jax.random.PRNGKey(seed), sample)
        _, straight = trainer_a.fit(
            state_a, train_stream().batches(), steps=total_steps, prefetch=0
        )

        writes_before = _datastream_event_count("checkpoint_write")
        trainer_b = Trainer(_Net(), mesh, make_config())
        state_b = trainer_b.init(jax.random.PRNGKey(seed), sample)
        stream_b = train_stream()
        ck = AsyncShardedCheckpointer(
            root / "ackpt", every_steps=1, n_shards=3
        )
        state_b, losses1 = trainer_b.fit(
            state_b,
            stream_b.batches(),
            steps=stop,
            prefetch=0,
            checkpointer=ck,
            datastream=stream_b,
        )
        ck.wait()
        report.check(
            losses1 == straight[:stop],
            "pre-stop losses bit-identical to the uninterrupted run "
            "(same records, same arithmetic)",
        )
        report.check(
            ck.latest_step() == stop
            and _datastream_event_count("checkpoint_write") - writes_before >= 1,
            "the background writer committed the stop-step manifest "
            "(journaled checkpoint_write) without ever blocking a step",
        )
        trainer_c = Trainer(_Net(), mesh, make_config())
        template = trainer_c.init(jax.random.PRNGKey(seed), sample)
        restored = ck.restore_latest(template=template)
        report.check(restored is not None, "v3 manifest restored")
        assert restored is not None
        state_c, rstep = restored
        report.check(
            rstep == stop
            and ck.last_stream_state is not None
            and ck.last_stream_state["host"] == "10.0.0.1",
            "restore returned the stop step and the envelope's stream "
            "state for the right host",
        )
        stream_c = train_stream(state=ck.last_stream_state)
        report.check(
            stream_c.records_total == stop * 32,
            "resumed stream cursor sits exactly stop*batch records in — "
            "no replay, no skip",
        )
        _, losses2 = trainer_c.fit(
            state_c,
            stream_c.batches(),
            steps=total_steps - stop,
            prefetch=0,
        )
        report.check(
            losses1 + losses2 == straight,
            "resumed run reproduces the unbroken run's loss sequence "
            "bit-identically (exact float equality, the v3 acceptance "
            "bar: JSON codec + stream cursor both lossless)",
        )
        ck.close()

        # --- phase 2b: writer crash at the manifest commit point ---------
        disk = ManifestCrashDisk()
        failed_before = _datastream_event_count("checkpoint_write_failed")
        topo = mesh_topology(mesh)
        payload = {
            "w": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b": np.float32(0.5),
        }
        ck2 = AsyncShardedCheckpointer(
            root / "crash", every_steps=1, n_shards=2, io=disk
        )
        ck2.save(
            1,
            payload,
            mesh_topology=topo,
            stream_state={"host": "10.0.0.1", "cursor": 1},
        )
        ck2.wait()
        disk.arm()
        ck2.save(2, {"w": payload["w"] + 1.0, "b": np.float32(1.5)})
        ck2.wait()
        report.check(
            ck2.write_failures == 1
            and disk.crashes == 1
            and _datastream_event_count("checkpoint_write_failed")
            - failed_before
            == 1,
            "the armed crash fired exactly once at the manifest write and "
            "was journaled as checkpoint_write_failed (writer survived)",
        )
        report.check(
            not (root / "crash" / "ckpt-00000002.manifest.json").exists()
            and (
                root / "crash" / "ckpt-00000002.shard-00-of-02.json"
            ).exists(),
            "the crashed step left shard litter but NO manifest: the "
            "commit point never passed",
        )
        template2 = {"w": np.zeros((3, 4), np.float32), "b": np.float32(0.0)}
        r2 = ck2.restore_latest(template=template2, expected_topology=topo)
        report.check(
            r2 is not None
            and r2[1] == 1
            and np.array_equal(r2[0]["w"], payload["w"])
            and ck2.last_stream_state == {"host": "10.0.0.1", "cursor": 1},
            "the previous checkpoint (state, step, stream state) is "
            "fully restorable after the crash — bit-equal leaves",
        )
        mismatch = False
        try:
            ck2.restore_latest(
                template=template2,
                expected_topology={"devices": 4, "axes": {"fsdp": 4}},
            )
        except TopologyMismatch:
            mismatch = True
        report.check(
            mismatch,
            "the recorded v3 mesh topology gates cross-topology restores "
            "(TopologyMismatch, fail-fast)",
        )
        ck2.close()

        report.details.update(
            stop_step=stop,
            total_records=total,
            shard_sizes=sizes,
            straight_losses=[round(v, 6) for v in straight],
            resumed_losses=[round(v, 6) for v in losses1 + losses2],
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return report


# --- straggler ---------------------------------------------------------------


def straggler(seed: int) -> ScenarioReport:
    """One host runs injected-slow steps under seeded cross-host clock
    skew; the merged trace must recover the skews from heartbeat pairs,
    order events correctly, and name exactly the injected straggler."""
    import json
    import random

    from deeplearning_cfn_tpu.obs.recorder import FlightRecorder
    from deeplearning_cfn_tpu.obs.trace_export import (
        chrome_trace,
        merge_journals,
        straggler_table,
    )

    report = ScenarioReport("straggler", seed)
    rng = random.Random(seed)
    hosts = ["host-a", "host-b", "host-c"]
    slow_host = hosts[seed % len(hosts)]
    # Skew magnitude > the 1 s step spacing: a raw-timestamp merge is
    # GUARANTEED to interleave steps wrongly, so correct ordering after
    # alignment is a real proof, not luck.  Virtual clocks throughout —
    # every timestamp below is computed, never read from time.time().
    base = 1_700_000_000.0
    skews = {
        host: round(rng.uniform(2.0, 6.0) * rng.choice((-1, 1)), 6)
        for host in hosts
    }
    n_steps = 8
    slow_steps = set(range(2, 7))  # 5 of 8: a strict slowest-count majority
    slow_extra_ms = 40.0

    root = Path(tempfile.mkdtemp(prefix="dlcfn-chaos-straggler-"))
    try:
        # Supervisor journal (skew 0 = the reference clock): observes
        # each worker's beats 2 s after the true send instant.
        sup = FlightRecorder(path=root / "sup.jsonl")
        for host in hosts:
            for seq, t_send in enumerate((0.0, 10.0, 20.0), start=1):
                sup.record(
                    "heartbeat_observed",
                    ts=round(base + t_send + 2.0, 6),
                    host="sup",
                    pid=1,
                    worker=host,
                    seq=seq,
                    age_s=2.0,
                )
        sup.close()
        # Worker journals: every ts is the TRUE instant plus that host's
        # clock skew (caller fields override the recorder's identity).
        true_durations: dict[str, dict[int, float]] = {}
        for hi, host in enumerate(hosts):
            rec = FlightRecorder(path=root / f"{host}.jsonl")
            for seq, t_send in enumerate((0.0, 10.0, 20.0), start=1):
                rec.record(
                    "heartbeat_sent",
                    ts=round(base + t_send + skews[host], 6),
                    host=host,
                    pid=1,
                    worker=host,
                    seq=seq,
                )
            durations = {}
            for step in range(n_steps):
                dur_ms = 50.0 + hi * 1.0 + step * 0.5
                if host == slow_host and step in slow_steps:
                    dur_ms += slow_extra_ms
                durations[step] = dur_ms
                t_end = base + 100.0 + step * 1.0 + dur_ms / 1e3
                rec.record(
                    "step_time",
                    ts=round(t_end + skews[host], 6),
                    host=host,
                    pid=1,
                    worker=host,
                    profiler="train",
                    step=step,
                    steps=1,
                    total_ms=round(dur_ms, 3),
                    dispatch_ms=round(dur_ms * 0.1, 3),
                    host_ms=round(dur_ms * 0.05, 3),
                )
                rec.record(
                    "span",
                    ts=round(t_end + skews[host], 6),
                    host=host,
                    pid=1,
                    worker=host,
                    span="train_step",
                    seconds=round(dur_ms / 1e3, 6),
                    ok=True,
                )
            true_durations[host] = durations
            rec.close()

        paths = [root / "sup.jsonl"] + [root / f"{h}.jsonl" for h in hosts]

        def step_sequence(events):
            return [
                e["step"] for e in events if e.get("kind") == "step_time"
            ]

        raw_events, _ = merge_journals(paths, align=False)
        raw_seq = step_sequence(raw_events)
        report.check(
            raw_seq != sorted(raw_seq),
            "raw (unaligned) merge interleaves steps out of order — the "
            "injected skew is large enough to matter",
        )

        events, meta = merge_journals(paths, align=True)
        report.check(meta["reference"] == "sup", "supervisor journal is the reference clock")
        offsets = meta["offsets"]
        report.check(
            all(
                abs(offsets.get(host, 0.0) + skews[host]) < 1e-3
                for host in hosts
            ),
            "heartbeat pairs recover every host's clock offset (within 1 ms)",
        )
        aligned_seq = step_sequence(events)
        report.check(
            aligned_seq == sorted(aligned_seq),
            "aligned merge orders every step_time event by true step across hosts",
        )

        table = straggler_table(events)
        slowed_rows = [r for r in table["steps"] if r["step"] in slow_steps]
        report.check(
            bool(slowed_rows)
            and all(
                r["slowest"] == slow_host and r["margin_ms"] >= 30.0
                for r in slowed_rows
            ),
            "every injected-slow step names the slow host with a wide margin",
        )
        report.check(
            all(
                r["margin_ms"] < 10.0
                for r in table["steps"]
                if r["step"] not in slow_steps
            ),
            "steps without injection show no false wide-margin straggler",
        )
        report.check(
            table["top_straggler"] == slow_host,
            "the slowest-count majority names the injected host",
        )

        trace = chrome_trace(events)
        payload = json.dumps(trace, allow_nan=False)
        decoded = json.loads(payload)
        report.check(
            decoded.get("traceEvents") == trace["traceEvents"],
            "trace-event JSON is strict (allow_nan) and round-trips",
        )
        slices = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        report.check(
            bool(slices)
            and all(
                isinstance(e.get("ts"), (int, float))
                and isinstance(e.get("dur"), (int, float))
                and e.get("dur") >= 0
                and "pid" in e
                and "tid" in e
                for e in slices
            ),
            "every complete (X) slice carries ts/dur/pid/tid",
        )
        processes = {
            e["args"]["name"]
            for e in trace["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        report.check(
            processes == set(hosts) | {"sup"},
            "one trace process row per journal (3 workers + supervisor)",
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)

    report.details.update(
        slow_host=slow_host,
        slow_steps=sorted(slow_steps),
        skews_s=dict(sorted(skews.items())),
        recovered_offsets_s=dict(sorted(offsets.items())),
        top_straggler=table["top_straggler"],
        slowest_counts=table["slowest_counts"],
        straggler_steps=len(table["steps"]),
        trace_events=len(trace["traceEvents"]),
    )
    return report


# --- serve-replica-loss ------------------------------------------------------


def serve_replica_loss(seed: int) -> ScenarioReport:
    """A serving replica dies mid-traffic; no accepted request may be lost.

    Drives the real serving plane end-to-end on virtual time: two
    :class:`ServeReplica` engines behind a :class:`ServeFrontEnd`, seeded
    Poisson traffic from the load generator, replica liveness beating a
    :class:`SimBroker`, and the elasticity controller's
    ``on_instance_loss`` seam wired to the front-end's failover.  Mid-run
    an ``INSTANCE_TERMINATE`` for a seed-picked victim kills one replica;
    its in-flight requests replay onto the survivor with their original
    arrival times.

    Invariants: every accepted request completes (zero loss); greedy
    outputs are identical to an undisturbed single-engine reference run
    (failover is invisible in content, visible only in latency); p99
    per-token latency and p99 TTFT stay inside the SLO even through the
    disruption; the victim's heartbeat goes silent while the survivor
    keeps beating; the failover is journaled exactly once.
    """
    from deeplearning_cfn_tpu.analysis.schedules import (
        SimBroker,
        SimBrokerConnection,
        VirtualClock,
    )
    from deeplearning_cfn_tpu.cluster.elasticity import (
        ElasticityController,
        GroupPolicy,
    )
    from deeplearning_cfn_tpu.provision.events import (
        EventBus,
        EventKind,
        LifecycleEvent,
    )

    # Import order: the serve engine imports jax; chaos runs under
    # `dlcfn chaos` where conftest's XLA flags may be absent.  The engine
    # is single-device (colocated), so no device-count guard is needed.
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning_cfn_tpu.models.llama import LlamaConfig, init_params
    from deeplearning_cfn_tpu.serve import (
        ContinuousBatchingEngine,
        ServeConfig,
        ServeFrontEnd,
        ServeReplica,
        TrafficConfig,
        run_load,
    )

    # SLOs asserted through the disruption (virtual milliseconds; the
    # traffic model charges 10ms/step + 4ms/prefill, so these bound
    # QUEUEING, deterministically, not host FLOPs).
    slo_per_token_p99_ms = 150.0
    slo_ttft_p99_ms = 250.0

    report = ScenarioReport("serve-replica-loss", seed)
    cfg = dataclasses.replace(
        LlamaConfig.tiny(vocab_size=64, seq_len=64), dtype=jnp.float32
    )
    params = init_params(cfg, jax.random.key(0))
    scfg = ServeConfig(
        num_slots=4, block_size=4, blocks_per_slot=8, prefill_len=16
    )
    tcfg = TrafficConfig(requests=80, seed=seed)

    def make_engine(clock, name):
        return ContinuousBatchingEngine(
            cfg, params, scfg, clock=clock, name=name, journal=False
        )

    # --- undisturbed single-engine reference (expected outputs) --------
    ref_clock = VirtualClock()
    reference = run_load(make_engine(ref_clock, "ref"), tcfg, ref_clock)

    # --- live run: 2 replicas, broker liveness, terminate mid-traffic --
    vclock = VirtualClock()
    broker = SimBroker(vclock)

    class _KV:
        """Broker KV verbs a register() needs (a BrokerConnection.set
        stand-in; same key/value contract)."""

        def __init__(self):
            self.table: dict[str, str] = {}

        def set(self, key: str, value: str) -> None:
            self.table[key] = value

    class _Backend:
        """Event-plane-only backend (the elasticity controller only
        touches .events for terminate handling)."""

        def __init__(self):
            self.events = EventBus()

    kv = _KV()
    replicas = [
        ServeReplica(
            make_engine(vclock, f"rep{i}"),
            f"rep{i}",
            group="serve",
            connection_factory=lambda: SimBrokerConnection(broker),
        )
        for i in range(2)
    ]
    for r in replicas:
        r.register(kv)
    frontend = ServeFrontEnd(replicas)

    backend = _Backend()
    controller = ElasticityController(
        backend=backend,
        coordinator_queue_name="coord",
        on_instance_loss=frontend.on_instance_loss,
        clock=vclock,
    )
    controller.register(GroupPolicy("serve", 1, "sig-serve"))
    controller.attach()

    victim = f"rep{seed % 2}"
    survivor = f"rep{1 - seed % 2}"
    kill_step = 20 + seed % 7
    failover_before = _journal_count("serve_failover")
    lost_before = _journal_count("instance_lost")
    killed: list[str] = []

    def on_step(step: int) -> None:
        # Live replicas beat every scheduler step; a failed one falls out
        # of the front-end and goes silent — exactly what the liveness
        # watcher would escalate.
        for rep in frontend.replicas.values():
            rep.beat()
        if step == kill_step and not killed:
            killed.append(victim)
            backend.events.publish(
                LifecycleEvent(
                    kind=EventKind.INSTANCE_TERMINATE,
                    group="serve",
                    instance_id=f"serve/{victim}",
                    detail={"reason": "chaos"},
                )
            )

    load = run_load(frontend, tcfg, vclock, on_step=on_step)

    report.check(
        load.completed == tcfg.requests and not frontend.lost_requests(),
        f"zero lost accepted requests: all {tcfg.requests} completed "
        "through the replica death",
    )
    report.check(
        frontend.failed == [victim]
        and f"serve/{victim}" in controller.lost_instances,
        "the terminate event reached the front-end through the "
        "elasticity controller's on_instance_loss seam",
    )
    report.check(
        load.completions == reference.completions,
        "greedy outputs identical to the undisturbed single-engine "
        "reference — failover is invisible in content",
    )
    per_token_p99 = load.latency_per_token_ms.get("p99", float("inf"))
    ttft_p99 = load.ttft_ms.get("p99", float("inf"))
    report.check(
        per_token_p99 <= slo_per_token_p99_ms,
        f"p99 per-token latency {per_token_p99}ms inside the "
        f"{slo_per_token_p99_ms}ms SLO through the disruption",
    )
    report.check(
        ttft_p99 <= slo_ttft_p99_ms,
        f"p99 TTFT {ttft_p99}ms inside the {slo_ttft_p99_ms}ms SLO "
        "through the disruption",
    )
    victim_silence = broker.silence_s(f"serve/{victim}")
    survivor_silence = broker.silence_s(f"serve/{survivor}")
    report.check(
        victim_silence is not None
        and survivor_silence is not None
        and victim_silence > survivor_silence,
        "victim's heartbeat went silent while the survivor kept beating",
    )
    report.check(
        _journal_count("serve_failover") - failover_before == 1
        and _journal_count("instance_lost") - lost_before == 1,
        "journal shows exactly one failover and one instance loss",
    )
    report.check(
        sorted(kv.table) == ["serve/serve/rep0", "serve/serve/rep1"],
        "both replicas registered in the broker KV table",
    )
    checksum = int(
        np.sum(
            [np.sum(tokens, dtype=np.int64) for tokens in load.completions.values()],
            dtype=np.int64,
        )
    )
    report.details.update(
        victim=victim,
        kill_step=kill_step,
        replayed=sorted(frontend.replayed),
        requests=tcfg.requests,
        steps=load.steps,
        duration_s=load.duration_s,
        throughput_rps=load.throughput_rps,
        tokens_out=load.tokens_out,
        output_checksum=checksum,
        ttft_p99_ms=ttft_p99,
        per_token_p99_ms=per_token_p99,
        reference_steps=reference.steps,
        victim_silence_s=round(victim_silence or 0.0, 6),
    )
    return report


# --- broker-failover ---------------------------------------------------------


def broker_failover(seed: int) -> ScenarioReport:
    """The primary broker dies mid-traffic under 1,000 heartbeating
    agents; the warm standby is promoted and NOTHING is lost.

    Runs :func:`soak_failover` — real Heartbeaters and a real
    BrokerLivenessWatcher over the replicated sim pair on virtual time —
    and pins the acceptance invariants: every silently-killed agent is
    terminated exactly once (zero lost, zero spurious, zero premature
    INSTANCE_TERMINATE events), idempotent re-sends across the switch
    produce zero duplicate side effects, and the promotion fenced a
    strictly-higher epoch with no fenced writes (no split brain here).
    """
    from deeplearning_cfn_tpu.analysis.schedules import soak_failover

    report = ScenarioReport("broker-failover", seed)
    soak = soak_failover(agents=1000, seed=seed)
    report.check(
        soak["terminated"] == soak["killed"]
        and soak["lost_terminates"] == 0,
        "zero lost INSTANCE_TERMINATE events across the failover "
        f"({soak['killed']} killed agents all terminated)",
    )
    report.check(
        soak["spurious_terminates"] == 0,
        "no live agent was spuriously terminated during the broker outage",
    )
    report.check(
        soak["duplicate_terminates"] == 0,
        "each killed agent terminated exactly once (no duplicates)",
    )
    report.check(
        soak["premature_terminates"] == 0,
        "every termination happened at silence >= dead_after_s "
        "(ground truth from the replicated heartbeat table)",
    )
    report.check(
        soak["duplicate_sends"] == 0
        and soak["work_depth"] == soak["senders"],
        "idempotent re-sends across the switch: every request id landed "
        "exactly once (replayed or re-sent, never both)",
    )
    report.check(
        soak["epoch"] == 1 and soak["fenced_writes"] == 0,
        "standby promoted to a strictly-higher epoch; no write was fenced "
        "(single leader throughout)",
    )
    report.check(
        soak["unshipped_at_kill"] > 0
        and soak["replayed_seq"] == soak["journaled_seq"] - soak["unshipped_at_kill"],
        "the kill left a real unshipped journal tail and the standby "
        "replayed exactly the shipped prefix",
    )
    report.check(
        soak["client_failovers"] == soak["senders"],
        "every re-sending client failed over past the dead primary",
    )
    report.details.update(soak)
    return report


# --- split-brain -------------------------------------------------------------


def split_brain(seed: int) -> ScenarioReport:
    """A partition isolates the primary; the standby is promoted; the
    deposed primary keeps accepting writes on its side.  Epoch fencing
    must reject every one of its stale replication entries, the deposed
    node must stand down on contact with the higher epoch, and healed
    clients' re-sends must land exactly once on the true primary."""
    import random as _random

    from deeplearning_cfn_tpu.analysis.schedules import (
        FailoverSimConnection,
        ReplicatedSimBroker,
        SimFenced,
        SimNotPrimary,
        VirtualClock,
    )

    report = ScenarioReport("split-brain", seed)
    rng = _random.Random(seed)
    clock = VirtualClock()
    cluster = ReplicatedSimBroker(clock)

    # Healthy traffic, fully replicated, before the partition.
    pre = 20
    for i in range(pre):
        cluster.primary.send_idempotent("work", f"pre-{i}".encode(), f"pre-{i}")
        clock.advance(0.5)
    cluster.stream()
    report.check(
        cluster.standby.sync_seq == cluster.primary.seq == pre,
        "standby fully caught up before the partition",
    )

    # The operator side can't reach the primary and promotes the standby.
    epoch = cluster.promote_standby()
    report.check(
        epoch == 1 and cluster.standby.role == "primary",
        "standby promoted to a strictly-higher epoch",
    )

    # Dual leader: the deposed primary still believes it leads and keeps
    # accepting writes from clients on its side of the partition.
    stale = [f"stale-{seed}-{i}" for i in range(7 + rng.randrange(5))]
    for rid in stale:
        cluster.primary.send_idempotent("work", rid.encode(), rid)
        clock.advance(0.5)
    report.check(
        cluster.primary.role == "primary" and cluster.primary.epoch == 0,
        "deposed primary still claims leadership at the stale epoch "
        "(the dangerous window is real)",
    )

    # Its replication stream must be fenced entry by entry.
    fenced_raises = 0
    for entry in cluster.pending():
        try:
            cluster.standby.sync(entry["epoch"], entry["seq"], entry["frame"])
        except SimFenced:
            fenced_raises += 1
    report.check(
        fenced_raises == len(stale)
        and cluster.standby.fenced == len(stale),
        f"epoch fencing rejected every stale-primary write "
        f"({len(stale)} of {len(stale)})",
    )
    true_rids = {rid for rid, _body in cluster.standby.queues.get("work", [])}
    report.check(
        not (set(stale) & true_rids) and len(true_rids) == pre,
        "no stale write leaked into the promoted primary's state",
    )

    # First contact with the higher epoch demotes the deposed node (the
    # receive-side half: a SYNC from the new term stands it down).
    cluster.standby.set("leader", b"broker-b")
    new_entry = cluster.standby.journal[-1]
    cluster.primary.sync(
        new_entry["epoch"], cluster.primary.seq + 1, new_entry["frame"]
    )
    report.check(
        cluster.primary.role == "standby"
        and cluster.primary.epoch == epoch,
        "deposed primary demoted itself on first higher-epoch contact",
    )
    demoted_rejects = False
    try:
        cluster.primary.send_idempotent("work", b"late", "post-demote")
    except SimNotPrimary:
        demoted_rejects = True
    report.check(
        demoted_rejects, "demoted node rejects client writes (not primary)"
    )

    # Heal: clients from the wrong side re-send their request ids through
    # the failover path — exactly-once effects on the true primary, even
    # with a duplicate retry round.
    conn = FailoverSimConnection(cluster.nodes())
    for _round in range(2):
        for rid in stale:
            conn.send_idempotent("work", rid.encode(), rid)
    conn.close()
    work = cluster.standby.queues.get("work", [])
    rid_list = [rid for rid, _body in work]
    report.check(
        len(rid_list) == len(set(rid_list))
        and set(stale) <= set(rid_list)
        and len(work) == pre + len(stale),
        "healed re-sends landed exactly once on the true primary",
    )
    report.check(
        conn.failovers == 2 * len(stale),
        "every healed send failed over past the demoted node",
    )
    report.details.update(
        pre_partition_writes=pre,
        stale_writes=len(stale),
        fenced=cluster.standby.fenced,
        epoch=epoch,
        true_primary_depth=len(work),
        demoted_epoch=cluster.primary.epoch,
    )
    return report


# --- shard-failover ----------------------------------------------------------


def shard_failover(seed: int) -> ScenarioReport:
    """One shard's primary dies mid-traffic in a sharded fleet; the other
    shards never notice, and the failed pair auto-heals back to a
    replicating primary+standby.

    Runs :func:`soak_fleet` — real Heartbeaters and per-shard
    BrokerLivenessWatchers over a consistent-hash-sharded sim fleet on
    virtual time — and pins the sharded acceptance invariants on top of
    the single-pair ones: a failover on one shard stalls ONLY that
    shard's clients (zero failovers on connections routed elsewhere),
    every pair ends the run healed (a degraded pair is never steady
    state), and the concurrent split-brain on another shard is fenced
    without a single diverged entry.
    """
    from deeplearning_cfn_tpu.analysis.schedules import soak_fleet

    report = ScenarioReport("shard-failover", seed)
    soak = soak_fleet(
        agents=2000,
        shards=4,
        seed=seed,
        kill_count=50,
        senders=100,
        failover_shards=1,
        unshipped_tail=5,
        stale_writes=3,
    )
    report.check(
        soak["terminated"] == soak["killed"]
        and soak["lost_terminates"] == 0
        and soak["spurious_terminates"] == 0
        and soak["duplicate_terminates"] == 0
        and soak["premature_terminates"] == 0,
        f"exactly-once liveness verdicts across the shard failover "
        f"({soak['killed']} killed agents, {soak['agents']} total)",
    )
    report.check(
        soak["delivered"] == soak["senders"] + soak["stale_writes"]
        and soak["duplicate_sends"] == 0,
        "idempotent re-sends across the shard switch: every request id "
        "landed exactly once on its shard's acting primary",
    )
    report.check(
        soak["unaffected_shard_failovers"] == 0,
        "a single-shard failover stalled only that shard: clients routed "
        "to healthy shards never failed over",
    )
    report.check(
        all(epoch == 1 for epoch in soak["epochs"].values())
        and soak["unshipped_at_kill"] > 0,
        "each failed shard promoted to a strictly-higher epoch with a "
        "real unshipped journal tail at the kill",
    )
    report.check(
        soak["degraded_pairs"] == 0
        and soak["healed_pairs"] == soak["shards"]
        and soak["reprovisions"] == len(soak["failover_shards"]) + 1,
        "auto-heal restored a replicating primary+standby pair on every "
        "shard (no degraded pair as steady state)",
    )
    report.check(
        soak["diverged_entries"] == 0 and soak["fenced_streams"] == 1,
        "the concurrent split-brain shard fenced its deposed primary's "
        "stream; zero entries diverged",
    )
    report.details.update(soak)
    return report


# --- degraded-pair-heal ------------------------------------------------------


def degraded_pair_heal(seed: int) -> ScenarioReport:
    """A promoted standby must not stay alone: after a failover the new
    primary re-provisions a fresh standby and replication lag drains to
    zero — the self-healing half of the broker failover ladder.

    Drives one replicated sim pair through kill -> promote ->
    re-provision and pins that the replay of the promoted journal into
    the fresh standby (old-term entries shipped under the new term) is
    never fenced, converges to zero pending entries, and that
    replication of NEW writes resumes on the healed pair."""
    import random as _random

    from deeplearning_cfn_tpu.analysis.schedules import (
        ReplicatedSimBroker,
        VirtualClock,
    )

    report = ScenarioReport("degraded-pair-heal", seed)
    rng = _random.Random(seed)
    clock = VirtualClock()
    cluster = ReplicatedSimBroker(clock)

    # Replicated traffic, then a tail the standby never saw.
    pre = 30 + rng.randrange(10)
    tail = 3 + rng.randrange(4)
    for i in range(pre + tail):
        cluster.primary.send_idempotent("work", f"r-{i}".encode(), f"r-{i}")
        clock.advance(0.5)
    cluster.stream(max_entries=pre)
    cluster.kill_primary()
    epoch = cluster.promote_standby()
    acting = cluster.active()
    report.check(
        epoch == 1
        and acting is cluster.standby
        and acting.sync_seq == pre,
        "standby promoted at a strictly-higher epoch holding exactly the "
        f"shipped prefix ({pre} of {pre + tail} writes)",
    )

    # The degraded window is real: the promoted node is alone.
    report.check(
        cluster.primary is not acting or cluster.standby is acting,
        "pair is degraded after promotion (promoted node has no standby)",
    )

    # Auto-heal: fresh standby at the promoted epoch, full journal replay.
    fresh = cluster.reprovision_standby()
    report.check(
        cluster.primary is acting
        and cluster.standby is fresh
        and fresh.role == "standby"
        and fresh.epoch == epoch,
        "re-provisioned standby joined at the promoted epoch",
    )
    report.check(
        fresh.fenced == 0,
        "replaying old-term journal entries under the new term was never "
        "fenced (sender-epoch stamping)",
    )
    report.check(
        len(cluster.pending()) == 0 and fresh.sync_seq == acting.seq,
        "replication lag drained to zero within the scenario",
    )
    healed_rids = {rid for rid, _body in fresh.queues.get("work", [])}
    report.check(
        healed_rids == {f"r-{i}" for i in range(pre)},
        "fresh standby state matches the acting primary's exactly "
        "(the dead node's unshipped tail is gone from both)",
    )

    # The healed pair replicates new writes like any healthy pair.
    post = 5 + rng.randrange(5)
    for i in range(post):
        acting.send_idempotent("work", f"post-{i}".encode(), f"post-{i}")
        clock.advance(0.5)
    shipped = cluster.stream()
    report.check(
        shipped == post
        and fresh.sync_seq == acting.seq
        and fresh.fenced == 0,
        "replication of new writes resumed on the healed pair",
    )
    report.details.update(
        pre_writes=pre,
        unshipped_tail=tail,
        post_writes=post,
        epoch=epoch,
        reprovisions=cluster.reprovisions,
        standby_seq=fresh.sync_seq,
    )
    return report


# --- alert-storm -------------------------------------------------------------


def alert_storm(seed: int) -> ScenarioReport:
    """The full telemetry plane under a correlated incident: ~200 agents
    piggyback TELEM snapshots on their heartbeats at a replicated sim
    broker while the SHIPPED SLO rules (obs/slo.DEFAULT_RULES) evaluate
    the fleet merge every round on virtual time.

    Storyline: a seeded subset dies silently (dead-fraction must fire
    exactly once, after its for-window), a second subset turns straggler
    (step-time p99 must fire exactly once), the primary broker dies with
    an unshipped telemetry tail mid-storm (firing alerts must HOLD
    through the one-round blackout — no flapping — and telemetry loss is
    bounded by the tail), the fleet heals (both alerts resolve exactly
    once), and a quiet drain proves no further transitions.  Alert
    transitions are journaled as kind "alert" and published as
    EventKind.ALERT; the terminate events also trigger a blackbox
    capture, tying the postmortem path into the same storm.
    """
    import random as _random

    from deeplearning_cfn_tpu.analysis.schedules import (
        FailoverSimConnection,
        ReplicatedSimBroker,
        VirtualClock,
    )
    from deeplearning_cfn_tpu.cluster.broker_service import (
        BrokerLivenessWatcher,
    )
    from deeplearning_cfn_tpu.obs.aggregator import (
        FleetAggregator,
        fleet_metric_values,
    )
    from deeplearning_cfn_tpu.obs.blackbox import BlackBox
    from deeplearning_cfn_tpu.obs.heartbeat import Heartbeater
    from deeplearning_cfn_tpu.obs.liveness import LivenessConfig
    from deeplearning_cfn_tpu.obs.recorder import FlightRecorder
    from deeplearning_cfn_tpu.obs.slo import DEFAULT_RULES, SloEngine
    from deeplearning_cfn_tpu.provision.events import EventBus, EventKind

    report = ScenarioReport("alert-storm", seed)
    rng = _random.Random(seed)
    tick_s = 5.0
    agents = 200
    kill_count = 30  # 15% dead > the 10% rule threshold
    straggler_count = 20
    unshipped_tail = 57

    clock = VirtualClock()
    cluster = ReplicatedSimBroker(clock)
    cfg = LivenessConfig(suspect_after_s=15.0, dead_after_s=60.0)
    bus = EventBus()
    recorder = FlightRecorder()  # in-memory ring, no journal file
    alerts_on_bus: list[tuple[str, str]] = []
    terminates: list[str] = []

    def on_event(event) -> None:
        if event.kind is EventKind.ALERT:
            alerts_on_bus.append(
                (event.detail.get("rule"), event.detail.get("state"))
            )
        elif event.kind is EventKind.INSTANCE_TERMINATE:
            terminates.append(event.instance_id)

    bus.subscribe(on_event)
    watcher = BrokerLivenessWatcher(
        cluster_name="sim-storm",
        group="agents",
        bus=bus,
        config=cfg,
        clock=clock,
        fetch=cluster.active_dump,
    )
    engine = SloEngine(
        DEFAULT_RULES, clock=clock.now, bus=bus, recorder=recorder
    )
    aggregator = FleetAggregator()

    tmp = Path(tempfile.mkdtemp(prefix="dlcfn-storm-"))
    blackbox = BlackBox(
        tmp, host="sim-host", worker="agents", recorder=recorder, clock=clock.now
    )
    blackbox.attach(bus)

    names = [f"agent-{i:03d}" for i in range(agents)]
    # Per-agent mutable profile the telemetry closure reads each beat:
    # the straggler phase flips "ms", the heal phase flips it back.
    profiles = {w: {"ms": 100.0} for w in names}

    def make_source(worker: str):
        def source() -> dict:
            return {
                "v": 1,
                "gauges": {"dlcfn_serve_queue_depth": 1.0},
                "summaries": {"dlcfn_step_ms": [profiles[worker]["ms"]] * 8},
            }

        return source

    beaters = {
        w: Heartbeater(
            host="sim",
            port=0,
            worker_id=w,
            interval_s=tick_s,
            connection_factory=lambda: FailoverSimConnection(cluster.nodes()),
            telemetry_source=make_source(w),
        )
        for w in names
    }
    alive = set(names)
    transitions: list[dict] = []

    def round_(stream: bool = True) -> list[dict]:
        for w in names:
            if w in alive:
                beaters[w].beat_step()
        if stream and cluster.active() is cluster.primary:
            cluster.stream()
        clock.advance(tick_s)
        watcher.poll()
        merged = aggregator.merge(
            cluster.active_dump_telem(), liveness=watcher.snapshot()
        )
        new = engine.evaluate(fleet_metric_values(merged))
        transitions.extend(new)
        return new

    try:
        # Phase 1 — warmup: healthy fleet, replication caught up, quiet.
        for _ in range(4):
            round_()
        report.check(
            not transitions, "warmup: healthy fleet raised no alerts"
        )

        # Phase 2 — silent death: the dead-fraction rule must fire once,
        # only after classification (dead_after_s) plus its for-window.
        alive -= set(rng.sample(names, kill_count))
        for _ in range(22):
            round_()
        dead_state = engine.snapshot()["worker-dead-fraction"]
        report.check(
            dead_state["firing"] and dead_state["fired_count"] == 1,
            "dead-fraction alert fired exactly once for the silent deaths",
        )
        report.check(
            len(set(terminates)) == kill_count
            and blackbox.captures == len(terminates),
            "every dead agent terminated once and each terminate "
            "triggered a blackbox capture",
        )

        # Phase 3 — stragglers: slow step samples push the fleet p99
        # over the shipped threshold; fires once after its for-window.
        for w in rng.sample(sorted(alive), straggler_count):
            profiles[w]["ms"] = 4000.0
        for _ in range(15):
            round_()
        strag_state = engine.snapshot()["step-time-p99-straggler"]
        report.check(
            strag_state["firing"] and strag_state["fired_count"] == 1,
            "step-time p99 straggler alert fired exactly once",
        )

        # Phase 4 — broker failover mid-storm with an unshipped tail.
        before = len(transitions)
        for w in names:
            if w in alive:
                beaters[w].beat_step()
        # Ground truth at the instant of death: the primary's post-beat
        # table — whatever the standby lacks of THIS is the real loss.
        pre_counts = {
            w: c for w, (_a, c, _p) in cluster.primary.dump_telem().items()
        }
        backlog = len(cluster.pending())
        cluster.stream(max_entries=max(0, backlog - unshipped_tail))
        lag_at_kill = len(cluster.pending())
        cluster.kill_primary()
        clock.advance(tick_s)
        watcher.poll()  # outage round: empty fetch, firing alerts HOLD
        merged = aggregator.merge(
            cluster.active_dump_telem(), liveness=watcher.snapshot()
        )
        transitions.extend(engine.evaluate(fleet_metric_values(merged)))
        epoch = cluster.promote_standby()
        post_telem = cluster.standby.dump_telem()
        post_counts = {w: c for w, (_a, c, _p) in post_telem.items()}
        lost_snapshots = sum(
            pre_counts[w] - post_counts.get(w, 0) for w in pre_counts
        )
        report.check(
            lag_at_kill == unshipped_tail and 0 < lost_snapshots <= unshipped_tail,
            f"telemetry loss across failover bounded by the unshipped "
            f"journal tail ({lost_snapshots} <= {unshipped_tail} frames)",
        )
        round_()  # first round on the new primary: agents fail over
        report.check(
            alive <= set(cluster.standby.dump_telem()),
            "one beat round after promotion every live agent's snapshot "
            "is back on the new primary (telemetry self-heals)",
        )
        report.check(
            len(transitions) == before
            and engine.snapshot()["worker-dead-fraction"]["firing"]
            and engine.snapshot()["step-time-p99-straggler"]["firing"],
            "no alert flapped through the failover blackout: both "
            "incidents held firing, zero transitions",
        )

        # Phase 5 — heal: dead agents resurrect, stragglers normalize;
        # each alert resolves exactly once.
        alive = set(names)
        for profile in profiles.values():
            profile["ms"] = 100.0
        for _ in range(4):
            round_()
        snap = engine.snapshot()
        report.check(
            not snap["worker-dead-fraction"]["firing"]
            and snap["worker-dead-fraction"]["resolved_count"] == 1,
            "dead-fraction alert resolved exactly once on heal",
        )
        report.check(
            not snap["step-time-p99-straggler"]["firing"]
            and snap["step-time-p99-straggler"]["resolved_count"] == 1,
            "straggler alert resolved exactly once on heal",
        )

        # Phase 6 — drain: a quiet fleet must stay quiet.
        quiet_before = len(transitions)
        for _ in range(6):
            round_()
        report.check(
            len(transitions) == quiet_before,
            "drain: no flapping after recovery",
        )

        snap = engine.snapshot()
        report.check(
            all(s["fired_count"] == s["resolved_count"] for s in snap.values())
            and sum(s["fired_count"] for s in snap.values()) == 2,
            "exactly two incidents fleet-wide; every fire has one resolve",
        )
        journaled = [
            e for e in recorder.tail(4096) if e.get("kind") == "alert"
        ]
        report.check(
            len(journaled) == len(transitions) == len(alerts_on_bus) == 4,
            "every transition journaled as kind 'alert' and published as "
            "EventKind.ALERT (4 of 4)",
        )
        report.check(
            [t["rule"] for t in transitions]
            == [r for r, _s in alerts_on_bus],
            "journal and bus agree on transition order",
        )
        final = aggregator.merge(
            cluster.active_dump_telem(), liveness=watcher.snapshot()
        )
        report.check(
            final["hosts"] == agents and final["dead_fraction"] == 0.0,
            "final fleet merge sees every agent fresh and alive",
        )
        report.details.update(
            agents=agents,
            killed=kill_count,
            stragglers=straggler_count,
            epoch=epoch,
            unshipped_at_kill=lag_at_kill,
            lost_snapshots=lost_snapshots,
            transitions=[(t["rule"], t["state"]) for t in transitions],
            terminates=len(terminates),
            blackbox_captures=blackbox.captures,
            fleet_gauge_sum=final["gauges"]
            .get("dlcfn_serve_queue_depth", {})
            .get("sum"),
            step_p99_final=final["summaries"]
            .get("dlcfn_step_ms", {})
            .get("p99"),
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return report


# --- sched-flash-crowd -------------------------------------------------------


def sched_flash_crowd(seed: int) -> ScenarioReport:
    """Competing train+serve jobs under a flash crowd; the arbiter preempts.

    The multi-tenancy gate (docs/SCHEDULER.md), end-to-end on virtual
    time: a FleetArbiter places a ``prod-serve`` chat job (slice s0, two
    replicas) and a ``prod-train`` FSDP job (slices s1+s2, a REAL
    8-device SPMD trainer) on one 3-slice inventory.  A seeded flash
    crowd floods the serve pool while — mid-crowd — one of its replicas
    dies outright; the inflight SLO rule pages, the arbiter preempts the
    train job's non-anchor slice (live reshard 8 -> 4 devices, grad
    accum 1 -> 2 preserving the global batch) and lends it to the serve
    pool as a fresh replica.  The crowd draining resolves the page; the
    arbiter reclaims the replica (stragglers replayed — zero loss) and
    re-grows the mesh, returning grad accum to exactly 1
    (``symmetric_accum`` — the restore is bit-safe, not merely monotone).

    Invariants: train loss-continuity against an uninterrupted 8-device
    run; the SLO fires and resolves exactly once; zero lost serve
    requests through BOTH the replica death and the pool resizes;
    exactly one ``sched_preempt`` and one ``sched_restore`` in the
    journal; and an arbiter crashed mid-preemption resumes from the
    broker-persisted ledger absorbing a replayed page WITHOUT repeating
    the preemption.
    """
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import dataclasses
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np
    import flax.linen as nn

    from deeplearning_cfn_tpu.analysis.schedules import VirtualClock
    from deeplearning_cfn_tpu.cluster.contract import ClusterContract
    from deeplearning_cfn_tpu.cluster.elasticity import (
        ElasticityController,
        GroupPolicy,
    )
    from deeplearning_cfn_tpu.cluster.recovery import LiveReshardManager
    from deeplearning_cfn_tpu.models.llama import LlamaConfig, init_params
    from deeplearning_cfn_tpu.obs.recorder import get_recorder
    from deeplearning_cfn_tpu.obs.slo import SloEngine, SloRule
    from deeplearning_cfn_tpu.parallel.mesh import (
        MeshSpec,
        hybrid_mesh_for_slices,
        virtual_cpu_devices,
    )
    from deeplearning_cfn_tpu.provision.events import (
        EventBus,
        EventKind,
        LifecycleEvent,
    )
    from deeplearning_cfn_tpu.sched import (
        LEDGER_KEY,
        FleetArbiter,
        JobSpec,
        PreemptionDriver,
        ServePoolHandle,
        TrainJobHandle,
    )
    from deeplearning_cfn_tpu.serve import (
        ContinuousBatchingEngine,
        ServeConfig,
        ServeFrontEnd,
        ServeReplica,
        ServeRequest,
    )
    from deeplearning_cfn_tpu.train.data import SyntheticDataset
    from deeplearning_cfn_tpu.train.reshard import (
        LiveReshardCoordinator,
        mesh_topology,
    )
    from deeplearning_cfn_tpu.train.trainer import Trainer, TrainerConfig

    report = ScenarioReport("sched-flash-crowd", seed)
    devices = virtual_cpu_devices(8)
    vclock = VirtualClock()

    class _MLP(nn.Module):
        # Same shape as slice-loss-live: fc2's 256x256 kernel clears the
        # FSDP min_shard_elems heuristic, so the reshard moves genuinely
        # sharded arrays in both directions.
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(256, name="fc1")(x))
            x = nn.relu(nn.Dense(256, name="fc2")(x))
            return nn.Dense(10, name="head")(x)

    class _Backend:
        def __init__(self):
            self.events = EventBus()

    class _Store:
        """Broker KV stand-in the ledger persists through."""

        def __init__(self):
            self.table: dict[str, str] = {}

        def set(self, key: str, value: str) -> None:
            self.table[key] = value

        def get(self, key: str) -> str | None:
            return self.table.get(key)

    # --- the fleet: 3 slices, 2 hosts x 2 chips each --------------------
    fleet = ClusterContract.build(
        cluster_name="chaos-sched",
        coordinator_ip="10.0.0.1",
        other_worker_ips=[f"10.0.0.{i}" for i in range(2, 7)],
        chips_per_worker=2,
        storage_mount="/mnt/none",
        slices={
            "s0": ["10.0.0.1", "10.0.0.2"],
            "s1": ["10.0.0.3", "10.0.0.4"],
            "s2": ["10.0.0.5", "10.0.0.6"],
        },
    )

    def train_contract() -> ClusterContract:
        return ClusterContract.build(
            cluster_name="chaos-sched-train",
            coordinator_ip="10.0.0.3",
            other_worker_ips=["10.0.0.4", "10.0.0.5", "10.0.0.6"],
            chips_per_worker=2,
            storage_mount="/mnt/none",
            slices={
                "s1": ["10.0.0.3", "10.0.0.4"],
                "s2": ["10.0.0.5", "10.0.0.6"],
            },
        )

    def mesh_for(contract: ClusterContract):
        n = contract.slices_count
        per_slice = contract.total_chips // max(n, 1)
        return hybrid_mesh_for_slices(
            n,
            ici_spec=MeshSpec.fsdp_parallel(per_slice),
            dcn_axis="dp",
            devices=devices[: contract.total_chips],
        )

    # --- serve pool on s0 ------------------------------------------------
    cfg = dataclasses.replace(
        LlamaConfig.tiny(vocab_size=64, seq_len=64), dtype=jnp.float32
    )
    params = init_params(cfg, jax.random.key(0))

    def make_engine(name: str, slots: int) -> ContinuousBatchingEngine:
        scfg = ServeConfig(
            num_slots=slots, block_size=4, blocks_per_slot=8, prefill_len=16
        )
        return ContinuousBatchingEngine(
            cfg, params, scfg, clock=vclock, name=name, journal=False
        )

    frontend = ServeFrontEnd(
        [
            ServeReplica(make_engine(name, slots=4), name, group="serve")
            for name in ("pool-a", "pool-b")
        ]
    )

    # --- cluster control plane -------------------------------------------
    backend = _Backend()
    controller = ElasticityController(
        backend=backend,
        coordinator_queue_name="coord",
        on_instance_loss=frontend.on_instance_loss,
        slice_loss_window_s=1.0,
        clock=vclock,
    )
    controller.register(GroupPolicy("serve", 1, "sig-serve"))
    controller.register(GroupPolicy("s1", 1, "sig-s1", coordinator=True))
    controller.register(GroupPolicy("s2", 1, "sig-s2"))
    controller.attach()
    manager = LiveReshardManager(train_contract())
    manager.attach(controller)

    # --- the arbiter and its driver --------------------------------------
    store = _Store()
    driver = PreemptionDriver()
    driver.register_train("train-fsdp", TrainJobHandle(manager, bus=backend.events))
    driver.register_serve(
        "serve-chat",
        ServePoolHandle(
            frontend,
            # A whole lent slice is a bigger replica than the s0 pair's
            # colocated pair.  6 slots, not 8: the soak test's engine is
            # num_slots=8 with the same tiny model, and sharing its exact
            # decode shape would pre-warm the jit cache its
            # one-compile-at-warmup assertion watches.
            spawn=lambda name: ServeReplica(
                make_engine(name, slots=6), name, group="serve"
            ),
        ),
    )
    arbiter = FleetArbiter.from_contract(fleet, store=store, driver=driver)
    arbiter.attach(backend.events)
    controller.add_safe_point_hook(arbiter.reconcile)

    arbiter.submit(
        JobSpec(name="serve-chat", kind="serve", priority="prod-serve")
    )
    arbiter.submit(
        JobSpec(
            name="train-fsdp",
            kind="train",
            priority="prod-train",
            min_slices=1,
            max_slices=2,
        )
    )
    initial_assignments = {j: list(s) for j, s in arbiter.assignments.items()}
    report.check(
        initial_assignments
        == {"serve-chat": ["s0"], "train-fsdp": ["s1", "s2"]},
        "placer gave prod-serve the first slice and prod-train the rest "
        "(floors then priority-ordered fill)",
    )

    # The page rule: total inflight (queued + slotted) across the pool.
    # Inflight is invariant under replay/resize (requests move between
    # replicas, the total only drains), so a monotone drain produces
    # exactly one fire and one resolve — no flap at the reclaim.
    rule = SloRule(
        name="serve-queue-depth",
        metric="dlcfn_serve_queue_depth",
        agg="sum",
        op=">",
        threshold=12.0,
        for_s=2.0,
        severity="page",
        description="chaos: pool inflight beyond the two-replica budget",
    )
    slo = SloEngine(rules=(rule,), clock=vclock, bus=backend.events)

    def inflight_values() -> dict:
        return {
            "dlcfn_serve_queue_depth": {
                "sum": float(
                    sum(r.load for r in frontend.replicas.values())
                )
            }
        }

    # --- the trainer ------------------------------------------------------
    total_steps = 16
    dataset = lambda: SyntheticDataset(  # noqa: E731 - fresh iterator per run
        shape=(8, 8, 1), num_classes=10, batch_size=32, seed=seed
    )
    sample = next(iter(dataset().batches(1))).x

    def make_config() -> TrainerConfig:
        return TrainerConfig(
            optimizer="adamw",
            learning_rate=1e-3,
            strategy="fsdp",
            matmul_precision="float32",
            log_every=1,
            grad_accum_steps=1,
        )

    def run_straight() -> list[float]:
        trainer = Trainer(_MLP(), mesh_for(train_contract()), make_config())
        state = trainer.init(jax.random.PRNGKey(seed), sample)
        _, losses = trainer.fit(
            state, dataset().batches(total_steps), steps=total_steps, prefetch=0
        )
        return losses

    straight = run_straight()

    coordinator = LiveReshardCoordinator(
        manager=manager,
        mesh_for=mesh_for,
        flush=controller.flush_slice_losses,
        clock=vclock,
        symmetric_accum=True,
    )
    trainer = Trainer(_MLP(), mesh_for(manager.contract), make_config())
    state = trainer.init(jax.random.PRNGKey(seed), sample)

    # --- the world, one round per train step ------------------------------
    # Arrivals per round: calm, a 3-round flash crowd, then the tail.
    schedule = {0: 2, 1: 2, 2: 8, 3: 8, 4: 8, 5: 1, 6: 1}
    kill_round = 3 + seed % 2
    victim = "pool-a" if seed % 2 == 0 else "pool-b"
    rng = np.random.default_rng(seed)
    submitted: list[str] = []
    killed: list[str] = []
    timeline: list[tuple[int, str, str]] = []
    captured: dict[str, Any] = {
        "ledger": None,
        "assignments": None,
        "mid_topo": None,
        "mid_accum": None,
    }
    before = {
        kind: _journal_count(kind)
        for kind in (
            "sched_preempt",
            "sched_restore",
            "serve_failover",
            "serve_pool_resize",
            "reshard",
            "grad_accum_rescaled",
            "slice_restore_armed",
        )
    }

    def one_round(round_no: int) -> None:
        for _ in range(schedule.get(round_no, 0)):
            rid = f"req-{len(submitted):03d}"
            prompt = rng.integers(
                1, 64, size=int(rng.integers(4, 12)), dtype=np.int32
            )
            frontend.submit(
                ServeRequest(rid, prompt, max_new_tokens=4),
                arrival_s=vclock(),
            )
            submitted.append(rid)
        if round_no == kill_round and not killed:
            killed.append(victim)
            backend.events.publish(
                LifecycleEvent(
                    kind=EventKind.INSTANCE_TERMINATE,
                    group="serve",
                    instance_id=f"serve/{victim}",
                    detail={"reason": "chaos"},
                )
            )
        frontend.step_all()
        vclock.advance(1.0)
        for t in slo.evaluate(inflight_values()):
            timeline.append((round_no, t["rule"], t["state"]))
        # Crash evidence: the ledger as persisted right after the
        # preemption, while its loan is still outstanding.
        if captured["ledger"] is None and arbiter.counters["preemptions"] == 1:
            captured["ledger"] = store.get(LEDGER_KEY)
            captured["assignments"] = {
                j: list(s) for j, s in arbiter.assignments.items()
            }
        if captured["mid_topo"] is None and coordinator.live_total == 1:
            captured["mid_topo"] = mesh_topology(trainer.mesh)
            captured["mid_accum"] = trainer.config.grad_accum_steps

    def world(src):
        for i, b in enumerate(src):
            one_round(i)
            yield b

    state, live_losses = trainer.fit(
        state,
        world(dataset().batches(total_steps)),
        steps=total_steps,
        prefetch=0,
        reshard=coordinator,
    )

    # Drain the serve tail (train is done; the pool keeps stepping).
    drain_rounds = 0
    while frontend.pending() and drain_rounds < 200:
        frontend.step_all()
        vclock.advance(1.0)
        drain_rounds += 1

    # --- train-side invariants -------------------------------------------
    report.check(
        len(live_losses) == total_steps
        and int(jax.device_get(state.step)) == total_steps,
        "train survived preempt AND restore in one fit() call "
        "(no restart, monotone step count)",
    )
    report.check(
        coordinator.live_total == 2
        and coordinator.fallback_total == 0
        and _journal_count("reshard") - before["reshard"] == 2,
        "exactly two live reshards: the preempt shrink and the off-peak "
        "re-grow, zero fallbacks",
    )
    report.check(
        captured["mid_topo"] == {"devices": 4, "axes": {"fsdp": 4}}
        and captured["mid_accum"] == 2,
        "preempted mesh was the 4-device fsdp survivor with grad accum "
        "rescaled 1 -> 2 (global batch preserved)",
    )
    report.check(
        mesh_topology(trainer.mesh)
        == {"devices": 8, "axes": {"dp": 2, "fsdp": 4}}
        and manager.contract.slices_count == 2
        and trainer.config.grad_accum_steps == 1
        and _journal_count("grad_accum_rescaled")
        - before["grad_accum_rescaled"]
        == 2
        and _journal_count("slice_restore_armed")
        - before["slice_restore_armed"]
        == 1,
        "restore was bit-safe: full 2-slice mesh re-formed and grad "
        "accum returned to exactly 1 (symmetric rescale, journaled)",
    )
    report.check(
        bool(np.allclose(live_losses[:5], straight[:5], rtol=1e-5, atol=1e-6)),
        "pre-preemption losses identical to the uninterrupted run",
    )
    report.check(
        bool(np.allclose(live_losses, straight, rtol=5e-3, atol=1e-4)),
        "loss continuity through preempt and restore: full curve matches "
        "the uninterrupted 8-device run within tolerance",
    )

    # --- serve-side invariants -------------------------------------------
    report.check(
        len(frontend.completions) == len(submitted)
        and not frontend.lost_requests(),
        f"zero lost requests: all {len(submitted)} accepted requests "
        "completed through the replica death and both pool resizes",
    )
    report.check(
        frontend.failed == [victim]
        and _journal_count("serve_failover") - before["serve_failover"] == 1,
        "the mid-crowd replica death failed over exactly once",
    )
    report.check(
        _journal_count("serve_pool_resize") - before["serve_pool_resize"] == 2,
        "journal shows exactly two pool resizes: the lend and the reclaim",
    )

    # --- arbiter invariants ----------------------------------------------
    snap = slo.snapshot()[rule.name]
    report.check(
        arbiter.alert_counts == {rule.name: {"firing": 1, "resolved": 1}}
        and snap["fired_count"] == 1
        and snap["resolved_count"] == 1,
        "the SLO paged exactly once and resolved exactly once "
        "(engine and arbiter agree)",
    )
    report.check(
        arbiter.counters["preemptions"] == 1
        and arbiter.counters["restores"] == 1
        and _journal_count("sched_preempt") - before["sched_preempt"] == 1
        and _journal_count("sched_restore") - before["sched_restore"] == 1,
        "exactly one preemption and one restore, counted and journaled",
    )
    report.check(
        {j: list(s) for j, s in arbiter.assignments.items()}
        == initial_assignments
        and arbiter.loans == []
        and captured["assignments"]
        == {"serve-chat": ["s0", "s2"], "train-fsdp": ["s1"]},
        "the loan round-tripped: s2 to the serve pool during the crowd, "
        "back to the train job after, no loan left open",
    )

    # --- crash mid-preemption: resume must not repeat it ------------------
    def _absorbed_count() -> int:
        return sum(
            1
            for e in get_recorder().tail(4096)
            if e.get("kind") == "sched_decision"
            and e.get("action") == "page-absorbed"
        )

    resumed_ok = False
    if captured["ledger"] is not None:
        store2 = _Store()
        store2.table[LEDGER_KEY] = captured["ledger"]
        arbiter2 = FleetArbiter.resume(store2)
        preempts_before = _journal_count("sched_preempt")
        absorbed_before = _absorbed_count()
        # The page that caused the preemption, replayed post-crash.
        arbiter2.on_event(
            LifecycleEvent(
                kind=EventKind.ALERT,
                group="fleet",
                detail={
                    "rule": rule.name,
                    "state": "firing",
                    "value": 13.0,
                    "severity": "page",
                },
            )
        )
        actions = arbiter2.reconcile()
        resumed_ok = (
            actions == []
            and _journal_count("sched_preempt") - preempts_before == 0
            and _absorbed_count() - absorbed_before == 1
            and {j: list(s) for j, s in arbiter2.assignments.items()}
            == captured["assignments"]
            and json.loads(captured["ledger"])["loans"][0]["slice"] == "s2"
        )
    report.check(
        resumed_ok,
        "arbiter crashed mid-preemption resumed from the persisted ledger "
        "and ABSORBED the replayed page — no repeated preemption",
    )

    report.details.update(
        schedule={str(k): v for k, v in sorted(schedule.items())},
        kill_round=kill_round,
        victim=victim,
        timeline=timeline,
        requests=len(submitted),
        completions=len(frontend.completions),
        replayed=sorted(set(frontend.replayed)),
        drain_rounds=drain_rounds,
        mid_topology=captured["mid_topo"],
        post_topology=mesh_topology(trainer.mesh),
        grad_accum_mid=captured["mid_accum"],
        grad_accum_final=trainer.config.grad_accum_steps,
        straight_losses=[round(v, 6) for v in straight],
        live_losses=[round(v, 6) for v in live_losses],
        arbiter_counters=dict(arbiter.counters),
    )
    return report


# --- gauntlet ----------------------------------------------------------------


def gauntlet(seed: int) -> ScenarioReport:
    """Composed multi-fault incident: slice loss + broker shard failover
    in the SAME reshard pause + a writer crash at the manifest commit
    point, against one end-to-end workload — the cross-subsystem
    invariants no single-subsystem scenario can see (chaos/gauntlet.py).
    """
    from deeplearning_cfn_tpu.chaos.gauntlet import pinned_schedule, run_gauntlet

    return run_gauntlet(pinned_schedule(seed))


SCENARIOS: dict[str, Callable[[int], ScenarioReport]] = {
    "silent-death": silent_death,
    "partition": partition,
    "flaky-rpc": flaky_rpc,
    "slow-disk": slow_disk,
    "slice-loss-live": slice_loss_live,
    "data-reshard-live": data_reshard_live,
    "straggler": straggler,
    "serve-replica-loss": serve_replica_loss,
    "broker-failover": broker_failover,
    "split-brain": split_brain,
    "shard-failover": shard_failover,
    "degraded-pair-heal": degraded_pair_heal,
    "alert-storm": alert_storm,
    "sched-flash-crowd": sched_flash_crowd,
    "gauntlet": gauntlet,
}
# Pinned gauntlet regression reproducers (chaos/gauntlet.py
# REGRESSION_SCHEDULES) register themselves into SCENARIOS and
# SCENARIO_FAULTS when chaos.gauntlet is imported — the package
# __init__ always imports it, so `dlcfn chaos --all`, test_chaos's
# parametrization, and the DLC610 replay audit all see them.

#: Fault vocabulary per scenario — the seams each one injects into,
#: printed by ``dlcfn chaos --list`` next to the description.
SCENARIO_FAULTS: dict[str, tuple[str, ...]] = {
    "silent-death": ("silent-death",),
    "partition": ("partition", "message-chaos"),
    "flaky-rpc": ("http-errors", "connection-reset", "hard-down"),
    "slow-disk": ("torn-write", "slow-write"),
    "slice-loss-live": ("slice-loss", "forced-fallback"),
    "data-reshard-live": ("slice-loss", "writer-crash"),
    "straggler": ("straggler",),
    "serve-replica-loss": ("replica-loss",),
    "broker-failover": ("broker-failover",),
    "split-brain": ("partition", "split-brain"),
    "shard-failover": ("shard-failover", "silent-death", "split-brain"),
    "degraded-pair-heal": ("broker-failover",),
    "alert-storm": ("silent-death", "straggler", "broker-failover"),
    "sched-flash-crowd": ("flash-crowd", "replica-loss", "preemption"),
    "gauntlet": (
        "slice-loss",
        "shard-failover",
        "writer-crash",
        "telemetry-blackout",
    ),
}


def run_scenario(name: str, seed: int = 0) -> ScenarioReport:
    """Run one named scenario; unknown names list the catalog."""
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown chaos scenario {name!r}; available: "
            f"{sorted(SCENARIOS)}"
        ) from None
    return fn(seed)
