"""Named chaos scenarios: real components + seeded faults + invariants.

Each scenario drives PRODUCTION objects (Heartbeater,
BrokerLivenessWatcher, GoogleAuthTransport, StateCheckpointer,
ResilientSink, InMemoryQueue) through seeded fault schedules on virtual
clocks — no real sleeps, no wall-clock dependence — and records which
recovery invariants held.  ``run_scenario(name, seed)`` returns a
:class:`ScenarioReport` whose ``to_dict()`` is byte-identical across
runs with the same seed, which is what the regression tests and the
``dlcfn chaos`` CLI assert.

Catalog:

* ``silent-death`` — a worker stops beating under shuffled schedules;
  exactly-once termination + recovery (the PR-2 acceptance path, now
  fault-injected across many interleavings per seed).
* ``partition``   — short cuts must NOT kill anyone; long cuts must kill
  exactly once; healed workers resurrect; the metrics plane buffers
  through the outage (grace window) and message chaos cannot break
  at-least-once consumers.
* ``flaky-rpc``   — error bursts against the retry policy (jitter-bounded
  backoff on a fake clock) and a hard-down outage against the circuit
  breaker (fail-fast, half-open probe, re-trip).
* ``slow-disk``   — torn and slow checkpoint writes against the atomic
  write protocol and the local -> objectstore fallback chain.
"""

from __future__ import annotations

import shutil
import tempfile
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from deeplearning_cfn_tpu.chaos.injectors import (
    ChaosQueue,
    FlakyOpener,
    RecordingClock,
    SlowDisk,
    TornDisk,
)
from deeplearning_cfn_tpu.utils.timeouts import FakeClock


@dataclass
class ScenarioReport:
    """What a scenario proved (and what it could not)."""

    name: str
    seed: int
    passed: bool = True
    invariants: list[str] = field(default_factory=list)
    violations: list[str] = field(default_factory=list)
    details: dict[str, Any] = field(default_factory=dict)

    def check(self, condition: bool, description: str) -> None:
        if condition:
            self.invariants.append(description)
        else:
            self.violations.append(description)
            self.passed = False

    def to_dict(self) -> dict[str, Any]:
        return {
            "scenario": self.name,
            "seed": self.seed,
            "passed": self.passed,
            "invariants": list(self.invariants),
            "violations": list(self.violations),
            "details": dict(self.details),
        }


def _degraded_event_count() -> int:
    from deeplearning_cfn_tpu.obs.recorder import get_recorder

    return sum(
        1 for e in get_recorder().tail(4096) if e.get("kind") == "degraded"
    )


# --- silent-death ------------------------------------------------------------

_SD_PREFIX = ["beat:w0", "beat:w1", "poll"]
_SD_MIDDLE = (
    "beat:w0",
    "beat:w1",
    "beat:w1",
    "tick",
    "tick",
    "poll",
    "kill:w0",
    "poll",
)
_SD_DRAIN = ["beat:w1", "tick"] * 13 + ["poll"]


def silent_death(seed: int) -> ScenarioReport:
    """A worker dies silently under several seeded interleavings; the
    liveness plane must terminate it exactly once and recovery must
    replace it, with the survivor untouched."""
    from deeplearning_cfn_tpu.analysis.schedules import (
        HeartbeatChoreography,
        InvariantViolation,
        interleavings,
    )
    from deeplearning_cfn_tpu.obs.liveness import LivenessConfig, WorkerState

    report = ScenarioReport("silent-death", seed)
    schedules = interleavings(_SD_MIDDLE, count=6, seed=seed)
    terminations = 0
    for middle in schedules:
        choreo = HeartbeatChoreography(
            ["w0", "w1"],
            config=LivenessConfig(suspect_after_s=15.0, dead_after_s=60.0),
            tick_s=5.0,
        )
        try:
            choreo.run(_SD_PREFIX + list(middle) + _SD_DRAIN + ["recover", "poll"])
        except InvariantViolation as violation:
            report.check(False, f"ground-truth invariant: {violation}")
            continue
        states = choreo.states()
        report.check(
            states.get("w0") == WorkerState.DEAD.value,
            "silently-dead worker classified DEAD",
        )
        w0_terminations = choreo.terminated_workers().count("w0")
        terminations += w0_terminations
        report.check(
            w0_terminations == 1, "exactly one INSTANCE_TERMINATE for the victim"
        )
        report.check(
            states.get("w1") == WorkerState.ALIVE.value
            and "w1" not in choreo.terminated_workers(),
            "survivor stayed ALIVE and was never terminated",
        )
        report.check(
            choreo.recovered == {"w0": "w0+1"}
            and states.get("w0+1") == WorkerState.ALIVE.value,
            "recovery replaced the victim; replacement is beating",
        )
    report.details.update(
        schedules=len(schedules), terminations=terminations
    )
    return report


# --- partition ---------------------------------------------------------------


class _FlappingSink:
    """A metrics sink that raises OSError while ``down``."""

    def __init__(self) -> None:
        self.down = False
        self.records: list[dict] = []

    def write(self, record: dict) -> None:
        if self.down:
            raise OSError("sink unreachable (partition)")
        self.records.append(record)

    def close(self) -> None:
        pass


def partition(seed: int) -> ScenarioReport:
    """Network cuts: short ones must not kill, long ones must kill
    exactly once, healing resurrects; meanwhile the metrics plane rides
    out the outage inside its grace window and queue-level chaos cannot
    break the at-least-once consumer contract."""
    from deeplearning_cfn_tpu.analysis.schedules import (
        HeartbeatChoreography,
        InvariantViolation,
        interleavings,
    )
    from deeplearning_cfn_tpu.cluster.queue import InMemoryQueue
    from deeplearning_cfn_tpu.obs.liveness import LivenessConfig, WorkerState
    from deeplearning_cfn_tpu.train.metrics import MetricsOutage, ResilientSink

    report = ScenarioReport("partition", seed)

    # -- liveness under cut/heal ----------------------------------------
    short_cut = ("beat:w0", "beat:w1", "tick", "tick", "poll")
    for middle in interleavings(short_cut, count=4, seed=seed):
        choreo = HeartbeatChoreography(
            ["w0", "w1"],
            config=LivenessConfig(suspect_after_s=15.0, dead_after_s=60.0),
            tick_s=5.0,
        )
        try:
            # Short partition (10 virtual seconds < suspect threshold),
            # then heal: nobody may be terminated.
            choreo.run(
                _SD_PREFIX
                + ["cut:w0"]
                + list(middle)
                + ["heal:w0", "beat:w0", "poll"]
            )
            report.check(
                choreo.terminated_workers() == []
                and choreo.states().get("w0") == WorkerState.ALIVE.value,
                "short partition: no termination, worker ALIVE after heal",
            )
            # Long partition: w0 cut past dead_after (65 virtual s) while
            # w1 keeps beating -> exactly one terminate, then recovery,
            # then heal resurrects the original.
            choreo.run(
                ["cut:w0"]
                + ["beat:w0", "beat:w1", "tick"] * 13
                + ["poll", "recover", "heal:w0", "beat:w0", "poll"]
            )
        except InvariantViolation as violation:
            report.check(False, f"ground-truth invariant: {violation}")
            continue
        states = choreo.states()
        report.check(
            choreo.terminated_workers().count("w0") == 1,
            "long partition: exactly one INSTANCE_TERMINATE",
        )
        report.check(
            "w1" not in choreo.terminated_workers()
            and states.get("w1") == WorkerState.ALIVE.value,
            "worker on the healthy side never terminated",
        )
        report.check(
            states.get("w0") == WorkerState.ALIVE.value,
            "healed worker resurrected to ALIVE",
        )
        report.check(
            choreo.recovered.get("w0") == "w0+1"
            and states.get("w0+1") == WorkerState.ALIVE.value,
            "recovery brought up a replacement during the cut",
        )

    # -- trainer grace window -------------------------------------------
    clock = FakeClock()
    inner = _FlappingSink()
    sink = ResilientSink(inner, grace_s=120.0, clock=clock)
    sink.write({"step": 0})
    inner.down = True
    buffered = 0
    for step in range(1, 6):  # 5 writes over 50 virtual s of outage
        clock.advance(10.0)
        sink.write({"step": step})
        buffered = sink.buffered
    report.check(
        buffered == 5 and sink.degraded,
        "metrics outage inside grace window: writes buffered, no raise",
    )
    inner.down = False
    sink.write({"step": 6})
    report.check(
        sink.buffered == 0
        and not sink.degraded
        and [r["step"] for r in inner.records] == list(range(7)),
        "sink recovery flushed the buffer in order, nothing lost",
    )
    inner.down = True
    outage_raised = False
    try:
        for step in range(7, 30):
            clock.advance(30.0)
            sink.write({"step": step})
    except MetricsOutage:
        outage_raised = True
    report.check(
        outage_raised, "outage past the grace window raises typed MetricsOutage"
    )

    # -- message chaos vs at-least-once consumers -----------------------
    chaos_q = ChaosQueue(
        InMemoryQueue("chaos", clock=clock),
        seed=seed,
        drop_rate=0.1,
        delay_rate=0.2,
        delay_ops=2,
        duplicate_rate=0.2,
        reorder=True,
    )
    sent = 30
    for i in range(sent):
        chaos_q.send({"event": "worker-setup", "id": i})
    seen: set[int] = set()
    deliveries = 0
    for _sweep in range(50):
        messages = chaos_q.receive(max_messages=10, visibility_timeout_s=60.0)
        if not messages and not chaos_q._held:
            break
        for msg in messages:
            deliveries += 1
            seen.add(int(msg.body["id"]))
            chaos_q.delete(msg.receipt)
    chaos_q.flush_held()
    for _sweep in range(10):
        messages = chaos_q.receive(max_messages=10, visibility_timeout_s=60.0)
        if not messages:
            break
        for msg in messages:
            deliveries += 1
            seen.add(int(msg.body["id"]))
            chaos_q.delete(msg.receipt)
    report.check(
        len(seen) == sent - chaos_q.dropped,
        "every non-dropped message delivered despite delay/dup/reorder",
    )
    report.check(
        deliveries >= len(seen), "duplicates deduplicated by consumers"
    )
    report.details.update(
        dropped=chaos_q.dropped,
        delayed=chaos_q.delayed,
        duplicated=chaos_q.duplicated,
        deliveries=deliveries,
    )
    return report


# --- flaky-rpc ---------------------------------------------------------------


def flaky_rpc(seed: int) -> ScenarioReport:
    """Retryable error bursts against the unified RetryPolicy (jittered,
    clock-injected, deadline-safe) and a hard outage against the circuit
    breaker wired into GoogleAuthTransport."""
    from deeplearning_cfn_tpu.provision.gcp_transport import (
        GCPAPIError,
        GoogleAuthTransport,
    )
    from deeplearning_cfn_tpu.utils.resilience import CircuitBreaker, CircuitOpen

    report = ScenarioReport("flaky-rpc", seed)

    # -- burst phase: every call must eventually succeed ----------------
    clock = RecordingClock()
    opener = FlakyOpener(seed=seed, error_rate=0.45, reset_rate=0.15)
    transport = GoogleAuthTransport(
        project="chaos",
        token_provider=lambda: ("tok", 1e18),
        opener=opener,
        max_retries=8,
        backoff_s=0.05,
        clock=clock,
        seed=seed,
    )
    calls = 20
    successes = 0
    for i in range(calls):
        try:
            out = transport("GET", f"projects/p/locations/z/nodes/n{i}", None)
            successes += 1 if out == {"ok": True} else 0
        except GCPAPIError:
            pass
    report.check(
        successes == calls,
        "all calls succeeded through seeded 429/500/503/reset bursts",
    )
    base, cap = 0.05, 0.05 * 2**8
    report.check(
        all(base <= s <= cap for s in clock.sleeps),
        "every backoff sleep within jitter bounds [base_s, cap_s]",
    )
    report.check(
        len(set(round(s, 6) for s in clock.sleeps)) > 1
        if len(clock.sleeps) > 4
        else True,
        "backoff is jittered (not a fixed exponential ladder)",
    )
    report.check(
        clock.now() == sum(clock.sleeps),
        "all waiting happened on the injected clock (no real sleeps)",
    )

    # -- hard-down phase: the breaker must fail fast --------------------
    degraded_before = _degraded_event_count()
    hard_opener = FlakyOpener(seed=seed + 1, hard_down=True)
    breaker = CircuitBreaker(
        name="gcp-control-plane",
        failure_threshold=3,
        reset_after_s=60.0,
        clock=clock,
    )
    down = GoogleAuthTransport(
        project="chaos",
        token_provider=lambda: ("tok", 1e18),
        opener=hard_opener,
        max_retries=1,
        backoff_s=0.01,
        clock=clock,
        seed=seed,
        breaker=breaker,
    )
    outcomes: list[str] = []
    for i in range(6):
        try:
            down("GET", f"projects/p/locations/z/nodes/d{i}", None)
            outcomes.append("ok")
        except CircuitOpen:
            outcomes.append("circuit-open")
        except GCPAPIError:
            outcomes.append("api-error")
    requests_when_open = len(hard_opener.requests)
    report.check(
        outcomes == ["api-error"] * 3 + ["circuit-open"] * 3,
        "breaker tripped after 3 consecutive outages, then failed fast",
    )
    report.check(
        requests_when_open == 3 * 2,
        "no HTTP requests issued while the circuit is open",
    )
    report.check(
        _degraded_event_count() == degraded_before + 1,
        "breaker trip published a degraded event to the obs plane",
    )
    # -- half-open probe ------------------------------------------------
    clock.advance(61.0)
    try:
        down("GET", "projects/p/locations/z/nodes/probe", None)
        probe_outcome = "ok"
    except GCPAPIError:
        probe_outcome = "api-error"
    except CircuitOpen:
        probe_outcome = "circuit-open"
    report.check(
        probe_outcome == "api-error"
        and len(hard_opener.requests) == requests_when_open + 2
        and breaker.state == "open",
        "after cooldown exactly one probe ran, failed, and re-opened the circuit",
    )
    report.details.update(
        burst_requests=len(opener.requests),
        retries=len(opener.requests) - calls,
        backoff_sleeps=len(clock.sleeps),
        virtual_wait_s=round(sum(clock.sleeps), 6),
        hard_down_requests=len(hard_opener.requests),
    )
    return report


# --- slow-disk ---------------------------------------------------------------


def slow_disk(seed: int) -> ScenarioReport:
    """Torn and slow checkpoint writes: the atomic protocol must make
    torn writes unobservable, and the fallback chain must keep absorbing
    checkpoints (degrading local -> objectstore) instead of failing."""
    from deeplearning_cfn_tpu.provision.objectstore import LocalObjectStore
    from deeplearning_cfn_tpu.train.checkpoint import (
        FallbackCheckpointer,
        ObjectStoreCheckpointer,
        StateCheckpointer,
    )

    report = ScenarioReport("slow-disk", seed)
    root = Path(tempfile.mkdtemp(prefix="dlcfn-chaos-"))
    try:
        clock = FakeClock()
        torn = TornDisk(seed=seed, fail_rate=0.6)
        local = StateCheckpointer(root / "local", io=torn)
        remote = ObjectStoreCheckpointer(
            store=LocalObjectStore(root=root / "bucket")
        )
        degraded_before = _degraded_event_count()
        chain = FallbackCheckpointer(
            tiers=[("local", local), ("objectstore", remote)],
            failure_threshold=3,
            reset_after_s=1_000.0,
            clock=clock,
        )
        tiers_used: list[str] = []
        steps = 12
        for step in range(1, steps + 1):
            tiers_used.append(chain.save(step, {"step": step, "loss": 0.5 / step}))
        report.check(
            len(tiers_used) == steps,
            "every checkpoint landed on some tier (no failed saves escaped)",
        )
        report.check(torn.torn > 0, "torn writes actually injected")
        restored = chain.restore_latest()
        report.check(
            restored is not None and restored[1] == steps,
            "restore_latest returns the newest checkpoint across tiers",
        )
        report.check(
            restored is not None and restored[0]["step"] == steps,
            "restored state is intact (content hash verified)",
        )
        # Every checkpoint visible on the local tier must verify: torn
        # writes may only ever leave temp files, never half a committed
        # checkpoint.
        local_ok = all(
            local.io.read_bytes(local._file(s)) and local.restore_latest()
            for s in local.steps()
        )
        committed = list((root / "local").glob("state-*.json"))
        temps = list((root / "local").glob(".state-*"))
        report.check(
            local_ok and not temps,
            "no torn bytes observable: committed files verify, temps cleaned",
        )
        # Accounting invariant: the local tier's save count equals its
        # successful writes (attempted minus torn), and everything else
        # fell through to the objectstore — fallback fires exactly when
        # the local tier failed or its breaker quarantined it, never
        # spuriously.
        report.check(
            tiers_used.count("local") == torn.writes - torn.torn
            and tiers_used.count("objectstore")
            == steps - tiers_used.count("local"),
            "fallback engaged exactly when the local tier failed or was quarantined",
        )
        if chain.breaker("local").state != "closed":
            report.check(
                _degraded_event_count() > degraded_before,
                "local-tier breaker trip published a degraded event",
            )

        # -- slow disk: latency consumes virtual, not wall, time --------
        slow = SlowDisk(clock=clock, latency_s=7.0)
        slow_ck = StateCheckpointer(root / "slow", io=slow)
        t0 = clock.now()
        for step in (1, 2, 3):
            slow_ck.save(step, {"step": step})
        report.check(
            clock.now() - t0 == 21.0,
            "slow-disk latency consumed injected-clock time only",
        )
        report.check(
            slow_ck.restore_latest() == ({"step": 3}, 3),
            "slow writes still commit atomically and restore cleanly",
        )
        report.details.update(
            tiers_used=tiers_used,
            torn_writes=torn.torn,
            total_writes=torn.writes,
            local_steps=local.steps(),
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return report


SCENARIOS: dict[str, Callable[[int], ScenarioReport]] = {
    "silent-death": silent_death,
    "partition": partition,
    "flaky-rpc": flaky_rpc,
    "slow-disk": slow_disk,
}


def run_scenario(name: str, seed: int = 0) -> ScenarioReport:
    """Run one named scenario; unknown names list the catalog."""
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown chaos scenario {name!r}; available: "
            f"{sorted(SCENARIOS)}"
        ) from None
    return fn(seed)
