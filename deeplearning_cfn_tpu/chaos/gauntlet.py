"""The full-stack chaos gauntlet: composed multi-fault incidents.

Every other chaos scenario proves one subsystem at a time.  Real
incidents are correlated: the slice you lose mid-epoch is also the
moment the broker shard fails over and the async checkpoint writer
tears a manifest.  The gauntlet runs ONE real end-to-end workload —
a 2-slice SPMD trainer on 8 virtual CPU devices, fed by the sharded
datastream, checkpointed by :class:`AsyncShardedCheckpointer`,
heartbeating through a 2-shard broker ring, under a live SLO engine —
and composes faults into it from a declarative, seeded
:class:`FaultSchedule` on one virtual clock.

Fault vocabulary (:data:`FAULT_KINDS`):

* ``slice-loss``          — the s1 terminate burst mid-epoch: live
  reshard onto the survivors AND the datastream reshard in the same
  pause (wired through the coordinator's ``on_commit`` seam).
* ``shard-failover``      — a broker shard's primary dies and its warm
  standby is promoted; when scheduled at the slice-loss step it
  executes INSIDE the reshard pause (the composed case).
* ``writer-crash``        — :class:`ManifestCrashDisk` armed so the
  next async checkpoint dies at the manifest commit point.
* ``telemetry-blackout``  — the SLO engine sees no fleet values for a
  window of rounds; firing alerts must HOLD, nothing may flap.

:class:`GauntletInvariants` then asserts the cross-subsystem contract
no single-subsystem gate can see: exactly-once training records across
the composed reshard, loss continuity against an undisturbed reference
run (bit-exact when no reshard occurred), zero process restarts, the
previous checkpoint fully restorable after the torn manifest, each SLO
alert firing and resolving exactly once through the blackout, and
byte-determinism per seed (the scenario is registered in
``chaos.SCENARIOS`` so the DLC610 replay audit double-runs it).

On top sits the seeded incident explorer: :func:`perturbed_schedule`
draws a random-but-valid composition per seed,
:func:`run_gauntlet_sweep` runs N of them, and :func:`shrink_schedule`
greedily deletes events from any failing schedule until it is a
minimal reproducer — which gets pinned in :data:`REGRESSION_SCHEDULES`
and auto-registered as a scenario.
"""

from __future__ import annotations

import random
import shutil
import tempfile
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable

from deeplearning_cfn_tpu.chaos.scenarios import (
    ScenarioReport,
    _datastream_event_count,
    _journal_count,
)

#: The composable fault vocabulary, in canonical order.
FAULT_KINDS = (
    "slice-loss",
    "shard-failover",
    "writer-crash",
    "telemetry-blackout",
)

_WORK_QUEUE = "gauntlet-work"


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault.  ``at_step`` is the driver round (== the
    0-based batch index; training step ``at_step`` has completed when
    the fault executes).  ``duration`` is rounds (blackout only);
    ``shard`` is the broker shard index (shard-failover only)."""

    kind: str
    at_step: int
    duration: int = 0
    shard: int = 0

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"kind": self.kind, "at_step": self.at_step}
        if self.kind == "telemetry-blackout":
            out["duration"] = self.duration
        if self.kind == "shard-failover":
            out["shard"] = self.shard
        return out

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FaultEvent":
        return cls(
            kind=str(d["kind"]),
            at_step=int(d["at_step"]),
            duration=int(d.get("duration", 0)),
            shard=int(d.get("shard", 0)),
        )


@dataclass(frozen=True)
class FaultSchedule:
    """A seeded, declarative incident: which faults, when, composed how.

    At most one event per kind — composition is ACROSS subsystems, not
    repetition within one.  ``validate()`` returns the structural
    errors that would make the incident un-assertable (e.g. a blackout
    that would swallow the alert's firing window)."""

    seed: int
    events: tuple[FaultEvent, ...]
    total_steps: int = 12
    n_broker_shards: int = 2

    def by_kind(self) -> dict[str, FaultEvent]:
        return {e.kind: e for e in self.events}

    def validate(self) -> list[str]:
        errors: list[str] = []
        T = self.total_steps
        if T < 8:
            errors.append(f"total_steps must be >= 8, got {T}")
        kinds = [e.kind for e in self.events]
        if len(set(kinds)) != len(kinds):
            errors.append(f"duplicate fault kinds: {sorted(kinds)}")
        for e in self.events:
            if e.kind not in FAULT_KINDS:
                errors.append(f"unknown fault kind {e.kind!r} (want {FAULT_KINDS})")
        if any(e.kind not in FAULT_KINDS for e in self.events):
            return errors
        by = self.by_kind()
        sl = by.get("slice-loss")
        fo = by.get("shard-failover")
        wc = by.get("writer-crash")
        bo = by.get("telemetry-blackout")
        if sl is not None and not (2 <= sl.at_step <= T - 6):
            errors.append(
                f"slice-loss at_step {sl.at_step} outside [2, {T - 6}] "
                "(needs a loss prefix and room to fire/heal the composed alert)"
            )
        if fo is not None:
            if not (1 <= fo.at_step <= T - 5):
                errors.append(
                    f"shard-failover at_step {fo.at_step} outside [1, {T - 5}] "
                    "(the alert must fire and resolve inside the run)"
                )
            if not (0 <= fo.shard < self.n_broker_shards):
                errors.append(
                    f"shard-failover shard {fo.shard} outside "
                    f"[0, {self.n_broker_shards})"
                )
        if wc is not None:
            if not (1 <= wc.at_step <= T - 2):
                errors.append(
                    f"writer-crash at_step {wc.at_step} outside [1, {T - 2}] "
                    "(arm needs a prior manifest and a probe round after)"
                )
            if sl is not None and wc.at_step <= sl.at_step:
                errors.append(
                    "writer-crash must land after slice-loss "
                    f"(got {wc.at_step} <= {sl.at_step}): the incident "
                    "narrative is a crash during/after the reshard pause, and "
                    "the frozen checkpoint must match the surviving topology"
                )
        if bo is not None:
            if not (1 <= bo.duration <= 3):
                errors.append(f"telemetry-blackout duration {bo.duration} outside [1, 3]")
            if bo.at_step < 1 or bo.at_step + bo.duration > T - 1:
                errors.append(
                    f"telemetry-blackout [{bo.at_step}, "
                    f"{bo.at_step + bo.duration}) must sit inside [1, {T - 1}] "
                    "(a post-blackout round must exist to heal and resolve)"
                )
            if fo is not None and bo.at_step < fo.at_step + 4:
                errors.append(
                    f"telemetry-blackout at {bo.at_step} would swallow the "
                    f"failover alert's firing window (needs at_step >= "
                    f"{fo.at_step + 4})"
                )
        return errors

    def to_dict(self) -> dict[str, Any]:
        return {
            "seed": self.seed,
            "total_steps": self.total_steps,
            "n_broker_shards": self.n_broker_shards,
            "events": [e.to_dict() for e in self.events],
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FaultSchedule":
        return cls(
            seed=int(d["seed"]),
            events=tuple(FaultEvent.from_dict(e) for e in d["events"]),
            total_steps=int(d.get("total_steps", 12)),
            n_broker_shards=int(d.get("n_broker_shards", 2)),
        )


def pinned_schedule(seed: int) -> FaultSchedule:
    """The pinned 3-fault incident (the check.sh gate): slice loss
    mid-epoch, a broker shard failover COMPOSED into the same reshard
    pause, and a writer crash at the manifest commit point two steps
    later."""
    die = 3 + seed % 3
    return FaultSchedule(
        seed=seed,
        events=(
            FaultEvent("slice-loss", at_step=die),
            FaultEvent("shard-failover", at_step=die, shard=seed % 2),
            FaultEvent("writer-crash", at_step=die + 2),
        ),
    )


def perturbed_schedule(seed: int, total_steps: int = 12) -> FaultSchedule:
    """One seeded draw from the incident space: 2-4 distinct fault
    kinds with valid (but perturbed) timing and ordering.  Pure
    function of ``seed`` — the sweep explorer's generator."""
    rng = random.Random(0x6AA7 ^ (seed * 2654435761 % (1 << 32)))
    T = total_steps
    n_kinds = rng.randint(2, 4)
    kinds = sorted(rng.sample(FAULT_KINDS, n_kinds), key=FAULT_KINDS.index)
    events: list[FaultEvent] = []
    sl_at: int | None = None
    fo_at: int | None = None
    for kind in kinds:
        if kind == "slice-loss":
            sl_at = rng.randint(2, T - 6)
            events.append(FaultEvent(kind, at_step=sl_at))
        elif kind == "shard-failover":
            if sl_at is not None and rng.random() < 0.5:
                fo_at = sl_at  # composed: failover inside the reshard pause
            else:
                fo_at = rng.randint(1, T - 5)
            events.append(FaultEvent(kind, at_step=fo_at, shard=rng.randrange(2)))
        elif kind == "writer-crash":
            lo = 1 if sl_at is None else sl_at + 1
            events.append(FaultEvent(kind, at_step=rng.randint(lo, T - 2)))
        elif kind == "telemetry-blackout":
            lo = 1 if fo_at is None else fo_at + 4
            if lo > T - 2:
                continue  # no room for a post-blackout resolve round
            at = rng.randint(lo, T - 2)
            dur = rng.randint(1, min(3, T - 1 - at))
            events.append(FaultEvent(kind, at_step=at, duration=dur))
    return FaultSchedule(seed=seed, events=tuple(events), total_steps=T)


class GauntletInvariants:
    """The cross-subsystem invariant catalog, conditioned on which
    faults the schedule composed.  ``verify(report, obs)`` runs every
    applicable check against the facts the engine observed."""

    def __init__(self, schedule: FaultSchedule):
        self.schedule = schedule
        self.by = schedule.by_kind()
        self.kinds = set(self.by)

    def verify(self, report: ScenarioReport, obs: dict[str, Any]) -> None:
        import numpy as np

        T = self.schedule.total_steps
        sl = self.by.get("slice-loss")
        fo = self.by.get("shard-failover")
        wc = self.by.get("writer-crash")
        bo = self.by.get("telemetry-blackout")

        # --- training plane ---------------------------------------------
        report.check(
            len(obs["losses"]) == T and obs["final_step"] == T,
            "zero process restarts: one fit() call trained every step "
            "through the composed incident (monotone step count)",
        )
        if sl is not None:
            report.check(
                obs["live_total"] == 1 and obs["fallback_total"] == 0,
                "the terminate burst coalesced into exactly one live "
                "reshard and zero fallbacks",
            )
            report.check(
                obs["journal"]["slice_loss_coalesced"] == 1
                and obs["journal"]["reshard"] == 1,
                "journal shows one coalesced slice loss and one reshard",
            )
            report.check(
                obs["post_mesh"] == {"devices": 4, "axes": {"fsdp": 4}}
                and obs["grad_accum"] == 2,
                "trainer rebound to the surviving 4-device fsdp mesh with "
                "grad accumulation rescaled 1 -> 2 (global batch preserved)",
            )
            report.check(
                bool(
                    np.allclose(
                        obs["losses"][: sl.at_step],
                        obs["straight"][: sl.at_step],
                        rtol=1e-5,
                        atol=1e-6,
                    )
                ),
                "pre-incident losses identical to the undisturbed run",
            )
            report.check(
                bool(
                    np.allclose(obs["losses"], obs["straight"], rtol=5e-3, atol=1e-4)
                ),
                "loss continuity across the composed incident: full curve "
                "matches the undisturbed run within tolerance",
            )
        else:
            report.check(
                obs["live_total"] == 0 and obs["journal"]["reshard"] == 0,
                "no slice loss scheduled: zero reshards executed",
            )
            report.check(
                obs["losses"] == obs["straight"],
                "without a reshard the incident is arithmetic-invisible: "
                "loss curve bit-identical to the undisturbed run",
            )

        # --- data plane (exactly-once records) --------------------------
        report.check(
            obs["plane_seen"] == list(range(obs["plane_total"])),
            "every datastream record consumed exactly once across the "
            "incident (zero dropped, zero duplicated)",
        )
        report.check(
            obs["journal"]["datastream_reshard"] == (1 if sl is not None else 0),
            "datastream resharded exactly once per slice loss (inside the "
            "same pause as the mesh reshard), never otherwise",
        )

        # --- checkpoint plane -------------------------------------------
        if wc is not None:
            report.check(
                obs["latest_at_arm"] == wc.at_step,
                "the writer had committed the arm-step manifest before the "
                "crash was armed (deterministic crash point)",
            )
            report.check(
                obs["write_failures"] == 1 and obs["disk_crashes"] == 1
                and obs["journal"]["checkpoint_write_failed"] == 1,
                "the armed crash fired exactly once at the manifest commit "
                "point and was journaled (writer thread survived)",
            )
            report.check(
                not obs["crashed_manifest_exists"] and obs["crashed_shard_exists"],
                "the crashed step left shard litter but NO manifest: the "
                "commit point never passed",
            )
            report.check(
                obs["restore_step"] == wc.at_step
                and obs["restore_stream_records"] == wc.at_step * 32,
                "the previous checkpoint (state + stream cursor) is fully "
                "restorable after the torn manifest — no training step or "
                "record position lost",
            )
        report.check(
            obs["final_latest"] == T,
            "the async writer recovered past the incident: the final step's "
            "manifest committed",
        )

        # --- broker plane ------------------------------------------------
        report.check(
            obs["work_depth"] == T and obs["resends"] == (1 if fo is not None else 0),
            "idempotent work submission is exactly-once through the "
            "incident: the post-failover re-send storm deduplicated, depth "
            "== one entry per round",
        )
        if fo is not None:
            report.check(
                obs["failed_shard_epoch"] == 1 and obs["reprovisions"] == 1,
                "the failed shard promoted its standby (epoch fenced 0 -> 1) "
                "and auto-re-provisioned a fresh one, exactly once",
            )
            report.check(
                obs["healed_pairs"] == self.schedule.n_broker_shards,
                "every broker shard pair is whole and caught up at the end "
                "(zero replication lag after the failover)",
            )
            report.check(
                obs["healthy_shard_failovers"] == 0,
                "zero spurious client failovers on the unaffected shard",
            )
        else:
            report.check(
                obs["healed_pairs"] == self.schedule.n_broker_shards
                and obs["total_failovers"] == 0
                and obs["reprovisions"] == 0,
                "no failover scheduled: the ring stayed whole, zero client "
                "failovers, zero re-provisions",
            )

        # --- SLO plane ----------------------------------------------------
        expect_fired = 1 if fo is not None else 0
        report.check(
            obs["slo"]["fired_count"] == expect_fired
            and obs["slo"]["resolved_count"] == expect_fired
            and not obs["slo"]["firing"],
            "each SLO alert fired and resolved exactly once for the "
            "incident (zero flaps, nothing left firing)",
        )
        if bo is not None:
            blackout = range(bo.at_step, bo.at_step + bo.duration)
            report.check(
                all(t["round"] not in blackout for t in obs["transitions"]),
                "zero alert transitions during the telemetry blackout "
                "(absence of evidence neither fires nor resolves)",
            )
            if fo is not None:
                report.check(
                    all(obs["firing_by_round"][r] for r in blackout),
                    "the firing alert HELD through the telemetry blackout "
                    "(no flap on missing data)",
                )


# Memoised reference loss curves keyed by (seed, total_steps); see the
# "undisturbed reference run" block in run_gauntlet.
_STRAIGHT_CACHE: dict[tuple[int, int], tuple[float, ...]] = {}


def run_gauntlet(schedule: FaultSchedule) -> ScenarioReport:
    """Run one composed incident end-to-end and return its report.

    The workload is real: an FSDP trainer on a 2-slice hybrid mesh (8
    virtual CPU devices) pulling record batches from a single-host
    shard stream, an id-carrying 4-host :class:`DataStreamPlane`
    exercising the datastream reshard, an async sharded checkpointer
    capturing the stream cursor every step, a 2-shard replicated broker
    ring carrying heartbeats + idempotent work, and an SLO engine
    watching broker pair health — all on ONE virtual clock, with the
    schedule's faults injected at their rounds.  Deterministic per
    seed: ``report.to_dict()`` is byte-identical across runs.
    """
    errors = schedule.validate()
    if errors:
        raise ValueError("invalid fault schedule: " + "; ".join(errors))

    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax
    import numpy as np
    import flax.linen as nn

    from deeplearning_cfn_tpu.analysis.schedules import (
        ShardedSimBroker,
        ShardedSimConnection,
        VirtualClock,
        interleavings,
    )
    from deeplearning_cfn_tpu.chaos.injectors import ManifestCrashDisk
    from deeplearning_cfn_tpu.cluster.contract import ClusterContract
    from deeplearning_cfn_tpu.cluster.elasticity import (
        ElasticityController,
        GroupPolicy,
    )
    from deeplearning_cfn_tpu.cluster.recovery import LiveReshardManager
    from deeplearning_cfn_tpu.obs.recorder import get_recorder
    from deeplearning_cfn_tpu.obs.slo import SloEngine, SloRule
    from deeplearning_cfn_tpu.parallel.mesh import (
        MeshSpec,
        hybrid_mesh_for_slices,
        virtual_cpu_devices,
    )
    from deeplearning_cfn_tpu.provision.events import (
        EventBus,
        EventKind,
        LifecycleEvent,
    )
    from deeplearning_cfn_tpu.train.data import SyntheticDataset
    from deeplearning_cfn_tpu.train.datastream import (
        AsyncShardedCheckpointer,
        DataStreamPlane,
        HostShardStream,
    )
    from deeplearning_cfn_tpu.train.records import (
        Field,
        RecordSpec,
        write_dataset,
        write_records,
    )
    from deeplearning_cfn_tpu.train.reshard import (
        LiveReshardCoordinator,
        mesh_topology,
        rescale_grad_accum,
    )
    from deeplearning_cfn_tpu.train.trainer import Trainer, TrainerConfig

    seed = schedule.seed
    T = schedule.total_steps
    by = schedule.by_kind()
    sl_ev = by.get("slice-loss")
    fo_ev = by.get("shard-failover")
    wc_ev = by.get("writer-crash")
    bo_ev = by.get("telemetry-blackout")
    composed_failover = (
        fo_ev is not None and sl_ev is not None and fo_ev.at_step == sl_ev.at_step
    )
    blackout_rounds = (
        range(bo_ev.at_step, bo_ev.at_step + bo_ev.duration) if bo_ev else range(0)
    )

    report = ScenarioReport("gauntlet", seed)
    report.faults = [e.to_dict() for e in schedule.events]
    report.details["schedule"] = schedule.to_dict()

    devices = virtual_cpu_devices(8)

    class _Net(nn.Module):
        # fc2's 256x256 kernel clears the FSDP heuristic's
        # min_shard_elems, so the reshard moves genuinely sharded arrays.
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(256, name="fc1")(x))
            x = nn.relu(nn.Dense(256, name="fc2")(x))
            return nn.Dense(10, name="head")(x)

    def make_contract() -> ClusterContract:
        return ClusterContract.build(
            cluster_name="chaos-gauntlet",
            coordinator_ip="10.0.0.1",
            other_worker_ips=["10.0.0.2", "10.0.0.3", "10.0.0.4"],
            chips_per_worker=2,
            storage_mount="/mnt/none",
            slices={
                "s0": ["10.0.0.1", "10.0.0.2"],
                "s1": ["10.0.0.3", "10.0.0.4"],
            },
        )

    def mesh_for(contract: ClusterContract):
        n = contract.slices_count
        per_slice = contract.total_chips // max(n, 1)
        return hybrid_mesh_for_slices(
            n,
            ici_spec=MeshSpec.fsdp_parallel(per_slice),
            dcn_axis="dp",
            devices=devices[: contract.total_chips],
        )

    def make_config() -> TrainerConfig:
        return TrainerConfig(
            optimizer="adamw",
            learning_rate=1e-3,
            strategy="fsdp",
            matmul_precision="float32",
            log_every=1,
            grad_accum_steps=1,
        )

    root = Path(tempfile.mkdtemp(prefix="dlcfn-gauntlet-"))
    obs: dict[str, Any] = {}
    try:
        # --- training records: 2 shards x 128 = 256 = 8 batches of 32,
        # single-host so the record order (and thus the loss curve) is
        # topology-independent; loop=True covers all 12 steps.
        spec2 = RecordSpec.classification((8, 8, 1), "float32")
        tpaths: list[Path] = []
        for i in range(2):
            ds = SyntheticDataset(
                shape=(8, 8, 1), num_classes=10, batch_size=32, seed=seed * 7 + i
            )
            p = root / f"train-{i}.dlc"
            write_dataset(p, spec2, ds.batches(4), 4)
            tpaths.append(p)

        def train_stream(state=None) -> HostShardStream:
            return HostShardStream(
                tpaths,
                spec2,
                32,
                host="10.0.0.1",
                hosts=("10.0.0.1",),
                seed=seed,
                loop=True,
                state=state,
            )

        sample = next(train_stream().batches(1)).x

        # --- id-carrying datastream plane: 6 uneven shards over 4 hosts,
        # gid baked into y so exactly-once is literal.
        idspec = RecordSpec((Field("x", "uint8", (2,)), Field("y", "int32", ())))
        sizes = [17 + (3 * sid + seed) % 7 for sid in range(6)]
        ipaths: list[Path] = []
        gid = 0
        for sid, n in enumerate(sizes):
            recs = []
            for _ in range(n):
                recs.append(
                    idspec.encode(
                        x=np.array([gid % 251, gid % 7], dtype=np.uint8),
                        y=np.int32(gid),
                    )
                )
                gid += 1
            p = root / f"ids-{sid:02d}.dlc"
            write_records(p, idspec, recs)
            ipaths.append(p)
        plane_total = gid

        # --- the undisturbed reference run ------------------------------
        # The reference curve is a pure function of (seed, T): the training
        # records, init key, and step count fully determine it, and it runs
        # before the journal delta is captured, so memoising it across the
        # many same-seed runs a test process makes changes nothing observable.
        if (seed, T) not in _STRAIGHT_CACHE:
            trainer_s = Trainer(_Net(), mesh_for(make_contract()), make_config())
            state_s = trainer_s.init(jax.random.PRNGKey(seed), sample)
            _, fresh = trainer_s.fit(
                state_s, train_stream().batches(), steps=T, prefetch=0
            )
            _STRAIGHT_CACHE[(seed, T)] = tuple(float(v) for v in fresh)
        straight = list(_STRAIGHT_CACHE[(seed, T)])

        # --- the world on one virtual clock -----------------------------
        vclock = VirtualClock()

        class _Backend:
            def __init__(self):
                self.events = EventBus()

        backend = _Backend()
        controller = ElasticityController(
            backend=backend,
            coordinator_queue_name="coord",
            slice_loss_window_s=10.0,
            clock=vclock,
        )
        controller.register(GroupPolicy("s0", 1, "sig-s0", coordinator=True))
        controller.register(GroupPolicy("s1", 1, "sig-s1"))
        controller.attach()
        manager = LiveReshardManager(make_contract())
        manager.attach(controller)

        plane = DataStreamPlane(
            make_contract(), ipaths, idspec, batch_size=5, seed=seed, loop=False
        )
        plane_iters = {h: plane.stream(h).batches() for h in plane.hosts}
        plane_ids: dict[str, list[int]] = {h: [] for h in plane.hosts}

        broker = ShardedSimBroker(vclock, n_shards=schedule.n_broker_shards)
        host_conns = {
            h: ShardedSimConnection(broker) for h in make_contract().datastream_hosts()
        }
        work_conn = ShardedSimConnection(broker)

        rule = SloRule(
            name="gauntlet-broker-degraded",
            metric="dlcfn_gauntlet_broker_degraded_pairs",
            agg="value",
            op=">",
            threshold=0.0,
            for_s=2.0,
            severity="page",
            description="gauntlet: a broker shard pair is degraded "
            "(failover in progress, replication lag, or a dead primary)",
        )
        slo = SloEngine(rules=(rule,), clock=vclock, bus=backend.events)
        transitions: list[dict[str, Any]] = []
        firing_by_round: list[bool] = []

        disk = ManifestCrashDisk(once=True)
        ck = AsyncShardedCheckpointer(
            root / "ckpt", every_steps=1, n_shards=2, io=disk
        )
        frozen = root / "frozen"

        state = {
            "failover_done": False,
            "healed": False,
            "resend_due": False,
            "resends": 0,
        }
        # A blackout scheduled after the failover (validation guarantees
        # the alert fires first) defers healing until telemetry is back:
        # automation cannot confirm pair health while the fleet is dark,
        # which is exactly the window the hold-don't-flap invariant needs.
        heal_from = 0
        if fo_ev is not None and bo_ev is not None:
            heal_from = bo_ev.at_step + bo_ev.duration

        def do_failover() -> None:
            shard = broker.shards[fo_ev.shard]
            shard.kill_primary()
            shard.promote_standby()
            state["failover_done"] = True
            state["resend_due"] = True

        def on_commit(contract) -> None:
            # The composed pause: the datastream reshards at the SAME
            # step boundary as the mesh, and — when scheduled — the
            # broker shard fails over inside that pause.
            plane.reshard(contract)
            if composed_failover:
                do_failover()

        coordinator = LiveReshardCoordinator(
            manager=manager,
            mesh_for=mesh_for,
            flush=controller.flush_slice_losses,
            clock=vclock,
            on_commit=on_commit,
        )

        burst = ["10.0.0.3", "10.0.0.4", "10.0.0.3"]  # dup on purpose
        order = list(interleavings(burst, count=1, seed=seed)[0])

        def driver(src):
            """The world loop, advanced once per produced batch: faults,
            heartbeats, idempotent work, replication, healing, SLO
            evaluation, and one id-plane round — all deterministic."""
            for i, b in enumerate(src):
                # 1. scheduled faults for this round
                if sl_ev is not None and i == sl_ev.at_step:
                    for ip in order:
                        backend.events.publish(
                            LifecycleEvent(
                                kind=EventKind.INSTANCE_TERMINATE,
                                group="s1",
                                instance_id=ip,
                                detail={"reason": "preempted"},
                            )
                        )
                        vclock.advance(0.5)
                    vclock.advance(11.0)
                if fo_ev is not None and not composed_failover and i == fo_ev.at_step:
                    do_failover()
                if wc_ev is not None and i == wc_ev.at_step:
                    ck.wait()
                    obs["latest_at_arm"] = ck.latest_step()
                    disk.arm()
                if wc_ev is not None and i == wc_ev.at_step + 1:
                    # Probe: the crashed step's save has been attempted
                    # (and failed) by now; freeze the directory as the
                    # post-crash disk image for the restorability check.
                    ck.wait()
                    obs["write_failures"] = ck.write_failures
                    obs["disk_crashes"] = disk.crashes
                    crashed = wc_ev.at_step + 1
                    obs["crashed_manifest_exists"] = (
                        root / "ckpt" / f"ckpt-{crashed:08d}.manifest.json"
                    ).exists()
                    obs["crashed_shard_exists"] = (
                        root / "ckpt" / f"ckpt-{crashed:08d}.shard-00-of-02.json"
                    ).exists()
                    shutil.copytree(root / "ckpt", frozen)
                # 2. the at-least-once re-send storm after a failover
                if state["resend_due"] and i >= 1:
                    rid = f"w-{i - 1:03d}"
                    work_conn.send_idempotent(_WORK_QUEUE, rid.encode(), rid)
                    state["resends"] += 1
                    state["resend_due"] = False
                # 3. heartbeats from every live host
                for h in list(plane.hosts):
                    host_conns[h].heartbeat(h)
                # 4. this round's idempotent work submission
                rid = f"w-{i:03d}"
                work_conn.send_idempotent(_WORK_QUEUE, rid.encode(), rid)
                # 5. replication pass (healthy shards stay caught up)
                broker.stream_all()
                # 6. auto-heal: once the alert fired (and telemetry is
                # back), the acting primary re-provisions a fresh standby
                if (
                    state["failover_done"]
                    and not state["healed"]
                    and i >= heal_from
                    and i not in blackout_rounds
                    and slo.snapshot()[rule.name]["firing"]
                ):
                    broker.shards[fo_ev.shard].reprovision_standby()
                    state["healed"] = True
                # 7. SLO evaluation (a blackout round observes nothing)
                if i in blackout_rounds:
                    values: dict[str, dict[str, float]] = {}
                else:
                    values = {
                        rule.metric: {
                            "value": float(
                                broker.n_shards - broker.healed_pairs()
                            )
                        }
                    }
                for t in slo.evaluate(values):
                    transitions.append({"round": i, "rule": t["rule"], "state": t["state"]})
                firing_by_round.append(slo.snapshot()[rule.name]["firing"])
                vclock.advance(1.0)
                # 8. one id-plane round across the live hosts
                for h in list(plane.hosts):
                    nb = next(plane_iters[h], None)
                    if nb is not None:
                        plane_ids[h].extend(int(v) for v in nb.y)
                yield b

        journal_before = {
            "slice_loss_coalesced": _journal_count("slice_loss_coalesced"),
            "reshard": _journal_count("reshard"),
            "checkpoint_write_failed": _datastream_event_count(
                "checkpoint_write_failed"
            ),
            "datastream_reshard": _datastream_event_count("reshard"),
        }

        trainer = Trainer(_Net(), mesh_for(manager.contract), make_config())
        tstate = trainer.init(jax.random.PRNGKey(seed), sample)
        stream = train_stream()
        tstate, losses = trainer.fit(
            tstate,
            driver(stream.batches()),
            steps=T,
            prefetch=0,
            checkpointer=ck,
            datastream=stream,
            reshard=coordinator,
        )
        ck.wait()

        # --- gather the facts -------------------------------------------
        obs["losses"] = losses
        obs["straight"] = straight
        obs["final_step"] = int(jax.device_get(tstate.step))
        obs["live_total"] = coordinator.live_total
        obs["fallback_total"] = coordinator.fallback_total
        obs["post_mesh"] = mesh_topology(trainer.mesh)
        obs["grad_accum"] = int(trainer.config.grad_accum_steps)
        obs["journal"] = {
            "slice_loss_coalesced": _journal_count("slice_loss_coalesced")
            - journal_before["slice_loss_coalesced"],
            "reshard": _journal_count("reshard") - journal_before["reshard"],
            "checkpoint_write_failed": _datastream_event_count(
                "checkpoint_write_failed"
            )
            - journal_before["checkpoint_write_failed"],
            "datastream_reshard": _datastream_event_count("reshard")
            - journal_before["datastream_reshard"],
        }

        for h in tuple(plane.hosts):  # survivors drain the epoch
            for nb in plane_iters[h]:
                plane_ids[h].extend(int(v) for v in nb.y)
        obs["plane_seen"] = sorted(v for ids in plane_ids.values() for v in ids)
        obs["plane_total"] = plane_total

        obs["final_latest"] = ck.latest_step()
        if wc_ev is not None:
            ckf = AsyncShardedCheckpointer(frozen, every_steps=1, n_shards=2)
            try:
                cfg_r = make_config()
                if manager.contract.degraded:
                    cfg_r.grad_accum_steps = rescale_grad_accum(
                        1, 8, mesh_for(manager.contract).size
                    )
                trainer_r = Trainer(_Net(), mesh_for(manager.contract), cfg_r)
                template = trainer_r.init(jax.random.PRNGKey(seed), sample)
                restored = ckf.restore_latest(template=template)
                obs["restore_step"] = None if restored is None else restored[1]
                obs["restore_stream_records"] = (
                    (ckf.last_stream_state or {}).get("records_total")
                )
            finally:
                ckf.close()
        ck.close()

        broker.stream_all()
        obs["healed_pairs"] = broker.healed_pairs()
        obs["reprovisions"] = sum(s.reprovisions for s in broker.shards)
        obs["resends"] = state["resends"]
        work_node = broker.route(_WORK_QUEUE).active()
        obs["work_depth"] = 0 if work_node is None else work_node.depth(_WORK_QUEUE)
        obs["total_failovers"] = work_conn.failovers + sum(
            c.failovers for c in host_conns.values()
        )
        if fo_ev is not None:
            failed = broker.shards[fo_ev.shard]
            acting = failed.active()
            obs["failed_shard_epoch"] = -1 if acting is None else acting.epoch
            healthy = [
                k for k in range(broker.n_shards) if k != fo_ev.shard
            ]
            obs["healthy_shard_failovers"] = sum(
                conn._conns[k].failovers
                for conn in [work_conn, *host_conns.values()]
                for k in healthy
            )
        obs["slo"] = slo.snapshot()[rule.name]
        obs["transitions"] = transitions
        obs["firing_by_round"] = firing_by_round

        GauntletInvariants(schedule).verify(report, obs)

        report.details.update(
            straight_losses=[round(v, 6) for v in straight],
            gauntlet_losses=[round(v, 6) for v in losses],
            plane_records=plane_total,
            plane_per_host={h: len(ids) for h, ids in sorted(plane_ids.items())},
            work_depth=obs["work_depth"],
            resends=obs["resends"],
            healed_pairs=obs["healed_pairs"],
            alert_timeline=transitions,
            journal_deltas=obs["journal"],
            restore_step=obs.get("restore_step"),
        )
        get_recorder().record(
            "gauntlet",
            event="run",
            seed=seed,
            passed=bool(report.passed),
            faults=len(schedule.events),
            violations=len(report.violations),
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return report


def shrink_schedule(
    schedule: FaultSchedule,
    still_fails: Callable[[FaultSchedule], bool],
) -> FaultSchedule:
    """Greedy delta-debugging: repeatedly drop the first event whose
    removal keeps the schedule both valid and failing, until no single
    removal does.  Deterministic (fixed scan order), never returns an
    empty schedule — the minimal reproducer to pin as a regression."""
    current = schedule
    shrunk = True
    while shrunk and len(current.events) > 1:
        shrunk = False
        for i in range(len(current.events)):
            events = current.events[:i] + current.events[i + 1 :]
            candidate = FaultSchedule(
                seed=current.seed,
                events=events,
                total_steps=current.total_steps,
                n_broker_shards=current.n_broker_shards,
            )
            if candidate.validate():
                continue
            if still_fails(candidate):
                current = candidate
                shrunk = True
                break
    return current


#: Pinned minimal reproducers from past sweep failures, auto-registered
#: as scenarios (name -> schedule).  Every entry here is a bug that WAS
#: shrunk, fixed at source, and kept as a permanent regression gate.
REGRESSION_SCHEDULES: dict[str, FaultSchedule] = {}


def _register_regressions() -> None:
    """Each pinned reproducer becomes a scenario of its own, joining
    the chaos gate and the DLC610 replay audit automatically.  The
    schedule is fixed; the seed argument is ignored by design — a
    reproducer replays ONE incident exactly."""
    from deeplearning_cfn_tpu.chaos import scenarios as _scenarios

    def make(schedule: FaultSchedule):
        def run(seed: int) -> ScenarioReport:
            return run_gauntlet(schedule)

        run.__doc__ = "Pinned gauntlet regression reproducer (fixed schedule)."
        return run

    for name, schedule in sorted(REGRESSION_SCHEDULES.items()):
        _scenarios.SCENARIOS[f"gauntlet-{name}"] = make(schedule)
        _scenarios.SCENARIO_FAULTS[f"gauntlet-{name}"] = tuple(
            e.kind for e in schedule.events
        )


_register_regressions()


def run_gauntlet_sweep(
    n_seeds: int = 20,
    base_seed: int = 0,
    runner: Callable[[FaultSchedule], ScenarioReport] = run_gauntlet,
    shrink: bool = True,
) -> dict[str, Any]:
    """The seeded incident explorer: run ``n_seeds`` perturbed fault
    schedules; for every failing one, greedily shrink it to a minimal
    reproducer.  Returns a deterministic summary (and journals a
    ``gauntlet``/``sweep`` event for the exporter)."""
    from deeplearning_cfn_tpu.obs.recorder import get_recorder

    failures: list[dict[str, Any]] = []
    fault_counts: dict[str, int] = {k: 0 for k in FAULT_KINDS}
    for s in range(n_seeds):
        schedule = perturbed_schedule(base_seed + s)
        for e in schedule.events:
            fault_counts[e.kind] += 1
        rep = runner(schedule)
        if not rep.passed:
            entry: dict[str, Any] = {
                "seed": schedule.seed,
                "schedule": schedule.to_dict(),
                "violations": list(rep.violations),
            }
            if shrink:
                minimal = shrink_schedule(
                    schedule, lambda sc: not runner(sc).passed
                )
                entry["shrunk"] = minimal.to_dict()
            failures.append(entry)
    summary = {
        "seeds": n_seeds,
        "base_seed": base_seed,
        "passed": n_seeds - len(failures),
        "failures": failures,
        "fault_counts": fault_counts,
    }
    get_recorder().record(
        "gauntlet",
        event="sweep",
        seeds=n_seeds,
        base_seed=base_seed,
        failures=len(failures),
    )
    return summary
