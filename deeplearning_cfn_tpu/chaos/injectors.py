"""Seeded fault injectors over the stack's real seams.

Every injector takes a ``seed`` and owns a private ``random.Random``, so
a chaos soak replays byte-for-byte: the same seed produces the same
faults in the same order.  None of them read the wall clock — time, when
it matters, comes from an injected :class:`~..utils.timeouts.Clock` (the
chaos scenarios pass fakes, so soaks run in microseconds).

Seams covered:

* :class:`FlakyOpener` — ``GoogleAuthTransport.opener``: HTTP error
  bursts (429/500/503), connection resets, and full hard-down outages.
* :class:`StallingConnectionFactory` — ``Heartbeater.connection_factory``:
  heartbeat stalls (the agent is alive; its beats don't land).
* :class:`ChaosQueue` — any :class:`RendezvousQueue`: message drop,
  delay, duplication, and reorder, the SQS pathologies consumers must
  already tolerate.
* :class:`TornDisk` / :class:`SlowDisk` / :class:`ManifestCrashDisk` —
  checkpoint ``CheckpointIO``: torn writes (a prefix lands, then
  OSError), high-latency disks on virtual time, and a writer crash at
  the async sharded checkpointer's manifest commit point.  All disk
  injectors share the :class:`DiskInjector` ``wrap()`` seam, so two
  faults stack deterministically (outermost injector first):
  ``SlowDisk(clock).wrap(TornDisk(seed))``.
"""

from __future__ import annotations

import io
import json
import os
import random
import urllib.error
from pathlib import Path
from typing import Any, Callable, Sequence

from deeplearning_cfn_tpu.cluster.queue import Message, RendezvousQueue
from deeplearning_cfn_tpu.utils.timeouts import Clock, FakeClock


class RecordingClock(FakeClock):
    """A FakeClock that remembers every sleep — the jitter-bounds probe:
    a retry loop run over this clock exposes its exact backoff schedule
    without a single real sleep."""

    def __init__(self, start: float = 0.0):
        super().__init__(start)
        self.sleeps: list[float] = []

    def sleep(self, seconds: float) -> None:
        self.sleeps.append(seconds)
        super().sleep(seconds)


# --- RPC layer ---------------------------------------------------------------


class _CannedResponse:
    def __init__(self, payload: dict):
        self._data = json.dumps(payload).encode()

    def read(self) -> bytes:
        return self._data

    def __enter__(self) -> "_CannedResponse":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


class FlakyOpener:
    """Drop-in for ``GoogleAuthTransport.opener``: seeded bursts of
    retryable HTTP errors and connection resets around canned successes.

    ``hard_down=True`` models a full control-plane outage: every request
    raises a connection reset (what a circuit breaker must convert into
    fail-fast refusals).
    """

    def __init__(
        self,
        seed: int = 0,
        error_rate: float = 0.4,
        reset_rate: float = 0.1,
        errors: Sequence[int] = (429, 500, 503),
        payload: dict | None = None,
        hard_down: bool = False,
    ):
        self._rng = random.Random(seed)
        self.error_rate = error_rate
        self.reset_rate = reset_rate
        self.errors = tuple(errors)
        self.payload = payload if payload is not None else {"ok": True}
        self.hard_down = hard_down
        self.requests: list[Any] = []

    def __call__(self, req: Any, timeout: float | None = None) -> _CannedResponse:
        self.requests.append(req)
        if self.hard_down:
            raise urllib.error.URLError("injected hard outage")
        roll = self._rng.random()
        if roll < self.reset_rate:
            raise urllib.error.URLError("injected connection reset")
        if roll < self.reset_rate + self.error_rate:
            code = self._rng.choice(self.errors)
            raise urllib.error.HTTPError(
                "https://chaos", code, "injected", hdrs=None, fp=io.BytesIO(b"{}")
            )
        return _CannedResponse(self.payload)


# --- heartbeat layer ---------------------------------------------------------


class _StallingConnection:
    """Wraps a heartbeat connection; seeded beats raise instead of landing."""

    def __init__(self, conn: Any, rng: random.Random, stall_rate: float):
        self._conn = conn
        self._rng = rng
        self.stall_rate = stall_rate

    def heartbeat(self, worker_id: str) -> int:
        if self._rng.random() < self.stall_rate:
            raise ConnectionError("injected heartbeat stall")
        return self._conn.heartbeat(worker_id)

    def close(self) -> None:
        self._conn.close()


class StallingConnectionFactory:
    """``Heartbeater.connection_factory`` wrapper: each dialed connection
    stalls a seeded fraction of beats, exercising the real
    drop-and-redial path under sustained (not one-shot) flakiness."""

    def __init__(
        self,
        inner_factory: Callable[[], Any],
        seed: int = 0,
        stall_rate: float = 0.3,
    ):
        self._inner = inner_factory
        self._rng = random.Random(seed)
        self.stall_rate = stall_rate
        self.dials = 0

    def __call__(self) -> _StallingConnection:
        self.dials += 1
        return _StallingConnection(self._inner(), self._rng, self.stall_rate)


# --- queue layer -------------------------------------------------------------


class ChaosQueue(RendezvousQueue):
    """Seeded drop/delay/duplicate/reorder wrapper over any queue.

    These are exactly the SQS behaviors the consumers already claim to
    tolerate (at-least-once, visibility churn, no ordering); the wrapper
    makes the claim falsifiable.  Delay is measured in *operations* (the
    message reappears after ``delay_ops`` further send/receive calls),
    which keeps it deterministic without a clock.
    """

    def __init__(
        self,
        inner: RendezvousQueue,
        seed: int = 0,
        drop_rate: float = 0.0,
        delay_rate: float = 0.0,
        delay_ops: int = 3,
        duplicate_rate: float = 0.0,
        reorder: bool = False,
    ):
        self.name = inner.name
        self._inner = inner
        self._rng = random.Random(seed)
        self.drop_rate = drop_rate
        self.delay_rate = delay_rate
        self.delay_ops = delay_ops
        self.duplicate_rate = duplicate_rate
        self.reorder = reorder
        self._ops = 0
        self._held: list[tuple[int, dict]] = []
        self.dropped = 0
        self.delayed = 0
        self.duplicated = 0

    def _tick(self) -> None:
        self._ops += 1
        due = [body for release, body in self._held if release <= self._ops]
        self._held = [
            (release, body)
            for release, body in self._held
            if release > self._ops
        ]
        for body in due:
            self._inner.send(body)

    def send(self, body: dict) -> str:
        self._tick()
        roll = self._rng.random()
        if roll < self.drop_rate:
            self.dropped += 1
            return "chaos-dropped"
        if roll < self.drop_rate + self.delay_rate:
            self.delayed += 1
            self._held.append((self._ops + self.delay_ops, body))
            return "chaos-delayed"
        if roll < self.drop_rate + self.delay_rate + self.duplicate_rate:
            self.duplicated += 1
            mid = self._inner.send(body)
            self._inner.send(body)
            return mid
        return self._inner.send(body)

    def receive(
        self,
        max_messages: int = 10,
        visibility_timeout_s: float = 60.0,
    ) -> list[Message]:
        self._tick()
        out = self._inner.receive(max_messages, visibility_timeout_s)
        if self.reorder and len(out) > 1:
            self._rng.shuffle(out)
        return out

    def delete(self, receipt: str) -> None:
        self._inner.delete(receipt)

    def purge(self) -> None:
        self._held.clear()
        self._inner.purge()

    def approximate_depth(self) -> int:
        depth = getattr(self._inner, "approximate_depth", None)
        inner_depth = depth() if depth is not None else 0
        return inner_depth + len(self._held)

    def flush_held(self) -> int:
        """Deliver every delayed message now (end-of-schedule drain)."""
        held = [body for _release, body in self._held]
        self._held.clear()
        for body in held:
            self._inner.send(body)
        return len(held)


# --- disk layer --------------------------------------------------------------


class _RealDisk:
    """The default delegation target: plain durable IO with the same
    fsync discipline as ``train.checkpoint.CheckpointIO`` (kept local so
    importing injectors never drags in jax/orbax)."""

    def write_bytes(self, path: Path, data: bytes) -> None:
        with open(path, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())

    def replace(self, src: Path, dst: Path) -> None:
        os.replace(src, dst)

    def read_bytes(self, path: Path) -> bytes:
        return Path(path).read_bytes()


class DiskInjector:
    """Base for CheckpointIO-compatible disk injectors: the uniform
    ``wrap()`` seam.

    Every disk injector runs its fault logic at the OUTER layer and
    delegates the raw bytes to ``inner`` (a real durable disk by
    default), so two faults stack deterministically and order is
    explicit::

        io = SlowDisk(clock).wrap(TornDisk(seed=1))   # outermost first

    reads "consume latency, then roll for a torn write".  ``wrap``
    re-points the delegation and returns ``self``, so stacks compose
    fluently and the outermost injector is handed to the checkpointer.
    """

    def __init__(self, inner: Any | None = None):
        self.inner: Any = inner if inner is not None else _RealDisk()

    def wrap(self, inner: Any) -> "DiskInjector":
        """Delegate raw IO to ``inner`` (another injector or a real
        CheckpointIO); returns self so stacks read outermost-first."""
        self.inner = inner
        return self

    def write_bytes(self, path: Path, data: bytes) -> None:
        self.inner.write_bytes(path, data)

    def replace(self, src: Path, dst: Path) -> None:
        self.inner.replace(src, dst)

    def read_bytes(self, path: Path) -> bytes:
        return self.inner.read_bytes(path)


class TornDisk(DiskInjector):
    """Torn-write disk: seeded writes persist only a prefix of the bytes
    (through the inner disk), then raise OSError — the fault the atomic
    write-temp -> fsync -> rename protocol must make unobservable."""

    def __init__(self, seed: int = 0, fail_rate: float = 0.5, inner: Any | None = None):
        super().__init__(inner)
        self._rng = random.Random(seed)
        self.fail_rate = fail_rate
        self.writes = 0
        self.torn = 0

    def write_bytes(self, path: Path, data: bytes) -> None:
        self.writes += 1
        if self._rng.random() < self.fail_rate:
            self.torn += 1
            self.inner.write_bytes(path, data[: max(1, len(data) // 2)])
            raise OSError("injected torn write")
        self.inner.write_bytes(path, data)


class ManifestCrashDisk(DiskInjector):
    """Disk that dies exactly at the manifest write once :meth:`arm`\\ ed
    — the async sharded writer's commit point
    (train/datastream.AsyncShardedCheckpointer writes every shard file,
    THEN the manifest).  Shard files written before the crash land
    normally, so the fault leaves realistic litter on disk; the manifest
    never lands, so ``restore_latest`` must fall back to the previous
    checkpoint untouched.  Deterministic by construction — no RNG, the
    crash fires on the first armed manifest write.  With ``once=True``
    (the default) the crash disarms itself after firing, so a run that
    keeps checkpointing past the incident recovers on the next save."""

    def __init__(self, marker: str = "manifest", once: bool = True, inner: Any | None = None):
        super().__init__(inner)
        self.marker = marker
        self.once = once
        self.armed = False
        self.crashes = 0
        self.writes = 0

    def arm(self) -> None:
        self.armed = True

    def write_bytes(self, path: Path, data: bytes) -> None:
        self.writes += 1
        if self.armed and self.marker in Path(path).name:
            self.crashes += 1
            if self.once:
                self.armed = False
            raise OSError("injected writer crash at the manifest commit point")
        self.inner.write_bytes(path, data)


class SlowDisk(DiskInjector):
    """Slow disk: every write consumes ``latency_s`` of injected-clock
    time before the inner disk lands it (virtually slow, wall-clock
    instant)."""

    def __init__(self, clock: Clock, latency_s: float = 5.0, inner: Any | None = None):
        super().__init__(inner)
        self.clock = clock
        self.latency_s = latency_s
        self.writes = 0

    def write_bytes(self, path: Path, data: bytes) -> None:
        self.writes += 1
        self.clock.sleep(self.latency_s)
        self.inner.write_bytes(path, data)
