"""Chaos-injection layer: seeded, deterministic faults over real seams.

``injectors`` wraps the seams the production stack already exposes
(GCPTransport's ``opener``, Heartbeater's ``connection_factory``, the
RendezvousQueue interface, checkpoint I/O) with seeded fault models;
``scenarios`` composes them into named end-to-end soaks — silent-death,
partition, flaky-rpc, slow-disk — that drive the REAL components over
virtual time and assert recovery invariants.  ``dlcfn chaos`` is the CLI
entry point; tests/test_chaos.py the regression harness.
"""

from deeplearning_cfn_tpu.chaos.injectors import (
    ChaosQueue,
    FlakyOpener,
    RecordingClock,
    SlowDisk,
    StallingConnectionFactory,
    TornDisk,
)
from deeplearning_cfn_tpu.chaos.scenarios import (
    SCENARIOS,
    ScenarioReport,
    run_scenario,
)

__all__ = [
    "ChaosQueue",
    "FlakyOpener",
    "RecordingClock",
    "SCENARIOS",
    "ScenarioReport",
    "SlowDisk",
    "StallingConnectionFactory",
    "TornDisk",
    "run_scenario",
]
