"""Chaos-injection layer: seeded, deterministic faults over real seams.

``injectors`` wraps the seams the production stack already exposes
(GCPTransport's ``opener``, Heartbeater's ``connection_factory``, the
RendezvousQueue interface, checkpoint I/O) with seeded fault models —
disk injectors share a uniform ``wrap()`` seam so faults stack;
``scenarios`` composes them into named end-to-end soaks — silent-death,
partition, flaky-rpc, slow-disk — that drive the REAL components over
virtual time and assert recovery invariants.  ``gauntlet`` composes
MULTIPLE faults into one incident against one end-to-end workload from
a declarative :class:`FaultSchedule`, with a seeded sweep explorer and
a greedy schedule shrinker.  ``dlcfn chaos`` is the CLI entry point;
tests/test_chaos.py and tests/test_gauntlet.py the regression harness.
"""

from deeplearning_cfn_tpu.chaos.gauntlet import (
    FAULT_KINDS,
    REGRESSION_SCHEDULES,
    FaultEvent,
    FaultSchedule,
    GauntletInvariants,
    pinned_schedule,
    perturbed_schedule,
    run_gauntlet,
    run_gauntlet_sweep,
    shrink_schedule,
)
from deeplearning_cfn_tpu.chaos.injectors import (
    ChaosQueue,
    DiskInjector,
    FlakyOpener,
    ManifestCrashDisk,
    RecordingClock,
    SlowDisk,
    StallingConnectionFactory,
    TornDisk,
)
from deeplearning_cfn_tpu.chaos.scenarios import (
    SCENARIO_FAULTS,
    SCENARIOS,
    ScenarioReport,
    run_scenario,
)

__all__ = [
    "ChaosQueue",
    "DiskInjector",
    "FAULT_KINDS",
    "FaultEvent",
    "FaultSchedule",
    "FlakyOpener",
    "GauntletInvariants",
    "ManifestCrashDisk",
    "REGRESSION_SCHEDULES",
    "RecordingClock",
    "SCENARIOS",
    "SCENARIO_FAULTS",
    "ScenarioReport",
    "SlowDisk",
    "StallingConnectionFactory",
    "TornDisk",
    "perturbed_schedule",
    "pinned_schedule",
    "run_gauntlet",
    "run_gauntlet_sweep",
    "run_scenario",
    "shrink_schedule",
]
