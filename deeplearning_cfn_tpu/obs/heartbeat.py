"""Agent-side heartbeater: a daemon thread beating HEARTBEAT at the broker.

Each worker agent runs one :class:`Heartbeater`; the supervisor side
(cluster/broker_service.py BrokerLivenessWatcher) polls the broker's
heartbeat table and drives the :mod:`~deeplearning_cfn_tpu.obs.liveness`
state machine.  The thread owns its own connection and reconnects with
a fresh dial on any error — a broker restart costs one missed interval,
not a dead worker.

cluster.broker_client is imported lazily: obs must stay importable
before (and without) the cluster layer, which itself imports
obs.tracing for RPC spans.
"""

from __future__ import annotations

import os
import threading

from deeplearning_cfn_tpu.obs.recorder import get_recorder
from deeplearning_cfn_tpu.utils.logging import get_logger

log = get_logger("dlcfn.obs")

ENV_INTERVAL = "DLCFN_HEARTBEAT_S"
DEFAULT_INTERVAL_S = 10.0


def heartbeat_interval_s() -> float:
    """Configured beat interval (``$DLCFN_HEARTBEAT_S``, default 10s)."""
    raw = os.environ.get(ENV_INTERVAL, "")
    try:
        value = float(raw)
    except ValueError:
        return DEFAULT_INTERVAL_S
    return value if value > 0 else DEFAULT_INTERVAL_S


class Heartbeater(threading.Thread):
    """Beats ``HEARTBEAT <worker_id>`` at the broker every interval.

    ``telemetry_source``: optional zero-arg callable returning the
    agent's current gauge/summary snapshot (the shape
    ``obs.aggregator.encode_snapshot`` accepts) or ``None`` to skip a
    cycle.  When set, every successful beat piggybacks one ``TELEM``
    frame on the SAME connection — fleet telemetry costs zero extra
    dials and inherits the beat cadence.  A telemetry failure is
    contained: the beat already landed, so liveness never regresses
    because a snapshot didn't.
    """

    def __init__(
        self,
        host: str,
        port: int,
        worker_id: str,
        token: str | None = None,
        interval_s: float | None = None,
        connect_timeout_s: float = 10.0,
        connection_factory=None,
        telemetry_source=None,
    ):
        # token=None -> BrokerConnection's ambient $DLCFN_BROKER_TOKEN
        # (how agents authenticate); pass "" for an open dev broker.
        super().__init__(name=f"heartbeater-{worker_id}", daemon=True)
        self.host = host
        self.port = port
        self.worker_id = worker_id
        self.token = token
        self.interval_s = (
            interval_s if interval_s is not None else heartbeat_interval_s()
        )
        self.connect_timeout_s = connect_timeout_s
        # connection_factory: () -> an object with heartbeat()/close().
        # The seam the deterministic interleaving harness
        # (analysis/schedules.py) injects a simulated broker through, so
        # beat_step() can be driven cooperatively without sockets.
        self._connection_factory = connection_factory
        self._telemetry_source = telemetry_source
        self.beats_sent = 0
        self.snapshots_sent = 0
        # beats_sent is read by other threads (status displays, tests);
        # the daemon loop increments it only under this lock.
        self._lock = threading.Lock()
        # not named _stop: threading.Thread's join internals
        # call a private _stop() method of that name.
        self._halt = threading.Event()
        self._conn = None

    def _dial(self):
        if self._connection_factory is not None:
            return self._connection_factory()
        from deeplearning_cfn_tpu.cluster.broker_client import BrokerConnection

        return BrokerConnection(
            self.host,
            self.port,
            token=self.token,
            timeout_s=self.connect_timeout_s,
        )

    def _beat_once(self) -> None:
        if self._conn is None:
            self._conn = self._dial()
        self._conn.heartbeat(self.worker_id)
        with self._lock:
            self.beats_sent += 1
            seq = self.beats_sent
        # Journaled with the SENDER's clock, outside the lock: matched
        # against the supervisor's heartbeat_observed event (same worker,
        # same seq) by obs/trace_export.py to recover cross-host clock
        # offsets for the merged timeline.
        get_recorder().record("heartbeat_sent", worker=self.worker_id, seq=seq)
        self._ship_telemetry()

    def _ship_telemetry(self) -> None:
        if self._telemetry_source is None or self._conn is None:
            return
        telem = getattr(self._conn, "telem", None)
        if telem is None:
            return  # connection seam predates TELEM (old sim); skip quietly
        try:
            snapshot = self._telemetry_source()
            if snapshot is None:
                return
            from deeplearning_cfn_tpu.obs.aggregator import encode_snapshot

            telem(self.worker_id, encode_snapshot(snapshot))
            with self._lock:
                self.snapshots_sent += 1
        except Exception as exc:
            # Contained: the beat landed; a telemetry hiccup must not
            # tear down the connection liveness depends on.
            log.warning(
                "telemetry from %s failed: %s", self.worker_id, exc
            )

    def beat_step(self) -> bool:
        """One protected beat iteration (the body of the daemon loop).

        Public so the interleaving harness can drive the REAL beat +
        reconnect logic cooperatively; returns whether the beat landed.
        """
        try:
            self._beat_once()
            return True
        except Exception as exc:
            # Drop the wedged connection; next beat dials fresh.
            log.warning("heartbeat to %s:%d failed: %s", self.host, self.port, exc)
            self._close_conn()
            return False

    def run(self) -> None:
        get_recorder().record(
            "heartbeater_start", worker=self.worker_id, interval_s=self.interval_s
        )
        while not self._halt.is_set():
            self.beat_step()
            self._halt.wait(self.interval_s)
        self._close_conn()

    def _close_conn(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:
                pass
            self._conn = None

    def stop(self, join_timeout_s: float = 5.0) -> None:
        """Signal the loop to exit and wait (bounded) for it."""
        self._halt.set()
        if self.is_alive():
            self.join(timeout=join_timeout_s)
