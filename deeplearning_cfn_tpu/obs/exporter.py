"""Prometheus text exposition for liveness and span aggregates.

``dlcfn status --format prom`` renders through here; the output follows
the text format (``# HELP`` / ``# TYPE`` then ``name{labels} value``)
so a node-exporter textfile collector or a curl-into-pushgateway cron
can scrape it without a client library.
"""

from __future__ import annotations

from typing import Any, Mapping


def _escape(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _labels(**labels: str) -> str:
    body = ",".join(f'{k}="{_escape(str(v))}"' for k, v in labels.items() if v != "")
    return "{" + body + "}" if body else ""


def fold_reshard_events(events) -> dict[str, Any]:
    """Fold flight-journal ``reshard`` / ``reshard_fallback`` events into
    the counters the exporter and ``dlcfn status`` surface.  Empty dict
    when the journal holds neither kind."""
    out: dict[str, Any] = {
        "total": 0,
        "seconds_total": 0.0,
        "fallback_total": 0,
        "last": None,
    }
    for event in events:
        kind = event.get("kind")
        if kind == "reshard":
            out["total"] += 1
            out["seconds_total"] = round(
                out["seconds_total"] + float(event.get("seconds") or 0.0), 6
            )
            out["last"] = {
                k: event.get(k)
                for k in (
                    "step",
                    "old_topology",
                    "new_topology",
                    "grad_accum_before",
                    "grad_accum_after",
                )
            }
        elif kind == "reshard_fallback":
            out["fallback_total"] += 1
    if not out["total"] and not out["fallback_total"]:
        return {}
    return out


def fold_serve_events(events) -> dict[str, Any]:
    """Fold flight-journal ``serve_metrics`` events into the latest
    snapshot per replica (each journal write is a full snapshot, so
    last-wins is the fold).  Empty dict when no replica ever reported."""
    out: dict[str, Any] = {}
    for event in events:
        if event.get("kind") != "serve_metrics":
            continue
        replica = str(event.get("replica") or "?")
        out[replica] = {
            k: event.get(k)
            for k in (
                "steps",
                "admitted",
                "completed",
                "rejected",
                "active_slots",
                "queue_depth",
                "tokens_out",
                "tokens_per_s",
                "ttft_ms",
                "itl_ms",
                "free_blocks",
                "recycled_blocks",
                "max_wait_steps",
                "kv_transfer_bytes",
                "disaggregated",
            )
        }
    return out


def fold_comms_events(events) -> dict[str, Any]:
    """Fold flight-journal ``comms_audit`` events into the latest budget
    per audited program (each audit journals a full per-program readout,
    so last-wins is the fold).  Empty dict when no audit ever ran."""
    out: dict[str, Any] = {}
    for event in events:
        if event.get("kind") != "comms_audit":
            continue
        for name, program in (event.get("programs") or {}).items():
            if not isinstance(program, Mapping):
                continue
            out[str(name)] = {
                k: program.get(k)
                for k in (
                    "collective_count",
                    "collective_bytes",
                    "peak_hbm_bytes",
                    "by_op",
                    "unpredicted_gathers",
                )
            }
    return out


def render_prometheus(
    liveness: Mapping[str, Mapping[str, Any]] | None = None,
    spans: Mapping[str, Mapping[str, Any]] | None = None,
    cluster: str = "",
    pipeline: Mapping[str, Mapping[str, Any]] | None = None,
    reshard: Mapping[str, Any] | None = None,
    mesh: Mapping[str, Any] | None = None,
    profile: Mapping[str, Any] | None = None,
    serve: Mapping[str, Mapping[str, Any]] | None = None,
    broker: Mapping[str, Any] | None = None,
    comms: Mapping[str, Mapping[str, Any]] | None = None,
) -> str:
    """Render liveness snapshot + span aggregates + input-pipeline
    counters as Prometheus text.

    ``liveness`` is ``LivenessTable.snapshot()``; ``spans`` is
    ``tracing.span_aggregates()`` (span dicts carrying ``p50_s`` /
    ``p95_s`` / ``p99_s`` additionally render as a summary-style
    ``dlcfn_span_seconds`` family); ``pipeline`` is
    ``train.pipeline.fold_pipeline_events()``; ``reshard`` is
    ``fold_reshard_events()``; ``mesh`` is the current mesh/contract
    shape from ``dlcfn status --cluster``; ``profile`` is the
    ``dlcfn status --profile`` dict (``{"profilers": {name: snapshot}}``)
    whose per-phase quantiles render as ``dlcfn_step_phase_ms``
    summaries; ``broker`` is
    ``broker_service.broker_replication_status()`` (role/epoch per node
    plus replication lag); ``comms`` is ``fold_comms_events()`` (the
    comms-audit sentinel's per-program collective/HBM budgets).  Any may
    be None/empty.
    """
    lines: list[str] = []
    if liveness:
        lines += [
            "# HELP dlcfn_worker_up 1 while the worker's heartbeat is not DEAD.",
            "# TYPE dlcfn_worker_up gauge",
        ]
        for worker, row in liveness.items():
            labels = _labels(cluster=cluster, worker=worker, state=row["state"])
            lines.append(
                f"dlcfn_worker_up{labels} {0 if row['state'] == 'dead' else 1}"
            )
        lines += [
            "# HELP dlcfn_heartbeat_age_seconds Seconds since the worker's last heartbeat.",
            "# TYPE dlcfn_heartbeat_age_seconds gauge",
        ]
        for worker, row in liveness.items():
            labels = _labels(cluster=cluster, worker=worker)
            lines.append(f"dlcfn_heartbeat_age_seconds{labels} {row['age_s']}")
        lines += [
            "# HELP dlcfn_heartbeats_total Heartbeats observed from the worker.",
            "# TYPE dlcfn_heartbeats_total counter",
        ]
        for worker, row in liveness.items():
            labels = _labels(cluster=cluster, worker=worker)
            lines.append(f"dlcfn_heartbeats_total{labels} {row['beats']}")
    if spans:
        lines += [
            "# HELP dlcfn_span_count Completed spans by name.",
            "# TYPE dlcfn_span_count counter",
        ]
        for name, agg in spans.items():
            lines.append(f"dlcfn_span_count{_labels(span=name)} {agg['count']}")
        lines += [
            "# HELP dlcfn_span_seconds_total Total wall seconds spent in spans.",
            "# TYPE dlcfn_span_seconds_total counter",
        ]
        for name, agg in spans.items():
            lines.append(
                f"dlcfn_span_seconds_total{_labels(span=name)} {agg['total_s']}"
            )
        lines += [
            "# HELP dlcfn_span_seconds_max Longest single span by name.",
            "# TYPE dlcfn_span_seconds_max gauge",
        ]
        for name, agg in spans.items():
            lines.append(f"dlcfn_span_seconds_max{_labels(span=name)} {agg['max_s']}")
        quantiled = {
            name: agg for name, agg in spans.items() if "p50_s" in agg
        }
        if quantiled:
            lines += [
                "# HELP dlcfn_span_seconds Span duration quantiles over the journal window.",
                "# TYPE dlcfn_span_seconds summary",
            ]
            for name, agg in quantiled.items():
                for quantile, key in (
                    ("0.5", "p50_s"),
                    ("0.95", "p95_s"),
                    ("0.99", "p99_s"),
                ):
                    value = agg.get(key)
                    if value is None:
                        continue
                    lines.append(
                        f"dlcfn_span_seconds"
                        f"{_labels(span=name, quantile=quantile)} {value}"
                    )
                lines.append(
                    f"dlcfn_span_seconds_sum{_labels(span=name)} {agg['total_s']}"
                )
                lines.append(
                    f"dlcfn_span_seconds_count{_labels(span=name)} {agg['count']}"
                )
    if pipeline:
        gauges = (
            ("bytes_transferred", "Host->device bytes moved by the input pipeline."),
            ("host_input_seconds", "Seconds producers spent in the source iterator."),
            ("producer_stall_seconds", "Seconds producers blocked on a full buffer."),
            ("consumer_wait_seconds", "Seconds the training loop waited for input."),
            ("overlap_fraction", "Fraction of the run with input hidden behind compute."),
        )
        for key, help_text in gauges:
            lines += [
                f"# HELP dlcfn_input_pipeline_{key} {help_text}",
                f"# TYPE dlcfn_input_pipeline_{key} gauge",
            ]
            for name, agg in pipeline.items():
                value = agg.get(key)
                if value is None:
                    continue
                lines.append(
                    f"dlcfn_input_pipeline_{key}"
                    f"{_labels(cluster=cluster, pipeline=name)} {value}"
                )
    if reshard:
        counters = (
            ("dlcfn_reshard_total", "counter", "Live elastic reshards completed.", "total"),
            (
                "dlcfn_reshard_seconds",
                "gauge",
                "Total seconds spent pausing and resharding (injected clock).",
                "seconds_total",
            ),
            (
                "dlcfn_reshard_fallback_total",
                "counter",
                "Reshards that degraded to the checkpoint/restore path.",
                "fallback_total",
            ),
        )
        for name, kind, help_text, key in counters:
            lines += [f"# HELP {name} {help_text}", f"# TYPE {name} {kind}"]
            lines.append(f"{name}{_labels(cluster=cluster)} {reshard.get(key, 0)}")
    if mesh:
        shape = (
            ("slices", "Slices in the current cluster contract."),
            ("workers", "Worker hosts in the current cluster contract."),
            ("chips_total", "Total chips across the current mesh."),
        )
        for key, help_text in shape:
            value = mesh.get(key)
            if value is None:
                continue
            lines += [
                f"# HELP dlcfn_mesh_{key} {help_text}",
                f"# TYPE dlcfn_mesh_{key} gauge",
            ]
            lines.append(f"dlcfn_mesh_{key}{_labels(cluster=cluster)} {value}")
    profilers = (profile or {}).get("profilers") or {}
    if profilers:
        lines += [
            "# HELP dlcfn_step_phase_ms Step-phase duration quantiles (rolling window).",
            "# TYPE dlcfn_step_phase_ms summary",
        ]
        for prof_name, snap in profilers.items():
            for phase, stats in (snap.get("phases") or {}).items():
                for quantile, key in (
                    ("0.5", "p50_ms"),
                    ("0.95", "p95_ms"),
                    ("0.99", "p99_ms"),
                ):
                    value = stats.get(key)
                    if value is None:
                        continue
                    lines.append(
                        f"dlcfn_step_phase_ms"
                        f"{_labels(cluster=cluster, profiler=prof_name, phase=phase, quantile=quantile)}"
                        f" {value}"
                    )
                lines.append(
                    f"dlcfn_step_phase_ms_sum"
                    f"{_labels(cluster=cluster, profiler=prof_name, phase=phase)}"
                    f" {stats.get('total_ms', 0.0)}"
                )
                lines.append(
                    f"dlcfn_step_phase_ms_count"
                    f"{_labels(cluster=cluster, profiler=prof_name, phase=phase)}"
                    f" {stats.get('count', 0)}"
                )
        lines += [
            "# HELP dlcfn_step_ms Whole-step duration quantiles (rolling window).",
            "# TYPE dlcfn_step_ms summary",
        ]
        for prof_name, snap in profilers.items():
            step_ms = snap.get("step_ms") or {}
            for quantile, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                value = step_ms.get(key)
                if value is None:
                    continue
                lines.append(
                    f"dlcfn_step_ms"
                    f"{_labels(cluster=cluster, profiler=prof_name, quantile=quantile)}"
                    f" {value}"
                )
            lines.append(
                f"dlcfn_step_ms_count"
                f"{_labels(cluster=cluster, profiler=prof_name)}"
                f" {snap.get('steps', 0)}"
            )
    if serve:
        for key, help_text in (
            ("active_slots", "Decode slots currently occupied on the replica."),
            ("queue_depth", "Requests admitted but not yet slotted."),
            ("tokens_per_s", "Sampled tokens per second (replica lifetime)."),
        ):
            lines += [
                f"# HELP dlcfn_serve_{key} {help_text}",
                f"# TYPE dlcfn_serve_{key} gauge",
            ]
            for replica, snap in serve.items():
                value = snap.get(key)
                if value is None:
                    continue
                lines.append(
                    f"dlcfn_serve_{key}"
                    f"{_labels(cluster=cluster, replica=replica)} {value}"
                )
        lines += [
            "# HELP dlcfn_serve_ttft_ms Time-to-first-token quantiles (replica lifetime).",
            "# TYPE dlcfn_serve_ttft_ms summary",
        ]
        for replica, snap in serve.items():
            ttft = snap.get("ttft_ms") or {}
            for quantile, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                value = ttft.get(key)
                if value is None:
                    continue
                lines.append(
                    f"dlcfn_serve_ttft_ms"
                    f"{_labels(cluster=cluster, replica=replica, quantile=quantile)}"
                    f" {value}"
                )
            lines.append(
                f"dlcfn_serve_ttft_ms_count"
                f"{_labels(cluster=cluster, replica=replica)}"
                f" {snap.get('admitted', 0)}"
            )
    if comms:
        for key, help_text in (
            (
                "collective_bytes",
                "Bytes moved by collectives per execution of the audited program.",
            ),
            (
                "peak_hbm_bytes",
                "Peak-HBM estimate (args + outputs + temps - aliased) of the audited program.",
            ),
            (
                "collective_count",
                "Collective ops (all-gather/all-reduce/...) in the audited program's HLO.",
            ),
        ):
            lines += [
                f"# HELP dlcfn_comms_{key} {help_text}",
                f"# TYPE dlcfn_comms_{key} gauge",
            ]
            for program, snap in comms.items():
                value = snap.get(key)
                if value is None:
                    continue
                lines.append(
                    f"dlcfn_comms_{key}"
                    f"{_labels(cluster=cluster, program=program)} {value}"
                )
    if broker:
        lines += [
            "# HELP dlcfn_broker_role Broker role per node (1 = primary, 0 = standby).",
            "# TYPE dlcfn_broker_role gauge",
            "# HELP dlcfn_broker_epoch Leadership term the node is fenced to.",
            "# TYPE dlcfn_broker_epoch gauge",
            "# HELP dlcfn_broker_up 1 while the node answers on loopback.",
            "# TYPE dlcfn_broker_up gauge",
        ]
        for node_name in ("primary", "standby"):
            node = broker.get(node_name)
            if not node:
                continue
            labels = _labels(
                cluster=cluster,
                node=node_name,
                endpoint=f"{node.get('host')}:{node.get('port')}",
            )
            role = node.get("role")
            lines.append(
                f"dlcfn_broker_role{labels} {1 if role == 'primary' else 0}"
            )
            lines.append(f"dlcfn_broker_epoch{labels} {node.get('epoch') or 0}")
            lines.append(f"dlcfn_broker_up{labels} {1 if node.get('alive') else 0}")
        lag_s = broker.get("lag_seconds")
        if lag_s is not None:
            lines += [
                "# HELP dlcfn_broker_replication_lag_seconds Age of the oldest journal entry the standby has not applied.",
                "# TYPE dlcfn_broker_replication_lag_seconds gauge",
            ]
            lines.append(
                f"dlcfn_broker_replication_lag_seconds{_labels(cluster=cluster)} {lag_s}"
            )
        lag_entries = broker.get("lag_entries")
        if lag_entries is not None:
            lines += [
                "# HELP dlcfn_broker_replication_lag_entries Journal entries the standby has not applied.",
                "# TYPE dlcfn_broker_replication_lag_entries gauge",
            ]
            lines.append(
                f"dlcfn_broker_replication_lag_entries{_labels(cluster=cluster)} {lag_entries}"
            )
    return "\n".join(lines) + ("\n" if lines else "")
