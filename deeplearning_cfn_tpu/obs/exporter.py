"""Prometheus text exposition for liveness and span aggregates.

``dlcfn status --format prom`` renders through here; the output follows
the text format (``# HELP`` / ``# TYPE`` then ``name{labels} value``)
so a node-exporter textfile collector or a curl-into-pushgateway cron
can scrape it without a client library.
"""

from __future__ import annotations

from typing import Any, Mapping


def _escape(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _labels(**labels: str) -> str:
    body = ",".join(f'{k}="{_escape(str(v))}"' for k, v in labels.items() if v != "")
    return "{" + body + "}" if body else ""


def render_prometheus(
    liveness: Mapping[str, Mapping[str, Any]] | None = None,
    spans: Mapping[str, Mapping[str, Any]] | None = None,
    cluster: str = "",
) -> str:
    """Render liveness snapshot + span aggregates as Prometheus text.

    ``liveness`` is ``LivenessTable.snapshot()``; ``spans`` is
    ``tracing.span_aggregates()``.  Either may be None/empty.
    """
    lines: list[str] = []
    if liveness:
        lines += [
            "# HELP dlcfn_worker_up 1 while the worker's heartbeat is not DEAD.",
            "# TYPE dlcfn_worker_up gauge",
        ]
        for worker, row in liveness.items():
            labels = _labels(cluster=cluster, worker=worker, state=row["state"])
            lines.append(
                f"dlcfn_worker_up{labels} {0 if row['state'] == 'dead' else 1}"
            )
        lines += [
            "# HELP dlcfn_heartbeat_age_seconds Seconds since the worker's last heartbeat.",
            "# TYPE dlcfn_heartbeat_age_seconds gauge",
        ]
        for worker, row in liveness.items():
            labels = _labels(cluster=cluster, worker=worker)
            lines.append(f"dlcfn_heartbeat_age_seconds{labels} {row['age_s']}")
        lines += [
            "# HELP dlcfn_heartbeats_total Heartbeats observed from the worker.",
            "# TYPE dlcfn_heartbeats_total counter",
        ]
        for worker, row in liveness.items():
            labels = _labels(cluster=cluster, worker=worker)
            lines.append(f"dlcfn_heartbeats_total{labels} {row['beats']}")
    if spans:
        lines += [
            "# HELP dlcfn_span_count Completed spans by name.",
            "# TYPE dlcfn_span_count counter",
        ]
        for name, agg in spans.items():
            lines.append(f"dlcfn_span_count{_labels(span=name)} {agg['count']}")
        lines += [
            "# HELP dlcfn_span_seconds_total Total wall seconds spent in spans.",
            "# TYPE dlcfn_span_seconds_total counter",
        ]
        for name, agg in spans.items():
            lines.append(
                f"dlcfn_span_seconds_total{_labels(span=name)} {agg['total_s']}"
            )
        lines += [
            "# HELP dlcfn_span_seconds_max Longest single span by name.",
            "# TYPE dlcfn_span_seconds_max gauge",
        ]
        for name, agg in spans.items():
            lines.append(f"dlcfn_span_seconds_max{_labels(span=name)} {agg['max_s']}")
    return "\n".join(lines) + ("\n" if lines else "")
