"""Prometheus text exposition for liveness and span aggregates.

``dlcfn status --format prom`` renders through here; the output follows
the text format (``# HELP`` / ``# TYPE`` then ``name{labels} value``)
so a node-exporter textfile collector or a curl-into-pushgateway cron
can scrape it without a client library.

Every family this module may ever emit is declared once in
:data:`METRIC_REGISTRY` — name, type, help — and headers are rendered
exclusively from it through a per-render seen-set, so two folds that
touch the same family can never produce duplicate ``# TYPE`` lines (a
hard error in Prometheus ingesters).  The registry is also the
authority SLO rules are validated against (scripts/check.sh rejects a
rule referencing an unregistered name).
"""

from __future__ import annotations

from typing import Any, Mapping

#: family name -> (prometheus type, help text).  Summary families list
#: the base name only; their ``_sum`` / ``_count`` children inherit the
#: header per the text format.
METRIC_REGISTRY: dict[str, tuple[str, str]] = {
    # liveness
    "dlcfn_worker_up": ("gauge", "1 while the worker's heartbeat is not DEAD."),
    "dlcfn_heartbeat_age_seconds": ("gauge", "Seconds since the worker's last heartbeat."),
    "dlcfn_heartbeats_total": ("counter", "Heartbeats observed from the worker."),
    "dlcfn_worker_dead_fraction": ("gauge", "Fraction of tracked workers currently declared dead."),
    # spans
    "dlcfn_span_count": ("counter", "Completed spans by name."),
    "dlcfn_span_seconds_total": ("counter", "Total wall seconds spent in spans."),
    "dlcfn_span_seconds_max": ("gauge", "Longest single span by name."),
    "dlcfn_span_seconds": ("summary", "Span duration quantiles over the journal window."),
    # input pipeline
    "dlcfn_input_pipeline_bytes_transferred": ("gauge", "Host->device bytes moved by the input pipeline."),
    "dlcfn_input_pipeline_host_input_seconds": ("gauge", "Seconds producers spent in the source iterator."),
    "dlcfn_input_pipeline_producer_stall_seconds": ("gauge", "Seconds producers blocked on a full buffer."),
    "dlcfn_input_pipeline_consumer_wait_seconds": ("gauge", "Seconds the training loop waited for input."),
    "dlcfn_input_pipeline_overlap_fraction": ("gauge", "Fraction of the run with input hidden behind compute."),
    # elastic reshard
    "dlcfn_reshard_total": ("counter", "Live elastic reshards completed."),
    "dlcfn_reshard_seconds": ("gauge", "Total seconds spent pausing and resharding (injected clock)."),
    "dlcfn_reshard_fallback_total": ("counter", "Reshards that degraded to the checkpoint/restore path."),
    # mesh / contract
    "dlcfn_mesh_slices": ("gauge", "Slices in the current cluster contract."),
    "dlcfn_mesh_workers": ("gauge", "Worker hosts in the current cluster contract."),
    "dlcfn_mesh_chips_total": ("gauge", "Total chips across the current mesh."),
    # step profiler
    "dlcfn_step_phase_ms": ("summary", "Step-phase duration quantiles (rolling window)."),
    "dlcfn_step_ms": ("summary", "Whole-step duration quantiles (rolling window)."),
    # serving
    "dlcfn_serve_active_slots": ("gauge", "Decode slots currently occupied on the replica."),
    "dlcfn_serve_queue_depth": ("gauge", "Requests admitted but not yet slotted."),
    "dlcfn_serve_tokens_per_s": ("gauge", "Sampled tokens per second (replica lifetime)."),
    "dlcfn_serve_ttft_ms": ("summary", "Time-to-first-token quantiles (replica lifetime)."),
    # comms audit
    "dlcfn_comms_collective_bytes": ("gauge", "Bytes moved by collectives per execution of the audited program."),
    "dlcfn_comms_peak_hbm_bytes": ("gauge", "Peak-HBM estimate (args + outputs + temps - aliased) of the audited program."),
    "dlcfn_comms_collective_count": ("gauge", "Collective ops (all-gather/all-reduce/...) in the audited program's HLO."),
    "dlcfn_comms_overlap_score": ("gauge", "Mean compute slack per collective in the audited program's optimized schedule (DLC512 ratchet)."),
    "dlcfn_replay_cases": ("gauge", "Cases (chaos scenarios + fleet soaks) double-run by the last replay audit."),
    "dlcfn_replay_divergent": ("gauge", "Cases whose same-seed double runs produced different report bytes."),
    "dlcfn_replay_clean": ("gauge", "1 when the last replay audit was byte-identical everywhere, else 0."),
    # chaos gauntlet (chaos/gauntlet.py, docs/RESILIENCE.md)
    "dlcfn_gauntlet_runs_total": ("counter", "Composed-incident gauntlet runs journaled."),
    "dlcfn_gauntlet_passed": ("gauge", "1 when the last gauntlet run held every cross-subsystem invariant, else 0."),
    "dlcfn_gauntlet_faults_injected": ("gauge", "Fault events in the last gauntlet run's schedule."),
    "dlcfn_gauntlet_violations": ("gauge", "Invariant violations in the last gauntlet run."),
    "dlcfn_gauntlet_sweep_seeds": ("gauge", "Seeds explored by the last gauntlet incident sweep."),
    "dlcfn_gauntlet_sweep_failures": ("gauge", "Failing schedules found by the last gauntlet incident sweep."),
    "dlcfn_gauntlet_broker_degraded_pairs": ("gauge", "Broker shard pairs not fully healed during a gauntlet incident (drives the gauntlet SLO rule)."),
    # broker control plane
    "dlcfn_broker_role": ("gauge", "Broker role per node (1 = primary, 0 = standby)."),
    "dlcfn_broker_epoch": ("gauge", "Leadership term the node is fenced to."),
    "dlcfn_broker_up": ("gauge", "1 while the node answers on loopback."),
    "dlcfn_broker_replication_lag_seconds": ("gauge", "Age of the oldest journal entry the standby has not applied."),
    "dlcfn_broker_replication_lag_entries": ("gauge", "Journal entries the standby has not applied."),
    # sharded broker control plane (one pair per keyspace shard)
    "dlcfn_broker_shard_role": ("gauge", "Broker role per shard node (1 = primary, 0 = standby)."),
    "dlcfn_broker_shard_epoch": ("gauge", "Leadership term the shard node is fenced to."),
    "dlcfn_broker_shard_up": ("gauge", "1 while the shard node answers on loopback."),
    "dlcfn_broker_shard_replication_lag_seconds": ("gauge", "Age of the oldest journal entry the shard's standby has not applied."),
    "dlcfn_broker_shard_replication_lag_entries": ("gauge", "Journal entries the shard's standby has not applied."),
    # sharded streaming data plane (train/datastream, docs/DATA.md)
    "dlcfn_datastream_records_per_s": ("gauge", "Records/second the data plane delivered (plane lifetime)."),
    "dlcfn_datastream_records_total": ("counter", "Records the data plane delivered."),
    "dlcfn_datastream_shard_lag": ("gauge", "Spread (max-min) of records remaining across hosts — shard imbalance."),
    "dlcfn_datastream_reshard_total": ("counter", "Data-plane reshards (epoch work redistributed over survivors)."),
    "dlcfn_datastream_checkpoint_write_seconds": ("gauge", "Off-path seconds the background writer spent on the last sharded checkpoint."),
    "dlcfn_datastream_checkpoint_writes_total": ("counter", "Async sharded checkpoint manifests committed."),
    "dlcfn_datastream_native_fallback_total": ("counter", "Record-loader falls from native to the pure-Python reader."),
    # fleet scheduler (sched/arbiter.py, docs/SCHEDULER.md)
    "dlcfn_sched_jobs": ("gauge", "Jobs admitted to the fleet arbiter."),
    "dlcfn_sched_slices_free": ("gauge", "Slices in the inventory not assigned to any job."),
    "dlcfn_sched_loans_outstanding": ("gauge", "Slices currently lent from a preempted job to the serve pool."),
    "dlcfn_sched_decisions_total": ("counter", "Arbiter decisions journaled (submit/preempt/restore/absorb/defer)."),
    "dlcfn_sched_preemptions_total": ("counter", "Slices preempted from a lower-priority job under a serve page."),
    "dlcfn_sched_restores_total": ("counter", "Lent slices returned to their owning job after the page resolved."),
    # fleet telemetry (TELEM plane, obs/aggregator.py)
    "dlcfn_fleet_workers": ("gauge", "Workers with a fresh telemetry snapshot in the fleet merge."),
    "dlcfn_fleet_telemetry_age_seconds": ("gauge", "Age of each worker's newest telemetry snapshot."),
    "dlcfn_fleet_gauge": ("gauge", "Fleet-merged agent gauge (agg label: sum/max fleet-wide, last per worker)."),
    "dlcfn_fleet_summary": ("summary", "Fleet-merged sample summaries (quantiles over all hosts' samples)."),
}


def _escape(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _labels(**labels: str) -> str:
    body = ",".join(f'{k}="{_escape(str(v))}"' for k, v in labels.items() if v != "")
    return "{" + body + "}" if body else ""


def fold_reshard_events(events) -> dict[str, Any]:
    """Fold flight-journal ``reshard`` / ``reshard_fallback`` events into
    the counters the exporter and ``dlcfn status`` surface.  Empty dict
    when the journal holds neither kind."""
    out: dict[str, Any] = {
        "total": 0,
        "seconds_total": 0.0,
        "fallback_total": 0,
        "last": None,
    }
    for event in events:
        kind = event.get("kind")
        if kind == "reshard":
            out["total"] += 1
            out["seconds_total"] = round(
                out["seconds_total"] + float(event.get("seconds") or 0.0), 6
            )
            out["last"] = {
                k: event.get(k)
                for k in (
                    "step",
                    "old_topology",
                    "new_topology",
                    "grad_accum_before",
                    "grad_accum_after",
                )
            }
        elif kind == "reshard_fallback":
            out["fallback_total"] += 1
    if not out["total"] and not out["fallback_total"]:
        return {}
    return out


def fold_broker_events(events) -> dict[str, Any]:
    """Fold flight-journal broker lifecycle events into the counters
    ``dlcfn status`` surfaces: ``broker_promoted`` (a standby adopted a
    dead primary's record) and ``standby_reprovisioned`` (the promoted
    primary healed its pair with a fresh standby — the self-healing half
    of the failover ladder).  Empty dict when the journal holds neither
    kind."""
    out: dict[str, Any] = {"promotions": 0, "reprovisions": 0}
    last: dict[str, Any] | None = None
    for event in events:
        kind = event.get("kind")
        if kind not in ("broker_promoted", "standby_reprovisioned"):
            continue
        out["promotions" if kind == "broker_promoted" else "reprovisions"] += 1
        last = event
    if last is None:
        return {}
    out["last_event"] = {
        key: last[key]
        for key in ("kind", "ts", "cluster", "epoch", "replayed")
        if key in last
    }
    return out


def fold_serve_events(events) -> dict[str, Any]:
    """Fold flight-journal ``serve_metrics`` events into the latest
    snapshot per replica (each journal write is a full snapshot, so
    last-wins is the fold).  Empty dict when no replica ever reported."""
    out: dict[str, Any] = {}
    for event in events:
        if event.get("kind") != "serve_metrics":
            continue
        replica = str(event.get("replica") or "?")
        out[replica] = {
            k: event.get(k)
            for k in (
                "steps",
                "admitted",
                "completed",
                "rejected",
                "active_slots",
                "queue_depth",
                "tokens_out",
                "tokens_per_s",
                "ttft_ms",
                "itl_ms",
                "free_blocks",
                "recycled_blocks",
                "max_wait_steps",
                "kv_transfer_bytes",
                "disaggregated",
            )
        }
    return out


def fold_comms_events(events) -> dict[str, Any]:
    """Fold flight-journal ``comms_audit`` events into the latest budget
    per audited program (each audit journals a full per-program readout,
    so last-wins is the fold).  Empty dict when no audit ever ran."""
    out: dict[str, Any] = {}
    for event in events:
        if event.get("kind") != "comms_audit":
            continue
        for name, program in (event.get("programs") or {}).items():
            if not isinstance(program, Mapping):
                continue
            out[str(name)] = {
                k: program.get(k)
                for k in (
                    "collective_count",
                    "collective_bytes",
                    "peak_hbm_bytes",
                    "overlap_score",
                    "by_op",
                    "unpredicted_gathers",
                )
            }
    return out


def fold_replay_events(events) -> dict[str, Any]:
    """Fold flight-journal ``replay_audit`` events into the latest
    audit's verdict (each audit journals a full summary, so last-wins
    is the fold).  Empty dict when no replay audit ever ran."""
    out: dict[str, Any] = {}
    for event in events:
        if event.get("kind") != "replay_audit":
            continue
        out = {
            "clean": bool(event.get("clean")),
            "cases": int(event.get("cases") or 0),
            "seeds": list(event.get("seeds") or []),
            "divergent": sorted(event.get("divergent") or []),
        }
    return out


def fold_gauntlet_events(events) -> dict[str, Any]:
    """Fold flight-journal ``gauntlet`` events (composed-incident runs
    and incident-explorer sweeps from ``chaos/gauntlet.py``) into the
    counters ``dlcfn status`` and the ``dlcfn_gauntlet_*`` gauges
    surface.  Runs count; the newest run and the newest sweep summary
    win.  Empty dict when no gauntlet ever ran."""
    out: dict[str, Any] = {"runs_total": 0, "last_run": None, "sweep": None}
    saw = False
    for event in events:
        if event.get("kind") != "gauntlet":
            continue
        saw = True
        name = event.get("event")
        if name == "run":
            out["runs_total"] += 1
            out["last_run"] = {
                k: event.get(k)
                for k in ("seed", "passed", "faults", "violations")
            }
        elif name == "sweep":
            out["sweep"] = {
                k: event.get(k) for k in ("seeds", "base_seed", "failures")
            }
    return out if saw else {}


def fold_datastream_events(events) -> dict[str, Any]:
    """Fold flight-journal ``datastream`` events (data-plane progress,
    reshards, async-checkpoint writes, loader fallbacks) into the
    counters ``dlcfn status`` and the ``dlcfn_datastream_*`` gauges
    surface.  Progress events are full snapshots, so last-wins; the
    rest count.  Empty dict when the data plane never journaled."""
    out: dict[str, Any] = {
        "progress": None,
        "hosts": {},
        "reshard_total": 0,
        "last_reshard": None,
        "checkpoint": {
            "writes": 0,
            "failures": 0,
            "superseded": 0,
            "seconds_total": 0.0,
            "last_write_seconds": None,
            "last_step": None,
        },
        "native_fallback_total": 0,
    }
    saw = False
    for event in events:
        if event.get("kind") != "datastream":
            continue
        saw = True
        name = event.get("event")
        if name == "progress":
            out["progress"] = {
                k: event.get(k)
                for k in (
                    "hosts",
                    "shards",
                    "records_total",
                    "records_per_s",
                    "shard_lag",
                    "reshards",
                    "epoch",
                )
            }
        elif name == "host_progress":
            out["hosts"][str(event.get("host") or "?")] = {
                k: event.get(k) for k in ("records", "remaining", "epoch")
            }
        elif name == "reshard":
            out["reshard_total"] += 1
            out["last_reshard"] = {
                k: event.get(k)
                for k in (
                    "epoch",
                    "lost_hosts",
                    "survivors",
                    "work_units",
                    "records_remaining",
                )
            }
        elif name == "checkpoint_write":
            ck = out["checkpoint"]
            ck["writes"] += 1
            ck["seconds_total"] = round(
                ck["seconds_total"] + float(event.get("seconds") or 0.0), 6
            )
            ck["last_write_seconds"] = event.get("seconds")
            ck["last_step"] = event.get("step")
        elif name == "checkpoint_write_failed":
            out["checkpoint"]["failures"] += 1
        elif name == "checkpoint_superseded":
            out["checkpoint"]["superseded"] += 1
        elif name == "native_fallback":
            out["native_fallback_total"] += 1
    return out if saw else {}


def fold_sched_events(events) -> dict[str, Any]:
    """Fold flight-journal scheduler events (``sched_decision`` /
    ``sched_preempt`` / ``sched_restore``) into the counters the
    ``dlcfn_sched_*`` families surface.  Decisions carry the arbiter's
    fleet shape (jobs, free slices), preempts/restores carry the loan
    book — last-wins for the gauges, counting for the totals.  Empty
    dict when the arbiter never journaled."""
    out: dict[str, Any] = {
        "decisions": 0,
        "preemptions": 0,
        "restores": 0,
        "jobs": None,
        "free_slices": None,
        "loans_outstanding": None,
        "last": None,
    }
    saw = False
    for event in events:
        kind = event.get("kind")
        if kind == "sched_decision":
            saw = True
            out["decisions"] += 1
            out["jobs"] = event.get("jobs")
            out["free_slices"] = event.get("free_slices")
            out["loans_outstanding"] = event.get("loans_outstanding")
        elif kind in ("sched_preempt", "sched_restore"):
            saw = True
            out["preemptions" if kind == "sched_preempt" else "restores"] += 1
            out["loans_outstanding"] = event.get("loans_outstanding")
            out["last"] = {
                k: event.get(k)
                for k in ("kind", "seq", "rule", "slice", "from_job", "to_job")
            }
    return out if saw else {}


def render_prometheus(
    liveness: Mapping[str, Mapping[str, Any]] | None = None,
    spans: Mapping[str, Mapping[str, Any]] | None = None,
    cluster: str = "",
    pipeline: Mapping[str, Mapping[str, Any]] | None = None,
    reshard: Mapping[str, Any] | None = None,
    mesh: Mapping[str, Any] | None = None,
    profile: Mapping[str, Any] | None = None,
    serve: Mapping[str, Mapping[str, Any]] | None = None,
    broker: Mapping[str, Any] | None = None,
    comms: Mapping[str, Mapping[str, Any]] | None = None,
    fleet: Mapping[str, Any] | None = None,
    datastream: Mapping[str, Any] | None = None,
    sched: Mapping[str, Any] | None = None,
    replay: Mapping[str, Any] | None = None,
    gauntlet: Mapping[str, Any] | None = None,
) -> str:
    """Render liveness snapshot + span aggregates + input-pipeline
    counters as Prometheus text.

    ``liveness`` is ``LivenessTable.snapshot()``; ``spans`` is
    ``tracing.span_aggregates()`` (span dicts carrying ``p50_s`` /
    ``p95_s`` / ``p99_s`` additionally render as a summary-style
    ``dlcfn_span_seconds`` family); ``pipeline`` is
    ``train.pipeline.fold_pipeline_events()``; ``reshard`` is
    ``fold_reshard_events()``; ``mesh`` is the current mesh/contract
    shape from ``dlcfn status --cluster``; ``profile`` is the
    ``dlcfn status --profile`` dict (``{"profilers": {name: snapshot}}``)
    whose per-phase quantiles render as ``dlcfn_step_phase_ms``
    summaries; ``broker`` is
    ``broker_service.broker_replication_status()`` (role/epoch per node
    plus replication lag); ``comms`` is ``fold_comms_events()`` (the
    comms-audit sentinel's per-program collective/HBM budgets);
    ``fleet`` is ``obs.aggregator.FleetAggregator.merge()`` (the TELEM
    fleet merge); ``datastream`` is ``fold_datastream_events()`` (the
    sharded streaming data plane's progress/reshard/async-checkpoint
    counters); ``sched`` is ``fold_sched_events()`` (the fleet
    arbiter's decision/preemption/loan counters); ``replay`` is
    ``fold_replay_events()`` (the replay-audit sentinel's double-run
    byte-determinism verdict); ``gauntlet`` is
    ``fold_gauntlet_events()`` (the composed-incident gauntlet's
    run/sweep verdicts).  Any may be None/empty.
    """
    lines: list[str] = []
    seen: set[str] = set()

    def head(name: str) -> None:
        # One HELP/TYPE header per family per render, straight from the
        # registry — folds can interleave without ever duplicating one.
        if name in seen:
            return
        seen.add(name)
        mtype, help_text = METRIC_REGISTRY[name]
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")

    if liveness:
        head("dlcfn_worker_up")
        for worker, row in liveness.items():
            labels = _labels(cluster=cluster, worker=worker, state=row["state"])
            lines.append(
                f"dlcfn_worker_up{labels} {0 if row['state'] == 'dead' else 1}"
            )
        head("dlcfn_heartbeat_age_seconds")
        for worker, row in liveness.items():
            labels = _labels(cluster=cluster, worker=worker)
            lines.append(f"dlcfn_heartbeat_age_seconds{labels} {row['age_s']}")
        head("dlcfn_heartbeats_total")
        for worker, row in liveness.items():
            labels = _labels(cluster=cluster, worker=worker)
            lines.append(f"dlcfn_heartbeats_total{labels} {row['beats']}")
    if spans:
        head("dlcfn_span_count")
        for name, agg in spans.items():
            lines.append(f"dlcfn_span_count{_labels(span=name)} {agg['count']}")
        head("dlcfn_span_seconds_total")
        for name, agg in spans.items():
            lines.append(
                f"dlcfn_span_seconds_total{_labels(span=name)} {agg['total_s']}"
            )
        head("dlcfn_span_seconds_max")
        for name, agg in spans.items():
            lines.append(f"dlcfn_span_seconds_max{_labels(span=name)} {agg['max_s']}")
        quantiled = {
            name: agg for name, agg in spans.items() if "p50_s" in agg
        }
        if quantiled:
            head("dlcfn_span_seconds")
            for name, agg in quantiled.items():
                for quantile, key in (
                    ("0.5", "p50_s"),
                    ("0.95", "p95_s"),
                    ("0.99", "p99_s"),
                ):
                    value = agg.get(key)
                    if value is None:
                        continue
                    lines.append(
                        f"dlcfn_span_seconds"
                        f"{_labels(span=name, quantile=quantile)} {value}"
                    )
                lines.append(
                    f"dlcfn_span_seconds_sum{_labels(span=name)} {agg['total_s']}"
                )
                lines.append(
                    f"dlcfn_span_seconds_count{_labels(span=name)} {agg['count']}"
                )
    if pipeline:
        for key in (
            "bytes_transferred",
            "host_input_seconds",
            "producer_stall_seconds",
            "consumer_wait_seconds",
            "overlap_fraction",
        ):
            head(f"dlcfn_input_pipeline_{key}")
            for name, agg in pipeline.items():
                value = agg.get(key)
                if value is None:
                    continue
                lines.append(
                    f"dlcfn_input_pipeline_{key}"
                    f"{_labels(cluster=cluster, pipeline=name)} {value}"
                )
    if reshard:
        for name, key in (
            ("dlcfn_reshard_total", "total"),
            ("dlcfn_reshard_seconds", "seconds_total"),
            ("dlcfn_reshard_fallback_total", "fallback_total"),
        ):
            head(name)
            lines.append(f"{name}{_labels(cluster=cluster)} {reshard.get(key, 0)}")
    if mesh:
        for key in ("slices", "workers", "chips_total"):
            value = mesh.get(key)
            if value is None:
                continue
            head(f"dlcfn_mesh_{key}")
            lines.append(f"dlcfn_mesh_{key}{_labels(cluster=cluster)} {value}")
    profilers = (profile or {}).get("profilers") or {}
    if profilers:
        head("dlcfn_step_phase_ms")
        for prof_name, snap in profilers.items():
            for phase, stats in (snap.get("phases") or {}).items():
                for quantile, key in (
                    ("0.5", "p50_ms"),
                    ("0.95", "p95_ms"),
                    ("0.99", "p99_ms"),
                ):
                    value = stats.get(key)
                    if value is None:
                        continue
                    lines.append(
                        f"dlcfn_step_phase_ms"
                        f"{_labels(cluster=cluster, profiler=prof_name, phase=phase, quantile=quantile)}"
                        f" {value}"
                    )
                lines.append(
                    f"dlcfn_step_phase_ms_sum"
                    f"{_labels(cluster=cluster, profiler=prof_name, phase=phase)}"
                    f" {stats.get('total_ms', 0.0)}"
                )
                lines.append(
                    f"dlcfn_step_phase_ms_count"
                    f"{_labels(cluster=cluster, profiler=prof_name, phase=phase)}"
                    f" {stats.get('count', 0)}"
                )
        head("dlcfn_step_ms")
        for prof_name, snap in profilers.items():
            step_ms = snap.get("step_ms") or {}
            for quantile, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                value = step_ms.get(key)
                if value is None:
                    continue
                lines.append(
                    f"dlcfn_step_ms"
                    f"{_labels(cluster=cluster, profiler=prof_name, quantile=quantile)}"
                    f" {value}"
                )
            lines.append(
                f"dlcfn_step_ms_count"
                f"{_labels(cluster=cluster, profiler=prof_name)}"
                f" {snap.get('steps', 0)}"
            )
    if serve:
        for key in ("active_slots", "queue_depth", "tokens_per_s"):
            head(f"dlcfn_serve_{key}")
            for replica, snap in serve.items():
                value = snap.get(key)
                if value is None:
                    continue
                lines.append(
                    f"dlcfn_serve_{key}"
                    f"{_labels(cluster=cluster, replica=replica)} {value}"
                )
        head("dlcfn_serve_ttft_ms")
        for replica, snap in serve.items():
            ttft = snap.get("ttft_ms") or {}
            for quantile, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                value = ttft.get(key)
                if value is None:
                    continue
                lines.append(
                    f"dlcfn_serve_ttft_ms"
                    f"{_labels(cluster=cluster, replica=replica, quantile=quantile)}"
                    f" {value}"
                )
            lines.append(
                f"dlcfn_serve_ttft_ms_count"
                f"{_labels(cluster=cluster, replica=replica)}"
                f" {snap.get('admitted', 0)}"
            )
    if comms:
        for key in (
            "collective_bytes",
            "peak_hbm_bytes",
            "collective_count",
            "overlap_score",
        ):
            head(f"dlcfn_comms_{key}")
            for program, snap in comms.items():
                value = snap.get(key)
                if value is None:
                    continue
                lines.append(
                    f"dlcfn_comms_{key}"
                    f"{_labels(cluster=cluster, program=program)} {value}"
                )
    if replay:
        head("dlcfn_replay_cases")
        lines.append(
            f"dlcfn_replay_cases{_labels(cluster=cluster)} "
            f"{replay.get('cases', 0)}"
        )
        head("dlcfn_replay_divergent")
        lines.append(
            f"dlcfn_replay_divergent{_labels(cluster=cluster)} "
            f"{len(replay.get('divergent') or [])}"
        )
        head("dlcfn_replay_clean")
        lines.append(
            f"dlcfn_replay_clean{_labels(cluster=cluster)} "
            f"{1 if replay.get('clean') else 0}"
        )
    if gauntlet:
        head("dlcfn_gauntlet_runs_total")
        lines.append(
            f"dlcfn_gauntlet_runs_total{_labels(cluster=cluster)} "
            f"{gauntlet.get('runs_total', 0)}"
        )
        last_run = gauntlet.get("last_run")
        if last_run:
            labels = _labels(cluster=cluster, seed=last_run.get("seed"))
            head("dlcfn_gauntlet_passed")
            lines.append(
                f"dlcfn_gauntlet_passed{labels} "
                f"{1 if last_run.get('passed') else 0}"
            )
            head("dlcfn_gauntlet_faults_injected")
            lines.append(
                f"dlcfn_gauntlet_faults_injected{labels} "
                f"{last_run.get('faults') or 0}"
            )
            head("dlcfn_gauntlet_violations")
            lines.append(
                f"dlcfn_gauntlet_violations{labels} "
                f"{last_run.get('violations') or 0}"
            )
        sweep = gauntlet.get("sweep")
        if sweep:
            head("dlcfn_gauntlet_sweep_seeds")
            lines.append(
                f"dlcfn_gauntlet_sweep_seeds{_labels(cluster=cluster)} "
                f"{sweep.get('seeds') or 0}"
            )
            head("dlcfn_gauntlet_sweep_failures")
            lines.append(
                f"dlcfn_gauntlet_sweep_failures{_labels(cluster=cluster)} "
                f"{sweep.get('failures') or 0}"
            )
    if broker:
        for name in ("dlcfn_broker_role", "dlcfn_broker_epoch", "dlcfn_broker_up"):
            head(name)
        for node_name in ("primary", "standby"):
            node = broker.get(node_name)
            if not node:
                continue
            labels = _labels(
                cluster=cluster,
                node=node_name,
                endpoint=f"{node.get('host')}:{node.get('port')}",
            )
            role = node.get("role")
            lines.append(
                f"dlcfn_broker_role{labels} {1 if role == 'primary' else 0}"
            )
            lines.append(f"dlcfn_broker_epoch{labels} {node.get('epoch') or 0}")
            lines.append(f"dlcfn_broker_up{labels} {1 if node.get('alive') else 0}")
        lag_s = broker.get("lag_seconds")
        if lag_s is not None:
            head("dlcfn_broker_replication_lag_seconds")
            lines.append(
                f"dlcfn_broker_replication_lag_seconds{_labels(cluster=cluster)} {lag_s}"
            )
        lag_entries = broker.get("lag_entries")
        if lag_entries is not None:
            head("dlcfn_broker_replication_lag_entries")
            lines.append(
                f"dlcfn_broker_replication_lag_entries{_labels(cluster=cluster)} {lag_entries}"
            )
        shards = broker.get("shards")
        if shards:
            for name in (
                "dlcfn_broker_shard_role",
                "dlcfn_broker_shard_epoch",
                "dlcfn_broker_shard_up",
            ):
                head(name)
            for entry in shards:
                shard = entry.get("shard")
                status = entry.get("status") or {}
                for node_name in ("primary", "standby"):
                    node = status.get(node_name)
                    if not node:
                        continue
                    labels = _labels(
                        cluster=cluster,
                        shard=shard,
                        node=node_name,
                        endpoint=f"{node.get('host')}:{node.get('port')}",
                    )
                    role = node.get("role")
                    lines.append(
                        f"dlcfn_broker_shard_role{labels}"
                        f" {1 if role == 'primary' else 0}"
                    )
                    lines.append(
                        f"dlcfn_broker_shard_epoch{labels} {node.get('epoch') or 0}"
                    )
                    lines.append(
                        f"dlcfn_broker_shard_up{labels}"
                        f" {1 if node.get('alive') else 0}"
                    )
            for key in ("lag_seconds", "lag_entries"):
                rows = [
                    (e.get("shard"), (e.get("status") or {}).get(key))
                    for e in shards
                ]
                rows = [(s, v) for s, v in rows if v is not None]
                if not rows:
                    continue
                head(f"dlcfn_broker_shard_replication_{key}")
                for shard, value in rows:
                    lines.append(
                        f"dlcfn_broker_shard_replication_{key}"
                        f"{_labels(cluster=cluster, shard=shard)} {value}"
                    )
    if fleet:
        head("dlcfn_fleet_workers")
        lines.append(
            f"dlcfn_fleet_workers{_labels(cluster=cluster)} {fleet.get('hosts', 0)}"
        )
        workers = fleet.get("workers") or {}
        if workers:
            head("dlcfn_fleet_telemetry_age_seconds")
            for worker, row in workers.items():
                lines.append(
                    f"dlcfn_fleet_telemetry_age_seconds"
                    f"{_labels(cluster=cluster, worker=worker)} {row.get('age_s', 0)}"
                )
        gauges = fleet.get("gauges") or {}
        if gauges:
            head("dlcfn_fleet_gauge")
            for metric, slot in gauges.items():
                for agg in ("sum", "max"):
                    value = slot.get(agg)
                    if value is None:
                        continue
                    lines.append(
                        f"dlcfn_fleet_gauge"
                        f"{_labels(cluster=cluster, metric=metric, agg=agg)} {value}"
                    )
                for worker, value in (slot.get("last") or {}).items():
                    lines.append(
                        f"dlcfn_fleet_gauge"
                        f"{_labels(cluster=cluster, metric=metric, worker=worker, agg='last')}"
                        f" {value}"
                    )
        summaries = fleet.get("summaries") or {}
        if summaries:
            head("dlcfn_fleet_summary")
            for metric, slot in summaries.items():
                for quantile, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                    value = slot.get(key)
                    if value is None:
                        continue
                    lines.append(
                        f"dlcfn_fleet_summary"
                        f"{_labels(cluster=cluster, metric=metric, quantile=quantile)}"
                        f" {value}"
                    )
                lines.append(
                    f"dlcfn_fleet_summary_sum"
                    f"{_labels(cluster=cluster, metric=metric)} {slot.get('sum', 0.0)}"
                )
                lines.append(
                    f"dlcfn_fleet_summary_count"
                    f"{_labels(cluster=cluster, metric=metric)} {slot.get('count', 0)}"
                )
        dead_fraction = fleet.get("dead_fraction")
        if dead_fraction is not None:
            head("dlcfn_worker_dead_fraction")
            lines.append(
                f"dlcfn_worker_dead_fraction{_labels(cluster=cluster)} {dead_fraction}"
            )
    if datastream:
        progress = datastream.get("progress") or {}
        for name, key in (
            ("dlcfn_datastream_records_per_s", "records_per_s"),
            ("dlcfn_datastream_records_total", "records_total"),
            ("dlcfn_datastream_shard_lag", "shard_lag"),
        ):
            value = progress.get(key)
            if value is None:
                continue
            head(name)
            lines.append(f"{name}{_labels(cluster=cluster)} {value}")
        head("dlcfn_datastream_reshard_total")
        lines.append(
            f"dlcfn_datastream_reshard_total{_labels(cluster=cluster)}"
            f" {datastream.get('reshard_total', 0)}"
        )
        checkpoint = datastream.get("checkpoint") or {}
        if checkpoint.get("last_write_seconds") is not None:
            head("dlcfn_datastream_checkpoint_write_seconds")
            lines.append(
                f"dlcfn_datastream_checkpoint_write_seconds"
                f"{_labels(cluster=cluster)} {checkpoint['last_write_seconds']}"
            )
        if checkpoint.get("writes"):
            head("dlcfn_datastream_checkpoint_writes_total")
            lines.append(
                f"dlcfn_datastream_checkpoint_writes_total"
                f"{_labels(cluster=cluster)} {checkpoint['writes']}"
            )
        if datastream.get("native_fallback_total"):
            head("dlcfn_datastream_native_fallback_total")
            lines.append(
                f"dlcfn_datastream_native_fallback_total"
                f"{_labels(cluster=cluster)} {datastream['native_fallback_total']}"
            )
    if sched:
        for name, key in (
            ("dlcfn_sched_jobs", "jobs"),
            ("dlcfn_sched_slices_free", "free_slices"),
            ("dlcfn_sched_loans_outstanding", "loans_outstanding"),
        ):
            value = sched.get(key)
            if value is None:
                continue
            head(name)
            lines.append(f"{name}{_labels(cluster=cluster)} {value}")
        for name, key in (
            ("dlcfn_sched_decisions_total", "decisions"),
            ("dlcfn_sched_preemptions_total", "preemptions"),
            ("dlcfn_sched_restores_total", "restores"),
        ):
            head(name)
            lines.append(f"{name}{_labels(cluster=cluster)} {sched.get(key, 0)}")
    return "\n".join(lines) + ("\n" if lines else "")
