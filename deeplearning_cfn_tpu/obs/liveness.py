"""Worker liveness state machine: ALIVE → SUSPECT → DEAD on silence.

The broker only stores last-heartbeat timestamps (native/broker/
broker.cpp keeps C++ dumb on purpose); the *interpretation* — how much
silence means suspect, how much means dead — lives here, Python-side,
where it is configurable and testable with an injected clock.

Transitions are monotone while a worker stays silent (a DEAD worker
that beats again is resurrected to ALIVE — brokers survive partitions),
and every transition is journaled plus handed to ``on_transition`` so
the broker service can publish ``INSTANCE_TERMINATE`` for DEAD workers
(cluster/broker_service.py BrokerLivenessWatcher).
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from deeplearning_cfn_tpu.obs.recorder import FlightRecorder, get_recorder


class WorkerState(str, enum.Enum):
    ALIVE = "alive"
    SUSPECT = "suspect"
    DEAD = "dead"


@dataclass(frozen=True)
class LivenessConfig:
    """Silence thresholds, in seconds of heartbeat age."""

    suspect_after_s: float = 15.0
    dead_after_s: float = 60.0

    def __post_init__(self) -> None:
        if not 0 < self.suspect_after_s <= self.dead_after_s:
            raise ValueError(
                "need 0 < suspect_after_s <= dead_after_s, got "
                f"{self.suspect_after_s} / {self.dead_after_s}"
            )

    def classify(self, age_s: float) -> WorkerState:
        if age_s >= self.dead_after_s:
            return WorkerState.DEAD
        if age_s >= self.suspect_after_s:
            return WorkerState.SUSPECT
        return WorkerState.ALIVE


@dataclass
class _Worker:
    last_beat: float
    beats: int = 0
    state: WorkerState = WorkerState.ALIVE


Transition = tuple[str, WorkerState, WorkerState]


@dataclass
class LivenessTable:
    """Tracks heartbeat recency per worker and classifies silence.

    ``clock`` is injectable (monotonic by default) so tests drive time
    explicitly instead of sleeping.
    """

    config: LivenessConfig = field(default_factory=LivenessConfig)
    clock: Callable[[], float] = time.monotonic
    on_transition: Callable[[Transition], None] | None = None
    recorder: FlightRecorder | None = None
    _workers: dict[str, _Worker] = field(default_factory=dict)

    def beat(self, worker_id: str, count: int | None = None) -> None:
        """Record a fresh heartbeat (direct observation, age zero)."""
        self.observe(worker_id, age_s=0.0, count=count)

    def observe(self, worker_id: str, age_s: float, count: int | None = None) -> None:
        """Record that ``worker_id``'s last beat was ``age_s`` seconds ago.

        This is the broker-poll path: the broker reports ages, not
        events, so the table back-dates last_beat accordingly.
        """
        now = self.clock()
        worker = self._workers.get(worker_id)
        if worker is None:
            worker = self._workers[worker_id] = _Worker(last_beat=now - age_s)
        else:
            worker.last_beat = max(worker.last_beat, now - age_s)
        if count is not None:
            worker.beats = max(worker.beats, count)
        else:
            worker.beats += 1

    def expect(self, worker_id: str) -> None:
        """Register a worker that *should* beat, starting its clock now.

        A worker that never sends a single heartbeat still marches
        through SUSPECT to DEAD from registration time.
        """
        if worker_id not in self._workers:
            self._workers[worker_id] = _Worker(last_beat=self.clock())

    def sweep(self) -> list[Transition]:
        """Re-classify every worker; returns (and journals) transitions."""
        now = self.clock()
        transitions: list[Transition] = []
        for worker_id, worker in self._workers.items():
            new = self.config.classify(now - worker.last_beat)
            if new is worker.state:
                continue
            transition = (worker_id, worker.state, new)
            worker.state = new
            transitions.append(transition)
            (self.recorder or get_recorder()).record(
                "liveness",
                worker=worker_id,
                from_state=transition[1].value,
                to_state=new.value,
                age_s=round(now - worker.last_beat, 3),
            )
            if self.on_transition is not None:
                self.on_transition(transition)
        return transitions

    def state(self, worker_id: str) -> WorkerState | None:
        worker = self._workers.get(worker_id)
        return worker.state if worker else None

    def snapshot(self) -> dict[str, dict[str, Any]]:
        """Per-worker view for ``dlcfn status`` and the exporter."""
        now = self.clock()
        return {
            worker_id: {
                "state": worker.state.value,
                "age_s": round(max(0.0, now - worker.last_beat), 3),
                "beats": worker.beats,
            }
            for worker_id, worker in sorted(self._workers.items())
        }
