"""Flight recorder: a bounded ring journal of structured cluster events.

Every control-plane actor (broker service, elasticity controller,
recovery manager, provisioner event bus) and the trainer's span
instrumentation write into one :class:`FlightRecorder`.  Events live in
a fixed-size in-memory ring (cheap enough for the train-step hot path)
and, when a journal path is configured, are appended as strict JSONL —
one ``json.dumps(..., allow_nan=False)`` object per line, every value
routed through ``train.metrics.json_safe`` so device arrays and numpy
scalars degrade to plain Python before serialization.

The journal file is itself bounded: after ``max_file_lines`` appends the
file rotates to ``<path>.1`` (overwriting the previous rotation), so a
long-running agent holds at most two generations on disk.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import weakref
from collections import deque
from pathlib import Path
from typing import Any, Callable, Iterator

ENV_JOURNAL = "DLCFN_FLIGHT_JOURNAL"

_json_safe: Callable[[Any], Any] | None = None


def _safe(obj: Any) -> Any:
    """train.metrics.json_safe, imported lazily (it pulls in jax).

    Journal events are almost always flat dicts of plain scalars, and
    ``record()`` sits on the heartbeat/liveness hot path (a 1k-agent
    soak journals tens of thousands of events), so flat plain-scalar
    fields bypass the recursive sanitizer.  The type checks are exact:
    numpy/jax scalars (``np.float64`` subclasses ``float``) and every
    container still take the full ``json_safe`` walk.
    """
    global _json_safe
    if _json_safe is None:
        from deeplearning_cfn_tpu.train.metrics import json_safe

        _json_safe = json_safe
    if type(obj) is dict:
        out = {}
        for key, value in obj.items():
            t = type(value)
            if t is str or t is bool or t is int or value is None:
                out[key] = value
            elif t is float:
                out[key] = (
                    value
                    if value == value and value not in (float("inf"), float("-inf"))
                    else None
                )
            else:
                out[key] = _json_safe(value)
        return out
    return _json_safe(obj)


def _identity() -> dict[str, Any]:
    ident: dict[str, Any] = {"host": socket.gethostname(), "pid": os.getpid()}
    cluster = os.environ.get("DLCFN_CLUSTER")
    if cluster:
        ident["cluster"] = cluster
    worker = os.environ.get("DLCFN_WORKER")
    if worker:
        ident["worker"] = worker
    return ident


class FlightRecorder:
    """Bounded ring of structured events, optionally mirrored to JSONL."""

    def __init__(
        self,
        path: str | Path | None = None,
        max_events: int = 4096,
        max_file_lines: int = 100_000,
    ):
        self._events: deque[dict[str, Any]] = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._path = Path(path) if path else None
        self._fh = None
        self._file_lines = 0
        self._max_file_lines = max(1, max_file_lines)
        self._identity = _identity()
        self._attached_buses: "weakref.WeakSet" = weakref.WeakSet()
        if self._path is not None:
            self._path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self._path, "a", encoding="utf-8")

    @property
    def path(self) -> Path | None:
        return self._path

    def record(self, kind: str, **fields: Any) -> dict[str, Any]:
        """Append one event; returns the (json-safe) event dict."""
        event: dict[str, Any] = {"ts": round(time.time(), 6), "kind": kind}
        event.update(self._identity)
        event.update(fields)
        event = _safe(event)
        with self._lock:
            self._events.append(event)
            if self._fh is not None:
                # default=str: a journal must never crash its host process
                # over an exotic detail payload — stringify, stay strict JSON.
                line = json.dumps(event, allow_nan=False, default=str)
                self._fh.write(line + "\n")
                self._fh.flush()
                self._file_lines += 1
                if self._file_lines >= self._max_file_lines:
                    self._rotate_locked()
        return event

    def _rotate_locked(self) -> None:
        assert self._fh is not None and self._path is not None
        self._fh.close()
        os.replace(self._path, self._path.with_suffix(self._path.suffix + ".1"))
        self._fh = open(self._path, "a", encoding="utf-8")
        self._file_lines = 0

    def tail(self, n: int = 100) -> list[dict[str, Any]]:
        with self._lock:
            events = list(self._events)
        return events[-n:]

    def attach_event_bus(self, bus) -> None:
        """Mirror every provisioner lifecycle event into the journal.

        Idempotent per bus: a backend shared by several provisioner
        generations must not journal each event once per generation.
        """
        with self._lock:
            if bus in self._attached_buses:
                return
            self._attached_buses.add(bus)

        def _on_event(event) -> None:
            self.record(
                "lifecycle",
                event=getattr(event.kind, "value", str(event.kind)),
                group=event.group,
                instance_id=event.instance_id,
                detail=dict(event.detail),
            )

        bus.subscribe(_on_event)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


_default: FlightRecorder | None = None
_default_lock = threading.Lock()


def configure(
    path: str | Path | None = None, max_events: int = 4096
) -> FlightRecorder:
    """Install the process-wide default recorder (closing any previous)."""
    global _default
    with _default_lock:
        if _default is not None:
            _default.close()
        _default = FlightRecorder(path=path, max_events=max_events)
        return _default


def get_recorder() -> FlightRecorder:
    """The process-wide recorder; created on first use.

    Journals to ``$DLCFN_FLIGHT_JOURNAL`` when set, else in-memory only.
    """
    global _default
    with _default_lock:
        if _default is None:
            _default = FlightRecorder(path=os.environ.get(ENV_JOURNAL) or None)
        return _default


def read_journal(
    path: str | Path, limit: int | None = None, kind: str | None = None
) -> Iterator[dict[str, Any]]:
    """Parse a JSONL flight journal back into event dicts.

    Reads ``<path>.1`` (the rotation) first when present, so the caller
    sees one chronological stream.  A torn final line (writer died
    mid-append) is skipped rather than raised.
    """
    path = Path(path)
    events: list[dict[str, Any]] = []
    rotated = path.with_suffix(path.suffix + ".1")
    for part in (rotated, path):
        if not part.exists():
            continue
        with open(part, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if kind is not None and event.get("kind") != kind:
                    continue
                events.append(event)
    if limit is not None:
        events = events[-limit:]
    return iter(events)


def _parse_journal_lines(
    lines: list[str], kind: str | None
) -> Iterator[dict[str, Any]]:
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
        except json.JSONDecodeError:
            continue
        if kind is not None and event.get("kind") != kind:
            continue
        yield event


def follow_journal(
    path: str | Path,
    kind: str | None = None,
    poll_s: float = 0.5,
    sleep: Callable[[float], None] = time.sleep,
    stop: Callable[[], bool] | None = None,
) -> Iterator[dict[str, Any]]:
    """Yield journal events as they are appended — ``tail -F`` semantics.

    Emits the rotation (``<path>.1``) and any existing live-file content
    first, then polls for new bytes.  A torn trailing line stays
    buffered until its newline arrives.  Rotation is detected by inode:
    ``FlightRecorder`` rotates with ``os.replace``, which leaves the old
    file readable through the open handle — the handle is drained to EOF
    before reopening the new live file, so no event is skipped across a
    rotation.  Waits for ``path`` to appear if it does not exist yet.

    ``sleep`` and ``stop`` are injectable so tests drive the poll loop
    deterministically without wall-clock waits; ``stop`` is checked once
    per poll after a full drain, so everything written before it flips
    is still yielded.  No deadline arithmetic — the loop is purely
    poll-driven.
    """
    path = Path(path)
    rotated = path.with_suffix(path.suffix + ".1")
    if rotated.exists():
        with open(rotated, encoding="utf-8") as fh:
            yield from _parse_journal_lines(fh.read().split("\n"), kind)
    fh = None
    buf = ""
    try:
        while True:
            if fh is None and path.exists():
                fh = open(path, encoding="utf-8")
                buf = ""
            if fh is not None:
                chunk = fh.read()
                if chunk:
                    buf += chunk
                    *complete, buf = buf.split("\n")
                    yield from _parse_journal_lines(complete, kind)
                else:
                    # At EOF: if the live path now names a different file
                    # (rotation happened), this handle is fully drained —
                    # switch to the new file without sleeping.
                    try:
                        live_ino = os.stat(path).st_ino
                    except FileNotFoundError:
                        live_ino = None
                    if live_ino != os.fstat(fh.fileno()).st_ino:
                        fh.close()
                        fh = None
                        continue
            if stop is not None and stop():
                return
            sleep(poll_s)
    finally:
        if fh is not None:
            fh.close()
