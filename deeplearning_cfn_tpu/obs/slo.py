"""Declarative SLO rules and the firing/resolved alert engine.

Rules are data, not code: ``SloRule(metric, agg, op, threshold, for_s)``
— the CloudWatch-alarm analog the reference wired per-ASG by hand
(deeplearning.template alarm blocks), expressed once over the FLEET
aggregate instead of per instance.  The engine is a pure state machine
over an injected clock:

* a breach starts a **pending** window; the rule must stay breached for
  ``for_s`` seconds before it **fires** (debounces the one-slow-step
  blip that would otherwise page at 3am);
* each transition is journaled as kind ``"alert"`` through the flight
  recorder and published on the cluster EventBus as
  ``EventKind.ALERT``, so postmortem timelines (obs/blackbox.py) and
  the elasticity controller both see it;
* recovery emits exactly one ``resolved`` — re-breaching restarts the
  pending window from zero, so a flapping metric produces
  fire/resolve pairs, never duplicate fires.

Missing or NaN values are *absence of evidence*: they clear the pending
window, never fire, and never resolve — a firing alert HOLDS through a
telemetry blackout (a broker failover blanks the fleet table for a
round; resolving on that would flap).  "No data" alarms are a separate
liveness problem, owned by the dead-fraction rule whose input the
liveness state machine always produces.

Evaluation is deterministic: rules evaluate in declaration order over a
plain values dict (``obs.aggregator.fleet_metric_values``), the clock
is injected, and transitions depend only on (values, now) — the
alert-storm chaos scenario replays byte-identically per seed.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

from deeplearning_cfn_tpu.obs.recorder import get_recorder
from deeplearning_cfn_tpu.utils.logging import get_logger

log = get_logger("dlcfn.obs")

#: Aggregations a rule may reference.  "value" reads synthesized fleet
#: metrics (dead fraction, worker count); the rest select a fold from
#: the aggregate (see obs.aggregator.fleet_metric_values).
AGGS = ("value", "sum", "max", "p50", "p95", "p99", "count")
OPS: dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}
SEVERITIES = ("page", "warn", "info")


@dataclass(frozen=True)
class SloRule:
    """One alert rule: ``<metric>.<agg> <op> <threshold> for <for_s>s``."""

    name: str
    metric: str
    agg: str
    op: str
    threshold: float
    for_s: float
    severity: str = "warn"
    description: str = ""

    def validate(self) -> list[str]:
        """Schema errors, empty when shippable — the list check.sh's
        SLO-schema stage prints verbatim."""
        errors = []
        if not self.name:
            errors.append("rule has no name")
        if not self.metric.startswith("dlcfn_"):
            errors.append(
                f"{self.name}: metric {self.metric!r} is not a dlcfn_* family"
            )
        if self.agg not in AGGS:
            errors.append(f"{self.name}: unknown agg {self.agg!r} (want {AGGS})")
        if self.op not in OPS:
            errors.append(f"{self.name}: unknown op {self.op!r}")
        if not math.isfinite(self.threshold):
            errors.append(f"{self.name}: non-finite threshold {self.threshold!r}")
        if not math.isfinite(self.for_s) or self.for_s < 0:
            errors.append(f"{self.name}: for_s must be finite and >= 0")
        if self.severity not in SEVERITIES:
            errors.append(
                f"{self.name}: unknown severity {self.severity!r} (want {SEVERITIES})"
            )
        return errors

    def breached(self, values: Mapping[str, Mapping[str, float]]) -> tuple[bool, float | None]:
        """(is_breached, observed_value) against a fleet values dict."""
        observed = (values.get(self.metric) or {}).get(self.agg)
        if observed is None or not math.isfinite(observed):
            return False, None
        return OPS[self.op](observed, self.threshold), observed


#: Shipped rules, referencing registered exporter families only (the
#: check.sh SLO-schema stage enforces this against METRIC_REGISTRY).
#: Thresholds are the conservative defaults docs/OBSERVABILITY.md
#: documents; deployments tune for_s/threshold, not the mechanism.
DEFAULT_RULES: tuple[SloRule, ...] = (
    SloRule(
        name="worker-dead-fraction",
        metric="dlcfn_worker_dead_fraction",
        agg="value",
        op=">=",
        threshold=0.10,
        for_s=30.0,
        severity="page",
        description=">=10% of the fleet missed enough heartbeats to be "
        "declared dead for 30s — correlated failure, not one flaky host.",
    ),
    SloRule(
        name="step-time-p99-straggler",
        metric="dlcfn_step_ms",
        agg="p99",
        op=">",
        threshold=1500.0,
        for_s=60.0,
        severity="warn",
        description="fleet-wide step-time p99 above 1.5s for a minute — "
        "a straggler host is gating every synchronous collective.",
    ),
    SloRule(
        name="serve-ttft-p99",
        metric="dlcfn_serve_ttft_ms",
        agg="p99",
        op=">",
        threshold=2000.0,
        for_s=60.0,
        severity="page",
        description="serving time-to-first-token p99 over 2s sustained — "
        "user-visible latency SLO breach.",
    ),
    SloRule(
        name="serve-queue-depth",
        metric="dlcfn_serve_queue_depth",
        agg="sum",
        op=">",
        threshold=256.0,
        for_s=30.0,
        severity="warn",
        description="admission queue backing up across the serve fleet — "
        "add replicas before TTFT follows.",
    ),
    SloRule(
        name="broker-replication-lag",
        metric="dlcfn_broker_replication_lag_entries",
        agg="max",
        op=">",
        threshold=1000.0,
        for_s=30.0,
        severity="page",
        description="warm standby more than 1000 journal entries behind — "
        "a failover now would lose that tail.",
    ),
)


@dataclass
class _RuleState:
    pending_since: float | None = None
    firing: bool = False
    fired_count: int = 0
    resolved_count: int = 0
    last_value: float | None = None


class SloEngine:
    """Evaluates rules over successive fleet-value snapshots.

    ``clock`` is injected (VirtualClock in chaos, time.monotonic in
    prod); ``bus`` / ``recorder`` are optional sinks — the engine works
    headless for unit tests and wires both in the control plane.
    """

    def __init__(
        self,
        rules: tuple[SloRule, ...] | list[SloRule] = DEFAULT_RULES,
        clock: Callable[[], float] | None = None,
        bus: Any = None,
        recorder: Any = None,
        group: str = "fleet",
    ):
        errors = [e for rule in rules for e in rule.validate()]
        if errors:
            raise ValueError("invalid SLO rules: " + "; ".join(errors))
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO rule names: {names}")
        self.rules = tuple(rules)
        self._clock = clock if clock is not None else _monotonic
        self._bus = bus
        self._recorder = recorder
        self._group = group
        self._state: dict[str, _RuleState] = {r.name: _RuleState() for r in self.rules}

    def evaluate(self, values: Mapping[str, Mapping[str, float]]) -> list[dict[str, Any]]:
        """One evaluation tick; returns the transitions it emitted
        (``{"rule", "state", "value", ...}``), empty when quiet."""
        now = self._clock()
        transitions: list[dict[str, Any]] = []
        for rule in self.rules:
            state = self._state[rule.name]
            breached, observed = rule.breached(values)
            state.last_value = observed
            if observed is None:
                # No evidence either way: clear the pending window, hold
                # any firing alert (a telemetry blackout must not flap).
                state.pending_since = None
                continue
            if breached:
                if state.firing:
                    continue
                if state.pending_since is None:
                    state.pending_since = now
                if now - state.pending_since >= rule.for_s:
                    state.firing = True
                    state.fired_count += 1
                    state.pending_since = None
                    transitions.append(self._emit(rule, "firing", observed, now))
            else:
                state.pending_since = None
                if state.firing:
                    state.firing = False
                    state.resolved_count += 1
                    transitions.append(self._emit(rule, "resolved", observed, now))
        return transitions

    def _emit(
        self, rule: SloRule, state: str, observed: float | None, now: float
    ) -> dict[str, Any]:
        transition = {
            "rule": rule.name,
            "state": state,
            "metric": rule.metric,
            "agg": rule.agg,
            "op": rule.op,
            "threshold": rule.threshold,
            "value": observed if observed is None or math.isfinite(observed) else None,
            "severity": rule.severity,
            "at": now,
        }
        recorder = self._recorder if self._recorder is not None else get_recorder()
        recorder.record("alert", **transition)
        if self._bus is not None:
            from deeplearning_cfn_tpu.provision.events import (
                EventKind,
                LifecycleEvent,
            )

            self._bus.publish(
                LifecycleEvent(
                    kind=EventKind.ALERT, group=self._group, detail=dict(transition)
                )
            )
        log.info(
            "alert %s %s: %s.%s=%r %s %r",
            transition["rule"], state, rule.metric, rule.agg,
            observed, rule.op, rule.threshold,
        )
        return transition

    def snapshot(self) -> dict[str, dict[str, Any]]:
        """Per-rule state for status displays and chaos assertions."""
        return {
            name: {
                "firing": s.firing,
                "pending": s.pending_since is not None,
                "fired_count": s.fired_count,
                "resolved_count": s.resolved_count,
                "last_value": s.last_value,
            }
            for name, s in sorted(self._state.items())
        }

    def active(self) -> list[str]:
        """Names of currently-firing rules, sorted."""
        return sorted(n for n, s in self._state.items() if s.firing)


def _monotonic() -> float:
    import time

    return time.monotonic()


def validate_rules(rules: tuple[SloRule, ...] = DEFAULT_RULES) -> list[str]:
    """Standalone schema check for check.sh: every rule parses, and its
    metric resolves against the exporter's registered families."""
    errors = [e for rule in rules for e in rule.validate()]
    from deeplearning_cfn_tpu.obs.exporter import METRIC_REGISTRY

    for rule in rules:
        if rule.metric not in METRIC_REGISTRY:
            errors.append(
                f"{rule.name}: metric {rule.metric!r} is not in "
                "obs.exporter.METRIC_REGISTRY"
            )
    return errors
