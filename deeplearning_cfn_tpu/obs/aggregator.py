"""Fleet telemetry: agent snapshots over TELEM, merged controller-side.

Every observability surface before this module was per-host; ROADMAP
items 3 (autoscale) and 5 (10k agents) need ONE fleet-wide view.  The
design piggybacks on planes that already exist:

* **Agent side** — :func:`agent_snapshot` builds a compact dict of
  gauges (scalar, last-write-wins per worker) and summaries (bounded
  raw-sample lists — the mergeable form; per-host p99s cannot be
  merged, samples can).  :func:`encode_snapshot` serializes it through
  ``json_safe`` with ``allow_nan=False`` so a snapshot line always
  parses (NaN/Inf become null, exactly like the flight journal).  The
  :class:`~deeplearning_cfn_tpu.obs.heartbeat.Heartbeater` ships it via
  the ``TELEM`` broker verb on the SAME connection and cadence as the
  beat — fleet telemetry costs zero extra dials.

* **Broker** — stores only (payload, steady-clock age, count) per
  worker and replicates TELEM frames through the PR 10 journal, so the
  fleet view survives a primary failover with at most the unshipped
  tail lost (the same bound the queue plane has).

* **Controller side** — :class:`FleetAggregator` merges the dump:
  gauges fold as sum / max / last-by-worker, summaries concatenate
  samples and reduce to quantiles once, fleet-wide.  The merge is
  deterministic (sorted worker order) so chaos reports built on it are
  byte-identical per seed.  ``dlcfn status --fleet`` renders the
  aggregate as json or Prometheus text (obs/exporter.py), and the SLO
  engine (obs/slo.py) evaluates alert rules over
  :func:`fleet_metric_values`.

Metric names inside snapshots are the exporter's registered families
(``dlcfn_*``, see ``obs.exporter.METRIC_REGISTRY``); the SLO schema
check in scripts/check.sh rejects rules referencing anything else.
"""

from __future__ import annotations

import json
import math
from typing import Any, Callable, Mapping, Sequence

SNAPSHOT_VERSION = 1

#: Per-host bound on samples shipped per summary metric: keeps the
#: heartbeat-path encode O(gauge count + capped samples) and the TELEM
#: payload small regardless of how long the agent has been running.
MAX_SUMMARY_SAMPLES = 64

#: Snapshots older than this are dropped from the merge — a worker that
#: stopped shipping (dead, partitioned) must not pin stale gauges into
#: the fleet view forever.  Interpretation is controller-side and
#: configurable, like liveness thresholds.
DEFAULT_STALE_AFTER_S = 120.0


def agent_snapshot(
    gauges: Mapping[str, float] | None = None,
    summaries: Mapping[str, Sequence[float]] | None = None,
    profiler: Any = None,
) -> dict[str, Any]:
    """One agent's current telemetry: ``{"v", "gauges", "summaries"}``.

    ``profiler`` (a :class:`~deeplearning_cfn_tpu.obs.profiler.StepProfiler`)
    contributes its rolling step-time window as the ``dlcfn_step_ms``
    summary.  Callers add serving/queue gauges under their registered
    exporter names.
    """
    snap: dict[str, Any] = {
        "v": SNAPSHOT_VERSION,
        "gauges": {str(k): v for k, v in (gauges or {}).items()},
        "summaries": {
            str(k): list(v)[-MAX_SUMMARY_SAMPLES:]
            for k, v in (summaries or {}).items()
        },
    }
    if profiler is not None:
        samples = profiler.recent_step_ms()
        if samples:
            snap["summaries"]["dlcfn_step_ms"] = samples[-MAX_SUMMARY_SAMPLES:]
    return snap


def encode_snapshot(snapshot: Mapping[str, Any]) -> bytes:
    """Serialize a snapshot for the TELEM payload.

    Strict JSON like the flight journal: values route through
    ``train.metrics.json_safe`` (NaN/Inf -> null, 0-d numpy/jax scalars
    -> plain Python) and ``allow_nan=False`` guarantees the wire bytes
    always re-parse.  Summary sample lists are re-capped here so a
    caller handing an unbounded list cannot bloat the heartbeat path.
    """
    # Lazy: obs stays importable without jax (train.metrics pulls it in).
    from deeplearning_cfn_tpu.train.metrics import json_safe

    body = {
        "v": int(snapshot.get("v", SNAPSHOT_VERSION)),
        "gauges": json_safe(dict(snapshot.get("gauges") or {})),
        "summaries": {
            str(k): json_safe(list(v)[-MAX_SUMMARY_SAMPLES:])
            for k, v in (snapshot.get("summaries") or {}).items()
        },
    }
    return json.dumps(
        body, allow_nan=False, sort_keys=True, separators=(",", ":")
    ).encode()


def decode_snapshot(payload: bytes) -> dict[str, Any] | None:
    """Parse a TELEM payload; ``None`` for torn/foreign bytes (a merge
    must survive one corrupt snapshot without dropping the fleet)."""
    try:
        body = json.loads(payload.decode())
    except (UnicodeDecodeError, ValueError):
        return None
    if not isinstance(body, dict):
        return None
    return body


def _quantile(ordered: Sequence[float], q: float) -> float:
    """Nearest-rank on the sorted sample list — the same reduction
    RollingQuantiles uses, so per-host and fleet-wide views agree on a
    single host."""
    n = len(ordered)
    return ordered[min(n - 1, round(q * (n - 1)))]


def _finite(value: Any) -> float | None:
    try:
        out = float(value)
    except (TypeError, ValueError):
        return None
    return out if math.isfinite(out) else None


class FleetAggregator:
    """Merge per-worker TELEM snapshots into one fleet aggregate.

    ``merge`` consumes the telemetry-dump shape both the real client
    (``BrokerConnection.telemetry()``) and the sim twin
    (``SimBrokerNode.dump_telem()``) produce: ``worker -> (age_s,
    count, payload_bytes)``.  Iteration is over sorted worker names and
    quantiles reduce once over the concatenated samples, so the output
    is a pure function of the input table — byte-deterministic.
    """

    def __init__(self, stale_after_s: float = DEFAULT_STALE_AFTER_S):
        self.stale_after_s = float(stale_after_s)

    def merge(
        self,
        table: Mapping[str, tuple[float, int, bytes]],
        liveness: Mapping[str, Mapping[str, Any]] | None = None,
    ) -> dict[str, Any]:
        workers: dict[str, dict[str, Any]] = {}
        gauges: dict[str, dict[str, Any]] = {}
        samples: dict[str, list[float]] = {}
        dropped_stale = 0
        dropped_corrupt = 0
        for worker in sorted(table):
            age_s, count, payload = table[worker]
            if age_s > self.stale_after_s:
                dropped_stale += 1
                continue
            body = decode_snapshot(payload)
            if body is None:
                dropped_corrupt += 1
                continue
            workers[worker] = {"age_s": round(float(age_s), 6), "count": int(count)}
            for name in sorted(body.get("gauges") or {}):
                value = _finite((body["gauges"] or {}).get(name))
                if value is None:
                    continue
                slot = gauges.setdefault(
                    name, {"sum": 0.0, "max": None, "last": {}}
                )
                slot["sum"] += value
                slot["max"] = value if slot["max"] is None else max(slot["max"], value)
                slot["last"][worker] = value
            for name in sorted(body.get("summaries") or {}):
                values = (body["summaries"] or {}).get(name) or []
                bucket = samples.setdefault(name, [])
                bucket.extend(
                    v for v in (_finite(x) for x in values) if v is not None
                )
        summaries: dict[str, dict[str, Any]] = {}
        for name in sorted(samples):
            ordered = sorted(samples[name])
            if not ordered:
                summaries[name] = {"count": 0, "sum": 0.0}
                continue
            summaries[name] = {
                "count": len(ordered),
                "sum": round(sum(ordered), 6),
                "p50": round(_quantile(ordered, 0.50), 6),
                "p95": round(_quantile(ordered, 0.95), 6),
                "p99": round(_quantile(ordered, 0.99), 6),
            }
        aggregate: dict[str, Any] = {
            "hosts": len(workers),
            "workers": workers,
            "gauges": {
                name: {
                    "sum": round(slot["sum"], 6),
                    "max": round(slot["max"], 6),
                    "last": {w: round(v, 6) for w, v in sorted(slot["last"].items())},
                }
                for name, slot in sorted(gauges.items())
            },
            "summaries": summaries,
            "dropped_stale": dropped_stale,
            "dropped_corrupt": dropped_corrupt,
        }
        if liveness is not None:
            total = len(liveness)
            dead = sum(
                1 for row in liveness.values() if row.get("state") == "dead"
            )
            aggregate["dead_fraction"] = (
                round(dead / total, 6) if total else 0.0
            )
        return aggregate


def fleet_metric_values(aggregate: Mapping[str, Any]) -> dict[str, dict[str, float]]:
    """Flatten a merged aggregate into ``metric -> {agg: value}`` — the
    view the SLO engine resolves rule references against.

    Gauges expose ``sum`` / ``max``; summaries expose ``p50`` / ``p95``
    / ``p99`` / ``count``; the synthesized fleet metrics expose
    ``value``.  Missing metrics are simply absent — a rule over an
    absent metric does not fire (no data is not a breach).
    """
    values: dict[str, dict[str, float]] = {}
    for name, slot in (aggregate.get("gauges") or {}).items():
        entry: dict[str, float] = {}
        for agg in ("sum", "max"):
            v = _finite(slot.get(agg))
            if v is not None:
                entry[agg] = v
        if entry:
            values[name] = entry
    for name, slot in (aggregate.get("summaries") or {}).items():
        entry = {}
        for agg in ("p50", "p95", "p99", "count"):
            v = _finite(slot.get(agg))
            if v is not None:
                entry[agg] = v
        if entry:
            values[name] = entry
    values["dlcfn_fleet_workers"] = {"value": float(aggregate.get("hosts") or 0)}
    dead_fraction = _finite(aggregate.get("dead_fraction"))
    if dead_fraction is not None:
        values["dlcfn_worker_dead_fraction"] = {"value": dead_fraction}
    return values


def telemetry_source(
    worker_id: str,
    profiler: Any = None,
    gauges: Callable[[], Mapping[str, float]] | None = None,
) -> Callable[[], dict[str, Any]]:
    """Build the zero-arg callable ``Heartbeater(telemetry_source=...)``
    wants: a fresh snapshot per beat from the live profiler window plus
    optional dynamic gauges.  ``worker_id`` only names the closure for
    logs — identity on the wire comes from the TELEM frame itself."""

    def produce() -> dict[str, Any]:
        return agent_snapshot(
            gauges=gauges() if gauges is not None else None,
            profiler=profiler,
        )

    produce.__name__ = f"telemetry_source[{worker_id}]"
    return produce
