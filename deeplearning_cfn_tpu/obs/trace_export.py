"""Cross-host trace timeline: merge flight journals into Chrome trace JSON.

Each host writes its own flight-recorder journal with timestamps from
its own clock.  Merging them naively interleaves events wrongly whenever
host clocks disagree (NTP skew on pods is routinely larger than a step
time).  This module aligns clocks using the broker heartbeat exchange
that already exists for liveness:

- every worker journals ``heartbeat_sent  {worker, seq, ts}`` with its
  own clock,
- the supervisor journals ``heartbeat_observed {worker, seq, age_s, ts}``
  with *its* clock when the beat count advances,

so for each matched ``(worker, seq)`` pair, ``(observed_ts - age_s)``
and ``sent_ts`` name the same instant on two clocks.  The median of the
differences is the sender->observer offset (median absorbs the odd
delayed observation).  The first journal containing ``heartbeat_observed``
events is the reference clock; journals with no matched beats keep
offset 0.

Output is Chrome trace-event JSON (the ``{"traceEvents": [...]}`` form),
loadable in ``chrome://tracing`` or https://ui.perfetto.dev: one process
row per host, ``span`` and ``step_time`` events as complete ("X") slices,
everything else as instants.  ``straggler_table`` turns per-host
``step_time`` events into the slowest-host-per-step table surfaced by
``dlcfn status --profile`` and ``dlcfn trace``.
"""

from __future__ import annotations

import statistics
from pathlib import Path
from typing import Any, Iterable, Sequence

from deeplearning_cfn_tpu.obs.recorder import read_journal


#: Event kinds whose ``worker`` field names ANOTHER host (the observed
#: worker), not the journal's owner — label by ``host`` for these.
_OBSERVER_KINDS = frozenset({"heartbeat_observed", "liveness"})


def _event_host(event: dict[str, Any]) -> str | None:
    keys = (
        ("trace_host", "host", "worker")
        if event.get("kind") in _OBSERVER_KINDS
        else ("trace_host", "worker", "host")
    )
    for key in keys:
        value = event.get(key)
        if isinstance(value, str) and value:
            return value
    return None


def _journal_label(events: list[dict[str, Any]], fallback: str) -> str:
    """A journal's host label: its dominant worker/host field, else stem."""
    counts: dict[str, int] = {}
    for event in events:
        keys = (
            ("host",)
            if event.get("kind") in _OBSERVER_KINDS
            else ("worker", "host")
        )
        for key in keys:
            value = event.get(key)
            if isinstance(value, str) and value:
                counts[value] = counts.get(value, 0) + 1
                break
    if counts:
        return max(sorted(counts), key=lambda label: counts[label])
    return fallback


def heartbeat_offsets(
    journals: dict[str, list[dict[str, Any]]],
) -> tuple[dict[str, float], str | None]:
    """Per-journal clock offset onto the reference (observer) clock.

    Returns ``(offsets, reference_label)``; every journal gets an entry
    (0.0 when unmatched), ``reference_label`` is None when no journal
    contains ``heartbeat_observed`` events (alignment degrades to raw
    timestamps).
    """
    reference: str | None = None
    observed: dict[tuple[str, int], tuple[float, float]] = {}
    for label, events in journals.items():
        for event in events:
            if event.get("kind") != "heartbeat_observed":
                continue
            worker, seq, ts = event.get("worker"), event.get("seq"), event.get("ts")
            if not isinstance(worker, str) or not isinstance(seq, int):
                continue
            if not isinstance(ts, (int, float)):
                continue
            if reference is None:
                reference = label
            age = event.get("age_s")
            age_s = float(age) if isinstance(age, (int, float)) else 0.0
            observed[(worker, seq)] = (float(ts), age_s)
    offsets = {label: 0.0 for label in journals}
    if reference is None:
        return offsets, None
    for label, events in journals.items():
        if label == reference:
            continue
        deltas = []
        for event in events:
            if event.get("kind") != "heartbeat_sent":
                continue
            worker, seq, ts = event.get("worker"), event.get("seq"), event.get("ts")
            if not isinstance(worker, str) or not isinstance(seq, int):
                continue
            if not isinstance(ts, (int, float)):
                continue
            match = observed.get((worker, seq))
            if match is None:
                continue
            observed_ts, age_s = match
            deltas.append((observed_ts - age_s) - float(ts))
        if deltas:
            offsets[label] = statistics.median(deltas)
    return offsets, reference


def merge_journals(
    paths: Sequence[str | Path], align: bool = True
) -> tuple[list[dict[str, Any]], dict[str, Any]]:
    """Merge per-host journals into one aligned, time-sorted event list.

    Every event gains a ``trace_host`` label (the journal it came from)
    and, when ``align`` is true, its ``ts`` is shifted onto the reference
    clock.  Returns ``(events, meta)`` where meta carries the recovered
    offsets and the reference journal's label.
    """
    journals: dict[str, list[dict[str, Any]]] = {}
    for i, path in enumerate(paths):
        events = list(read_journal(path))
        label = _journal_label(events, Path(path).stem or f"journal{i}")
        base, suffix = label, 2
        while label in journals:
            label = f"{base}#{suffix}"
            suffix += 1
        journals[label] = events
    if align:
        offsets, reference = heartbeat_offsets(journals)
    else:
        offsets, reference = {label: 0.0 for label in journals}, None
    merged = []
    for label, events in journals.items():
        offset = offsets.get(label, 0.0)
        for event in events:
            ts = event.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            out = dict(event)
            out["ts"] = float(ts) + offset
            out["trace_host"] = label
            merged.append(out)
    merged.sort(key=lambda e: (e["ts"], e["trace_host"], str(e.get("kind", ""))))
    meta = {
        "offsets": {label: round(value, 6) for label, value in offsets.items()},
        "reference": reference,
        "aligned": align and reference is not None,
    }
    return merged, meta


_STEP_PHASE_KEYS = (
    "data_wait_ms",
    "h2d_ms",
    "dispatch_ms",
    "compute_ms",
    "host_ms",
)


def chrome_trace(events: Iterable[dict[str, Any]]) -> dict[str, Any]:
    """Render merged events as Chrome trace-event JSON.

    One process per host (stable pids in sorted-label order); ``span``
    and ``step_time`` events become complete "X" slices ending at their
    journal timestamp (both are recorded at block end), everything else
    an instant.  Timestamps are microseconds, per the trace-event spec.
    """
    events = list(events)
    hosts = sorted({_event_host(e) or "host" for e in events})
    pid_of = {host: i + 1 for i, host in enumerate(hosts)}
    trace: list[dict[str, Any]] = []
    for host in hosts:
        trace.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid_of[host],
                "tid": 0,
                "args": {"name": host},
            }
        )
    for event in events:
        ts = event.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        pid = pid_of[_event_host(event) or "host"]
        kind = str(event.get("kind", "event"))
        end_us = float(ts) * 1e6
        if kind == "span" and isinstance(event.get("seconds"), (int, float)):
            dur = float(event["seconds"]) * 1e6
            args = {
                key: event[key]
                for key in ("step", "ok")
                if event.get(key) is not None
            }
            trace.append(
                {
                    "name": str(event.get("span") or "span"),
                    "cat": "span",
                    "ph": "X",
                    "ts": round(end_us - dur, 3),
                    "dur": round(dur, 3),
                    "pid": pid,
                    "tid": 0,
                    "args": args,
                }
            )
        elif kind == "step_time" and isinstance(
            event.get("total_ms"), (int, float)
        ):
            dur = float(event["total_ms"]) * 1e3
            args = {
                key: event[key] for key in _STEP_PHASE_KEYS if key in event
            }
            step = event.get("step")
            trace.append(
                {
                    "name": f"step {step}" if step is not None else "step",
                    "cat": "step",
                    "ph": "X",
                    "ts": round(end_us - dur, 3),
                    "dur": round(dur, 3),
                    "pid": pid,
                    "tid": 1,
                    "args": args,
                }
            )
        else:
            trace.append(
                {
                    "name": kind,
                    "cat": "event",
                    "ph": "i",
                    "ts": round(end_us, 3),
                    "pid": pid,
                    "tid": 0,
                    "s": "p",
                }
            )
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def straggler_table(events: Iterable[dict[str, Any]]) -> dict[str, Any]:
    """Slowest-host-per-step table from per-host ``step_time`` events.

    Steps observed on fewer than two hosts carry no cross-host signal
    and are skipped.  Ties break to the alphabetically-first host, so
    the table is deterministic for fixture journals.
    """
    by_step: dict[int, dict[str, float]] = {}
    for event in events:
        if event.get("kind") != "step_time":
            continue
        step, total = event.get("step"), event.get("total_ms")
        if not isinstance(step, int) or not isinstance(total, (int, float)):
            continue
        host = _event_host(event) or "host"
        by_step.setdefault(step, {})[host] = float(total)
    rows = []
    counts: dict[str, int] = {}
    for step in sorted(by_step):
        hosts = by_step[step]
        if len(hosts) < 2:
            continue
        slowest = max(sorted(hosts), key=lambda h: hosts[h])
        median_ms = statistics.median(hosts.values())
        rows.append(
            {
                "step": step,
                "slowest": slowest,
                "slowest_ms": round(hosts[slowest], 3),
                "median_ms": round(median_ms, 3),
                "margin_ms": round(hosts[slowest] - median_ms, 3),
                "hosts": {h: round(v, 3) for h, v in sorted(hosts.items())},
            }
        )
        counts[slowest] = counts.get(slowest, 0) + 1
    top = max(sorted(counts), key=lambda h: counts[h]) if counts else None
    return {
        "steps": rows,
        "slowest_counts": dict(sorted(counts.items())),
        "top_straggler": top,
    }
