"""Step-time profiler: per-phase breakdown of the training step loop.

The bench has emitted one aggregate images/sec number since PR 1; this
module splits every step into the phases that can actually eat it:

- ``data_wait``  — host blocked pulling the next batch from the source
  (``wrap_source`` times each ``next()`` on the batch iterator).
- ``h2d``        — host->device transfer.  Consumer-side ``device_put``
  is critical-path; producer-side transfer inside ``DevicePrefetcher``
  overlaps compute and is folded with ``critical=False`` so it shows in
  the phase stats without being subtracted from the host residual.
- ``dispatch``   — enqueueing the jitted step.  Under async dispatch
  this is host time only; a growing dispatch phase with flat compute is
  the per-call-overhead signature (docs/PERFORMANCE.md).
- ``compute``    — device time observed at sync boundaries.  The host
  only learns device time when it blocks on a readback, so this is a
  *lower bound* amortized over the steps drained at that boundary
  (``sync_boundary(steps=n)`` adds ``seconds / n`` per step).
- ``host``       — the residual: step wall time minus critical-path
  phase time.  Python loop overhead, logging, checkpoint hooks.

Each phase keeps count/total/max plus rolling p50/p95/p99 over a
bounded window (``RollingQuantiles`` — also reused by the CLI's span
aggregates).  ``snapshot()`` returns the flat ``*_ms`` per-step means
the bench JSON publishes; ``journal()`` records one ``step_profile``
event; ``per_step_events=True`` records a ``step_time`` event per step,
which is what ``dlcfn trace`` and straggler detection consume.

Profiling is OFF by default everywhere: ``Trainer.fit(profiler=None)``
uses ``NULL_PROFILER`` whose every method is an early-return no-op
(``wrap_source`` returns its argument unchanged), so the un-profiled
hot path pays one attribute check per call site.

``program_cost`` / ``program_attribution`` turn an AOT-compiled
program's ``cost_analysis`` into per-program MFU/MBU — the per-compiled-
program attribution the bench reports next to whole-run MFU.  Per-device
flops over per-chip peak, same convention as ``compile_stats``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Iterable, Iterator

from deeplearning_cfn_tpu.obs.recorder import FlightRecorder, get_recorder

#: Canonical phase order for snapshots and docs.
PHASES = ("data_wait", "h2d", "dispatch", "compute", "host")


class RollingQuantiles:
    """p50/p95/p99 over a bounded window of recent samples.

    A sorted copy per query (not per sample) keeps the hot-path cost at
    one deque append; queries happen at snapshot/export time only.  Not
    thread-safe on its own — callers hold their own lock.
    """

    __slots__ = ("_window",)

    def __init__(self, window: int = 512) -> None:
        self._window: deque[float] = deque(maxlen=max(2, int(window)))

    def add(self, value: float) -> None:
        self._window.append(float(value))

    def __len__(self) -> int:
        return len(self._window)

    def samples(self) -> list[float]:
        """The current window, oldest first — the mergeable raw form the
        fleet aggregator ships instead of pre-reduced quantiles (per-host
        p99s cannot be merged; samples can)."""
        return list(self._window)

    def quantiles(self) -> dict[str, float]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` — empty dict if no samples."""
        if not self._window:
            return {}
        ordered = sorted(self._window)
        n = len(ordered)
        out = {}
        for key, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            out[key] = ordered[min(n - 1, round(q * (n - 1)))]
        return out


class PhaseStats:
    """Aggregate for one phase: count / total / max / rolling quantiles."""

    __slots__ = ("count", "total_s", "max_s", "_quantiles")

    def __init__(self, window: int = 512) -> None:
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self._quantiles = RollingQuantiles(window)

    def fold(self, seconds: float, samples: int = 1) -> None:
        # ``samples`` amortizes one observation over n steps (a sync
        # boundary draining n steps of pending metrics observes the
        # device time of all n at once).
        samples = max(1, int(samples))
        per_step = seconds / samples
        self.count += samples
        self.total_s += seconds
        self.max_s = max(self.max_s, per_step)
        self._quantiles.add(per_step)

    def as_dict(self) -> dict[str, Any]:
        out = {
            "count": self.count,
            "total_ms": round(self.total_s * 1e3, 3),
            "mean_ms": round(self.total_s * 1e3 / self.count, 3)
            if self.count
            else 0.0,
            "max_ms": round(self.max_s * 1e3, 3),
        }
        for key, value in self._quantiles.quantiles().items():
            out[f"{key}_ms"] = round(value * 1e3, 3)
        return out


class _PhaseTimer:
    """Context manager timing one block into one phase."""

    __slots__ = ("_profiler", "_name", "_t0")

    def __init__(self, profiler: "StepProfiler", name: str) -> None:
        self._profiler = profiler
        self._name = name

    def __enter__(self) -> None:
        self._t0 = self._profiler._clock()

    def __exit__(self, *exc: Any) -> None:
        self._profiler.fold(self._name, self._profiler._clock() - self._t0)


class _SyncTimer:
    """Times a blocking readback into ``compute``, amortized over steps."""

    __slots__ = ("_profiler", "_steps", "_t0")

    def __init__(self, profiler: "StepProfiler", steps: int) -> None:
        self._profiler = profiler
        self._steps = max(1, int(steps))

    def __enter__(self) -> None:
        self._t0 = self._profiler._clock()

    def __exit__(self, *exc: Any) -> None:
        self._profiler.fold(
            "compute",
            self._profiler._clock() - self._t0,
            samples=self._steps,
        )


class _NullContext:
    """Reusable, reentrant no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: Any) -> None:
        return None


_NULL_CTX = _NullContext()


class StepProfiler:
    """Splits a step loop into phases with rolling distributions.

    Thread-safe: producer threads (``DevicePrefetcher``) fold overlapped
    transfer time concurrently with the consumer loop.  ``clock`` is
    injectable so tests never depend on wall time.
    """

    def __init__(
        self,
        name: str = "train",
        enabled: bool = True,
        window: int = 512,
        clock: Callable[[], float] = time.perf_counter,
        recorder: FlightRecorder | None = None,
        per_step_events: bool = False,
    ) -> None:
        self.name = name
        self.enabled = enabled
        self._clock = clock
        self._recorder = recorder
        self._per_step_events = per_step_events
        self._window = max(2, int(window))
        self._lock = threading.Lock()
        self._phases: dict[str, PhaseStats] = {}
        self._step_ms = RollingQuantiles(self._window)
        self._steps = 0
        self._step_total_s = 0.0
        self._step_max_s = 0.0
        self._step_start: float | None = None
        self._critical_s = 0.0
        self._interval: dict[str, float] = {}
        self._labels: dict[str, Any] = {}

    # -- marking ---------------------------------------------------------

    def start(self) -> None:
        """Anchor the first step interval at 'now' (call at loop entry)."""
        if not self.enabled:
            return
        with self._lock:
            self._step_start = self._clock()
            self._critical_s = 0.0
            self._interval = {}

    def phase(self, name: str) -> Any:
        """``with profiler.phase("dispatch"): ...`` times a block."""
        if not self.enabled:
            return _NULL_CTX
        return _PhaseTimer(self, name)

    def sync_boundary(self, steps: int = 1) -> Any:
        """Time a blocking readback into ``compute``, amortized over ``steps``."""
        if not self.enabled:
            return _NULL_CTX
        return _SyncTimer(self, steps)

    def fold(
        self, name: str, seconds: float, critical: bool = True, samples: int = 1
    ) -> None:
        """Fold ``seconds`` into phase ``name``.

        ``critical=False`` marks time that overlapped the step (producer-
        side transfer): it lands in the phase stats but is not counted
        against the step's host residual.
        """
        if not self.enabled:
            return
        with self._lock:
            stats = self._phases.get(name)
            if stats is None:
                stats = self._phases[name] = PhaseStats(self._window)
            stats.fold(seconds, samples=samples)
            if critical:
                self._critical_s += seconds
                self._interval[name] = self._interval.get(name, 0.0) + seconds

    def wrap_source(self, batches: Iterable[Any]) -> Iterable[Any]:
        """Time each ``next()`` on the batch source into ``data_wait``.

        Disabled profilers return ``batches`` unchanged — zero iterator
        indirection on the un-profiled path.
        """
        if not self.enabled:
            return batches

        def timed() -> Iterator[Any]:
            it = iter(batches)
            while True:
                t0 = self._clock()
                try:
                    item = next(it)
                except StopIteration:
                    return
                self.fold("data_wait", self._clock() - t0)
                yield item

        return timed()

    def step_done(self, step: int | None = None, steps: int = 1) -> None:
        """Close the current step interval; compute the host residual."""
        if not self.enabled:
            return
        now = self._clock()
        event: dict[str, Any] | None = None
        with self._lock:
            if self._step_start is None:
                # No anchor: the interval began at an unknown time, so
                # only set one for the next step.
                self._step_start = now
                self._critical_s = 0.0
                self._interval = {}
                return
            n = max(1, int(steps))
            total = max(0.0, now - self._step_start)
            host = max(0.0, total - self._critical_s)
            per_step = total / n
            stats = self._phases.get("host")
            if stats is None:
                stats = self._phases["host"] = PhaseStats(self._window)
            stats.fold(host, samples=n)
            self._step_ms.add(per_step * 1e3)
            self._steps += n
            self._step_total_s += total
            self._step_max_s = max(self._step_max_s, per_step)
            if self._per_step_events:
                event = {
                    "profiler": self.name,
                    "steps": n,
                    "total_ms": round(per_step * 1e3, 3),
                    "host_ms": round(host * 1e3 / n, 3),
                }
                if step is not None:
                    event["step"] = step
                for phase, seconds in sorted(self._interval.items()):
                    event[f"{phase}_ms"] = round(seconds * 1e3 / n, 3)
            self._step_start = now
            self._critical_s = 0.0
            self._interval = {}
        if event is not None:
            # Journal outside the lock (DLC203: no I/O under a lock).
            (self._recorder or get_recorder()).record("step_time", **event)

    def set_label(self, key: str, value: Any) -> None:
        """Attach an annotation carried by every later ``snapshot()``/
        ``journal()`` under ``labels`` — e.g. the bench tags each phase
        profiler with its dispatch ``mode``, so the journaled
        ``step_profile`` events say which loop produced the timings."""
        if not self.enabled:
            return
        with self._lock:
            self._labels[str(key)] = value

    # -- reporting -------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """Aggregate view: flat per-step phase means + full distributions."""
        with self._lock:
            phases = {name: st.as_dict() for name, st in self._phases.items()}
            steps = self._steps
            step_ms: dict[str, Any] = {
                key: round(value, 3)
                for key, value in self._step_ms.quantiles().items()
            }
            if steps:
                step_ms["mean"] = round(self._step_total_s * 1e3 / steps, 3)
                step_ms["max"] = round(self._step_max_s * 1e3, 3)
        out: dict[str, Any] = {"name": self.name, "steps": steps}
        for phase in PHASES:
            total_ms = phases.get(phase, {}).get("total_ms", 0.0)
            # Per-STEP mean (not per-sample): phases with more samples
            # than steps (producer folds) still average over steps.
            out[f"{phase}_ms"] = round(total_ms / steps, 3) if steps else 0.0
        out["step_ms"] = step_ms
        out["phases"] = dict(sorted(phases.items()))
        with self._lock:
            if self._labels:
                out["labels"] = dict(self._labels)
        return out

    def recent_step_ms(self) -> list[float]:
        """Raw step-time samples (ms) in the rolling window, oldest
        first — what ``obs.aggregator.agent_snapshot`` ships as a
        mergeable sketch."""
        with self._lock:
            return self._step_ms.samples()

    def journal(self, recorder: FlightRecorder | None = None) -> dict[str, Any]:
        """Record one ``step_profile`` event with the current snapshot."""
        snap = self.snapshot()
        if self.enabled:
            (recorder or self._recorder or get_recorder()).record(
                "step_profile", **snap
            )
        return snap


#: Shared disabled instance: ``Trainer.fit``'s default profiler.
NULL_PROFILER = StepProfiler(name="null", enabled=False)


# -- per-program cost attribution ---------------------------------------


def program_cost(compiled: Any) -> dict[str, float | None]:
    """Normalize an AOT-compiled program's ``cost_analysis`` to flops/bytes.

    Same list-vs-dict normalization as ``Trainer.compile_stats`` (the
    return shape varies across jax versions and backends); returns
    ``None`` values when the backend reports no cost model.
    """
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {"flops": None, "bytes_accessed": None}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {"flops": None, "bytes_accessed": None}
    flops = cost.get("flops")
    bytes_accessed = cost.get("bytes accessed", cost.get("bytes_accessed"))
    return {
        "flops": float(flops) if flops is not None else None,
        "bytes_accessed": float(bytes_accessed)
        if bytes_accessed is not None
        else None,
    }


def program_attribution(
    *,
    flops: float | None,
    bytes_accessed: float | None,
    seconds_per_call: float,
    steps_per_call: int = 1,
    peak_flops: float | None = None,
) -> dict[str, Any]:
    """Per-program MFU/MBU from cost-model flops and measured call time.

    ``flops``/``bytes_accessed`` are per *call* (a k-step program's cost
    covers all k iterations) and per device for SPMD modules, so
    ``mfu = flops / (seconds_per_call * peak_flops)`` is the per-chip
    utilization of that one program.
    """
    steps_per_call = max(1, int(steps_per_call))
    out: dict[str, Any] = {
        "steps_per_call": steps_per_call,
        "seconds_per_call": round(seconds_per_call, 6),
    }
    if flops is not None:
        out["flops_per_step"] = flops / steps_per_call
        if peak_flops and seconds_per_call > 0:
            out["mfu"] = round(flops / (seconds_per_call * peak_flops), 4)
    if bytes_accessed is not None:
        out["bytes_per_step"] = bytes_accessed / steps_per_call
        if seconds_per_call > 0:
            out["bytes_per_sec"] = round(bytes_accessed / seconds_per_call, 1)
    return out
