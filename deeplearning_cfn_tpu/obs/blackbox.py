"""Crash blackbox: per-host postmortem bundles and the merged timeline.

When an agent dies — fatal exception in the training loop, or the
provisioner announcing ``INSTANCE_TERMINATE`` — the most valuable bytes
are the ones that existed *just before*: the tail of the flight
journal, the profiler's rolling window, the resolved config, the
comms/compile budgets the static passes pinned.  This module freezes
exactly that into a **bundle** (one strict-JSON file per host, written
through the same ``json_safe``/``allow_nan=False`` discipline as the
journal, so a crash bundle always re-parses).

``dlcfn postmortem`` then merges bundles from every host into ONE
causal timeline: per-host clocks are aligned with the heartbeat-pair
offsets obs/trace_export.py already recovers for tracing (the
``heartbeat_sent`` / ``heartbeat_observed`` events ride inside each
bundle's journal tail, so the alignment needs no extra data), and ties
at the same aligned instant break deterministically by ``(host, seq)``
where ``seq`` is the event's index within its bundle — skewed host
clocks reorder nothing between runs.  Alert transitions (journal kind
``"alert"``, obs/slo.py) are surfaced as an overlay so the operator
reads "what fired" next to "what happened".
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Callable, Mapping

from deeplearning_cfn_tpu.obs.recorder import get_recorder
from deeplearning_cfn_tpu.obs.trace_export import heartbeat_offsets
from deeplearning_cfn_tpu.utils.logging import get_logger

log = get_logger("dlcfn.obs")

BUNDLE_VERSION = 1

#: Journal tail length a bundle freezes.  Matches the recorder ring's
#: order of magnitude — more would just re-ship the journal file.
DEFAULT_LAST_N = 200


def capture_bundle(
    reason: str,
    host: str,
    worker: str | None = None,
    recorder: Any = None,
    last_n: int = DEFAULT_LAST_N,
    profiler: Any = None,
    config: Mapping[str, Any] | None = None,
    budgets: Mapping[str, Any] | None = None,
    clock: Callable[[], float] = time.time,
) -> dict[str, Any]:
    """Freeze this host's observability state into a bundle dict.

    ``profiler`` is a ``StepProfiler`` (its ``snapshot()`` is taken) or
    an already-built snapshot dict; ``config`` / ``budgets`` are
    whatever resolved mappings the caller owns (agent config, comms /
    compile budget readouts) — stored verbatim, json-safe.
    """
    rec = recorder if recorder is not None else get_recorder()
    snap = profiler
    if profiler is not None and hasattr(profiler, "snapshot"):
        snap = profiler.snapshot()
    return {
        "v": BUNDLE_VERSION,
        "host": host,
        "worker": worker,
        "reason": reason,
        "captured_ts": round(float(clock()), 6),
        "events": rec.tail(last_n),
        "profiler": snap,
        "config": dict(config) if config else None,
        "budgets": dict(budgets) if budgets else None,
    }


def write_bundle(bundle: Mapping[str, Any], path: str | Path) -> Path:
    """Persist a bundle as strict JSON (NaN/Inf -> null, like the
    journal) — a postmortem written during a crash must never itself
    fail to parse later."""
    from deeplearning_cfn_tpu.train.metrics import json_safe

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(
        json.dumps(json_safe(dict(bundle)), allow_nan=False, default=str, indent=2)
        + "\n"
    )
    return path


def read_bundle(path: str | Path) -> dict[str, Any]:
    return json.loads(Path(path).read_text())


class BlackBox:
    """Arms the capture triggers for one agent process.

    * ``attach(bus)`` — capture on ``INSTANCE_TERMINATE`` for this
      worker's instance (the spot-reap path: the provisioner's warning
      is often the only notice the host gets);
    * ``capture(reason)`` — the fatal-error path agent_main wraps
      around its run loop.

    Each capture writes ``<dir>/blackbox-<host>.json`` (last capture
    wins — the newest state is the one the postmortem wants).
    """

    def __init__(
        self,
        out_dir: str | Path,
        host: str,
        worker: str | None = None,
        instance_id: str | None = None,
        profiler: Any = None,
        config: Mapping[str, Any] | None = None,
        budgets: Mapping[str, Any] | None = None,
        recorder: Any = None,
        clock: Callable[[], float] = time.time,
    ):
        self.out_dir = Path(out_dir)
        self.host = host
        self.worker = worker
        self.instance_id = instance_id
        self._profiler = profiler
        self._config = config
        self._budgets = budgets
        self._recorder = recorder
        self._clock = clock
        self.captures = 0
        self._handler = None

    @property
    def path(self) -> Path:
        return self.out_dir / f"blackbox-{self.host}.json"

    def capture(self, reason: str) -> Path:
        bundle = capture_bundle(
            reason=reason,
            host=self.host,
            worker=self.worker,
            recorder=self._recorder,
            profiler=self._profiler,
            config=self._config,
            budgets=self._budgets,
            clock=self._clock,
        )
        out = write_bundle(bundle, self.path)
        self.captures += 1
        log.warning("blackbox captured (%s) -> %s", reason, out)
        return out

    def attach(self, bus: Any) -> None:
        """Subscribe the terminate trigger; idempotent per BlackBox."""
        if self._handler is not None:
            return
        from deeplearning_cfn_tpu.provision.events import EventKind

        def _on_event(event) -> None:
            if event.kind is not EventKind.INSTANCE_TERMINATE:
                return
            if (
                self.instance_id is not None
                and event.instance_id is not None
                and event.instance_id != self.instance_id
            ):
                return
            self.capture(f"instance-terminate:{event.instance_id or event.group}")

        self._handler = _on_event
        bus.subscribe(_on_event)

    def detach(self, bus: Any) -> None:
        if self._handler is not None:
            bus.unsubscribe(self._handler)
            self._handler = None


def merge_bundles(bundles: list[Mapping[str, Any]]) -> dict[str, Any]:
    """Merge per-host bundles into one causally-ordered timeline.

    Clock alignment reuses the heartbeat-pair offsets from
    obs/trace_export.py over each bundle's embedded journal tail; hosts
    with no matched beats keep offset 0 (degrades to raw timestamps,
    which the meta records).  The sort key is ``(aligned_ts, host,
    seq)`` — ``seq`` being the event's index within its own bundle —
    so equal timestamps under clock skew still order byte-identically.
    """
    journals: dict[str, list[dict[str, Any]]] = {}
    labeled: list[tuple[str, Mapping[str, Any]]] = []
    for i, bundle in enumerate(bundles):
        label = str(bundle.get("host") or bundle.get("worker") or f"bundle{i}")
        base, suffix = label, 2
        while label in journals:
            label = f"{base}#{suffix}"
            suffix += 1
        journals[label] = list(bundle.get("events") or [])
        labeled.append((label, bundle))
    offsets, reference = heartbeat_offsets(journals)
    events: list[dict[str, Any]] = []
    for label, bundle in labeled:
        offset = offsets.get(label, 0.0)
        for seq, event in enumerate(journals[label]):
            ts = event.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            out = dict(event)
            out["ts"] = round(float(ts) + offset, 6)
            out["bb_host"] = label
            out["bb_seq"] = seq
            events.append(out)
    events.sort(key=lambda e: (e["ts"], e["bb_host"], e["bb_seq"]))
    alerts = [e for e in events if e.get("kind") == "alert"]
    return {
        "events": events,
        "alerts": alerts,
        "hosts": {
            label: {
                "reason": bundle.get("reason"),
                "worker": bundle.get("worker"),
                "captured_ts": bundle.get("captured_ts"),
                "offset_s": round(offsets.get(label, 0.0), 6),
            }
            for label, bundle in labeled
        },
        "reference": reference,
        "aligned": reference is not None,
    }


def render_timeline(merged: Mapping[str, Any], last_n: int | None = None) -> str:
    """Human postmortem view: one line per event on the aligned clock,
    alert transitions flagged, capture reasons up top."""
    lines: list[str] = []
    hosts = merged.get("hosts") or {}
    lines.append(
        f"postmortem: {len(hosts)} host(s), "
        f"clock alignment {'heartbeat-paired' if merged.get('aligned') else 'RAW (no matched beats)'}"
    )
    for label, info in sorted(hosts.items()):
        lines.append(
            f"  {label}: reason={info.get('reason')!r} "
            f"worker={info.get('worker')} offset={info.get('offset_s')}s"
        )
    alerts = merged.get("alerts") or []
    if alerts:
        lines.append(f"alerts ({len(alerts)} transition(s)):")
        for alert in alerts:
            lines.append(
                f"  {alert['ts']:.3f} [{alert['bb_host']}] "
                f"{alert.get('rule')} -> {alert.get('state')} "
                f"({alert.get('metric')}.{alert.get('agg')}={alert.get('value')})"
            )
    events = list(merged.get("events") or [])
    if last_n is not None:
        events = events[-last_n:]
    lines.append(f"timeline ({len(events)} event(s)):")
    for event in events:
        marker = " !" if event.get("kind") == "alert" else ""
        detail = {
            k: v
            for k, v in event.items()
            if k
            not in (
                "ts", "kind", "host", "pid", "cluster",
                "worker", "bb_host", "bb_seq",
            )
            and v is not None
        }
        body = " ".join(f"{k}={v}" for k, v in detail.items())
        lines.append(
            f"  {event['ts']:.3f} [{event['bb_host']}]"
            f"{marker} {event.get('kind')} {body}".rstrip()
        )
    return "\n".join(lines) + "\n"
