"""Cluster observability plane: flight recorder, spans, liveness, profiler.

The control plane (broker, elasticity, recovery, provisioner) and the
data plane (trainer) both feed one bounded JSONL flight journal; the
``dlcfn status`` / ``dlcfn events`` / ``dlcfn trace`` commands and the
Prometheus exporter read it back out.  Nothing in here imports jax at
module scope — the broker and CLI processes must stay light; the one
jax dependency (``train.metrics.json_safe``) is imported lazily at
first record.
"""

from deeplearning_cfn_tpu.obs.recorder import (
    FlightRecorder,
    configure,
    follow_journal,
    get_recorder,
    read_journal,
)
from deeplearning_cfn_tpu.obs.tracing import span, span_aggregates, reset_aggregates
from deeplearning_cfn_tpu.obs.liveness import (
    LivenessConfig,
    LivenessTable,
    WorkerState,
)
from deeplearning_cfn_tpu.obs.heartbeat import Heartbeater
from deeplearning_cfn_tpu.obs.profiler import (
    NULL_PROFILER,
    RollingQuantiles,
    StepProfiler,
    program_attribution,
    program_cost,
)
from deeplearning_cfn_tpu.obs.trace_export import (
    chrome_trace,
    merge_journals,
    straggler_table,
)
from deeplearning_cfn_tpu.obs.aggregator import (
    FleetAggregator,
    agent_snapshot,
    decode_snapshot,
    encode_snapshot,
    fleet_metric_values,
    telemetry_source,
)
from deeplearning_cfn_tpu.obs.slo import DEFAULT_RULES, SloEngine, SloRule
from deeplearning_cfn_tpu.obs.blackbox import (
    BlackBox,
    capture_bundle,
    merge_bundles,
    read_bundle,
    render_timeline,
    write_bundle,
)

__all__ = [
    "FlightRecorder",
    "configure",
    "follow_journal",
    "get_recorder",
    "read_journal",
    "span",
    "span_aggregates",
    "reset_aggregates",
    "LivenessConfig",
    "LivenessTable",
    "WorkerState",
    "Heartbeater",
    "NULL_PROFILER",
    "RollingQuantiles",
    "StepProfiler",
    "program_attribution",
    "program_cost",
    "chrome_trace",
    "merge_journals",
    "straggler_table",
    "FleetAggregator",
    "agent_snapshot",
    "decode_snapshot",
    "encode_snapshot",
    "fleet_metric_values",
    "telemetry_source",
    "DEFAULT_RULES",
    "SloEngine",
    "SloRule",
    "BlackBox",
    "capture_bundle",
    "merge_bundles",
    "read_bundle",
    "render_timeline",
    "write_bundle",
]
