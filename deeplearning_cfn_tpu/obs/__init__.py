"""Cluster observability plane: flight recorder, spans, liveness.

The control plane (broker, elasticity, recovery, provisioner) and the
data plane (trainer) both feed one bounded JSONL flight journal; the
``dlcfn status`` / ``dlcfn events`` commands and the Prometheus
exporter read it back out.  Nothing in here imports jax at module
scope — the broker and CLI processes must stay light; the one jax
dependency (``train.metrics.json_safe``) is imported lazily at first
record.
"""

from deeplearning_cfn_tpu.obs.recorder import (
    FlightRecorder,
    configure,
    get_recorder,
    read_journal,
)
from deeplearning_cfn_tpu.obs.tracing import span, span_aggregates, reset_aggregates
from deeplearning_cfn_tpu.obs.liveness import (
    LivenessConfig,
    LivenessTable,
    WorkerState,
)
from deeplearning_cfn_tpu.obs.heartbeat import Heartbeater

__all__ = [
    "FlightRecorder",
    "configure",
    "get_recorder",
    "read_journal",
    "span",
    "span_aggregates",
    "reset_aggregates",
    "LivenessConfig",
    "LivenessTable",
    "WorkerState",
    "Heartbeater",
]
