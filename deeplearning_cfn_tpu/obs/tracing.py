"""Step tracing: wall-clock spans with per-name aggregates.

``with span("train_step"): ...`` times a block, folds the duration into
a process-wide per-name aggregate (count / total / max / last), and
records a ``span`` event on the flight recorder.  Spans measure *host*
wall time — under an async jax dispatch a ``train_step`` span covers
enqueue, not device execution; the trainer's metric-readback boundaries
are where device time surfaces (documented in docs/OBSERVABILITY.md).

Aggregates are what ``dlcfn status --format prom`` exports, so the
overhead budget is the train-step hot path: one perf_counter pair, one
dict update under a lock, one ring append.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Iterator

from deeplearning_cfn_tpu.obs.recorder import FlightRecorder, get_recorder


@dataclass
class SpanStats:
    """Running aggregate for one span name."""

    count: int = 0
    errors: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    last_s: float = 0.0

    def fold(self, seconds: float, ok: bool) -> None:
        self.count += 1
        if not ok:
            self.errors += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)
        self.last_s = seconds

    def as_dict(self) -> dict[str, Any]:
        return {
            "count": self.count,
            "errors": self.errors,
            "total_s": round(self.total_s, 6),
            "max_s": round(self.max_s, 6),
            "last_s": round(self.last_s, 6),
        }


_aggregates: dict[str, SpanStats] = {}
_lock = threading.Lock()


@contextmanager
def span(
    name: str, recorder: FlightRecorder | None = None, **attrs: Any
) -> Iterator[None]:
    """Time a block; journal it and fold it into the name's aggregate."""
    t0 = time.perf_counter()
    ok = True
    try:
        yield
    except BaseException:
        ok = False
        raise
    finally:
        seconds = time.perf_counter() - t0
        with _lock:
            stats = _aggregates.get(name)
            if stats is None:
                stats = _aggregates[name] = SpanStats()
            stats.fold(seconds, ok)
        (recorder or get_recorder()).record(
            "span", span=name, seconds=round(seconds, 6), ok=ok, **attrs
        )


def span_aggregates() -> dict[str, dict[str, Any]]:
    """Snapshot of every span name's running aggregate."""
    with _lock:
        return {name: stats.as_dict() for name, stats in _aggregates.items()}


def reset_aggregates() -> None:
    with _lock:
        _aggregates.clear()
