from deeplearning_cfn_tpu.cluster.queue import InMemoryQueue, Message, RendezvousQueue  # noqa: F401
from deeplearning_cfn_tpu.cluster.contract import ClusterContract  # noqa: F401
