"""On-VM bootstrap agent entry point — what every TPU VM runs at boot.

The cfn-init/UserData analog (deeplearning.template:490-516): the queued
resource's startup-script runs this module on every worker VM.  Role and
rendezvous come from instance metadata / env, not SSH pushes:

  DLCFN_CLUSTER          cluster name (required)
  DLCFN_WORKER_INDEX     this VM's index in the slice (0 = coordinator)
  DLCFN_BROKER           host:port of the rendezvous broker
  DLCFN_GROUPS           comma-separated worker-group names
  DLCFN_STORAGE_MOUNT    shared storage mount point
  DLCFN_BOOTSTRAP_BUDGET_S  wallclock budget (default 2700, the
                            reference's 3300-600; dl_cfn_setup_v2.py:411-415)

Worker 0 runs the coordinator role (waits for group-success, harvests IPs,
broadcasts the contract, signals ready); everyone else waits for the
broadcast.  Both end by writing the cluster contract locally, after which
the training job can `source env.sh` and `jax.distributed.initialize`.
"""

from __future__ import annotations

import os
import sys

from deeplearning_cfn_tpu.cluster.bootstrap import BootstrapAgent, BootstrapError
from deeplearning_cfn_tpu.cluster.broker_client import BrokerQueue
from deeplearning_cfn_tpu.utils.logging import get_logger
from deeplearning_cfn_tpu.utils.timeouts import BudgetExhausted, TimeoutBudget

log = get_logger("dlcfn.agent")


def _my_ip() -> str:
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    finally:
        s.close()


def main() -> int:
    cluster = os.environ.get("DLCFN_CLUSTER")
    if not cluster:
        log.error("DLCFN_CLUSTER not set; refusing to bootstrap")
        return 2
    index = int(os.environ.get("DLCFN_WORKER_INDEX", "0"))
    broker = os.environ.get("DLCFN_BROKER", "127.0.0.1:8477")
    host, port = broker.rsplit(":", 1)
    groups = os.environ.get("DLCFN_GROUPS", f"{cluster}-workers").split(",")
    budget_s = float(os.environ.get("DLCFN_BOOTSTRAP_BUDGET_S", "2700"))

    # The on-VM agent has no cloud-API backend: instance harvesting happens
    # on the controller side; the agent needs only the two queues.  A
    # null backend satisfies the coordinator's signal call by writing a
    # local marker the controller's poll picks up via the broker.
    from deeplearning_cfn_tpu.provision.local import LocalBackend

    backend = LocalBackend()

    agent = BootstrapAgent(
        backend=backend,
        cluster_name=cluster,
        coordinator_queue=BrokerQueue(f"{cluster}-coordinator-queue", host, int(port)),
        worker_queue=BrokerQueue(f"{cluster}-worker-queue", host, int(port)),
        group_names=groups,
        budget=TimeoutBudget(budget_s),
        storage_mount=os.environ.get("DLCFN_STORAGE_MOUNT", "/mnt/dlcfn"),
    )
    try:
        if index == 0 and os.environ.get("DLCFN_ROLE") == "coordinator":
            contract = agent.run_coordinator(_my_ip())
        else:
            contract = agent.run_worker()
    except (BootstrapError, BudgetExhausted) as e:
        log.error("bootstrap failed: %s", e)
        return 1
    log.info(
        "bootstrap complete: %d workers, I am process %d",
        contract.workers_count,
        index,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
