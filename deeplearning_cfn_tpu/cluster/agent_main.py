"""On-VM bootstrap agent entry point — what every TPU VM runs at boot.

The cfn-init/UserData analog (deeplearning.template:490-516): the queued
resource's startup-script (cluster/startup.py) execs this module on every
worker VM with the cluster identity in env:

  DLCFN_CLUSTER          cluster name (required)
  DLCFN_ROLE             coordinator | worker (default: coordinator iff
                         DLCFN_WORKER_INDEX == 0)
  DLCFN_WORKER_INDEX     this VM's index in its slice
  DLCFN_SLICE            this VM's slice ordinal (default 0); worker 0 of
                         slice 0 is the default coordinator, and the
                         readiness ack carries the slice's group name so
                         per-slice indices stay globally unique
  DLCFN_BROKER_TOKEN     shared-secret for the broker AUTH handshake
                         (stamped into VM metadata at provision; consumed
                         ambiently by every BrokerConnection)
  DLCFN_BROKER           host:port of the rendezvous broker (required —
                         without it the agent has no control plane)
  DLCFN_GROUPS           comma-separated worker-group names
  DLCFN_STORAGE_MOUNT    shared storage mount point
  DLCFN_BOOTSTRAP_BUDGET_S  wallclock budget (default 2700, the
                            reference's 3300-600; dl_cfn_setup_v2.py:411-415)
  DLCFN_POLL_INTERVAL_S  poll cadence (default 30, dl_cfn_setup_v2.py:36)
  DLCFN_MY_IP            coordinator address override; unset = resolve from
                         the harvested group state (worker 0's instance IP)
  DLCFN_ROOT             contract publication dir (default /opt/deeplearning)

The agent runs against :class:`BrokerAgentBackend`: group snapshots,
signals, and queues all come from the broker — a VM needs no cloud
credentials, mirroring how the reference's workers needed only SQS while
the master alone called EC2/ASG (dl_cfn_setup_v2.py:170-208 vs :210-281);
here even the coordinator's "describe" is served by controller-published
snapshots.  Worker 0 runs the coordinator role (waits for group-success,
reads harvested IPs, broadcasts the contract, signals ready); everyone else
waits for the broadcast.  Both end by writing the cluster contract locally,
after which the training job can `source env.sh` and
`jax.distributed.initialize`.
"""

from __future__ import annotations

import os
import sys

from deeplearning_cfn_tpu.cluster.bootstrap import (
    BootstrapAgent,
    BootstrapError,
    cluster_ready_resource,
)
from deeplearning_cfn_tpu.cluster.broker_backend import BrokerAgentBackend
from deeplearning_cfn_tpu.cluster.broker_client import BrokerError
from deeplearning_cfn_tpu.obs.aggregator import telemetry_source
from deeplearning_cfn_tpu.obs.blackbox import BlackBox
from deeplearning_cfn_tpu.obs.heartbeat import Heartbeater
from deeplearning_cfn_tpu.obs.recorder import get_recorder
from deeplearning_cfn_tpu.provision.backend import ResourceSignal
from deeplearning_cfn_tpu.utils.logging import get_logger
from deeplearning_cfn_tpu.utils.timeouts import BudgetExhausted, TimeoutBudget

log = get_logger("dlcfn.agent")


def main() -> int:
    cluster = os.environ.get("DLCFN_CLUSTER")
    if not cluster:
        log.error("DLCFN_CLUSTER not set; refusing to bootstrap")
        return 2
    broker = os.environ.get("DLCFN_BROKER")
    if not broker or ":" not in broker:
        log.error("DLCFN_BROKER not set (need host:port); refusing to bootstrap")
        return 2
    index = int(os.environ.get("DLCFN_WORKER_INDEX", "0"))
    slice_idx = int(os.environ.get("DLCFN_SLICE", "0") or "0")
    role = os.environ.get("DLCFN_ROLE") or (
        "coordinator" if index == 0 and slice_idx == 0 else "worker"
    )
    host, port = broker.rsplit(":", 1)
    groups = os.environ.get("DLCFN_GROUPS", f"{cluster}-workers").split(",")
    if not (0 <= slice_idx < len(groups)):
        # A silent fallback here would collide readiness acks across
        # slices and mask the misconfiguration; refuse to boot instead.
        log.error(
            "DLCFN_SLICE=%d out of range for DLCFN_GROUPS (%d groups); "
            "refusing to bootstrap", slice_idx, len(groups),
        )
        return 2
    # This VM's own group (slice): worker indices restart at 0 in every
    # slice, so the readiness ack must carry the group to stay unique.
    my_group = groups[slice_idx]
    min_slices_env = os.environ.get("DLCFN_MIN_SLICES", "").strip()
    min_slices = int(min_slices_env) if min_slices_env else None
    budget_s = float(os.environ.get("DLCFN_BOOTSTRAP_BUDGET_S", "2700"))
    poll_s = float(os.environ.get("DLCFN_POLL_INTERVAL_S", "30"))

    budget = TimeoutBudget(budget_s)
    # The broker (on the controller or coordinator host) may come up after
    # this VM boots; retry within the bootstrap budget instead of dying on
    # the first refused connection — the same discipline the reference
    # applied to IAM-credential availability (check_instance_role_availability,
    # dl_cfn_setup_v2.py:359-386).
    backend = None
    while True:
        try:
            backend = BrokerAgentBackend(host, int(port))
            coordinator_queue = backend.get_queue(f"{cluster}-coordinator-queue")
            worker_queue = backend.get_queue(f"{cluster}-worker-queue")
            break
        except OSError as e:
            if backend is not None:
                backend.close()
                backend = None
            log.info("broker at %s not reachable yet (%s); retrying", broker, e)
            try:
                budget.sleep(poll_s, "broker-connect")
            except BudgetExhausted:
                log.error("broker at %s unreachable within budget", broker)
                return 1

    # Liveness: beat at the broker from the moment the control plane is
    # reachable until the agent exits.  The supervisor's liveness watcher
    # (broker_service.BrokerLivenessWatcher) turns sustained silence into
    # an INSTANCE_TERMINATE — so a VM that wedges after connect is
    # detected even though it never reports an error.  Every beat also
    # piggybacks a TELEM snapshot (obs/aggregator.py) so the controller's
    # fleet merge and SLO rules see this host without any extra dial.
    worker_id = f"{my_group}/{index}"
    heartbeater = Heartbeater(
        host,
        int(port),
        worker_id=worker_id,
        telemetry_source=telemetry_source(
            worker_id,
            gauges=lambda: {"dlcfn_mesh_workers": 1.0},
        ),
    )
    heartbeater.start()

    # Crash blackbox: freeze the journal tail + resolved identity on a
    # fatal bootstrap error so `dlcfn postmortem` can reconstruct the
    # cross-host timeline even when this VM is reaped seconds later.
    blackbox = BlackBox(
        out_dir=os.environ.get("DLCFN_BLACKBOX_DIR", "/tmp/dlcfn-blackbox"),
        host=os.environ.get("DLCFN_WORKER") or worker_id.replace("/", "-"),
        worker=worker_id,
        config={
            "cluster": cluster,
            "group": my_group,
            "index": index,
            "role": role,
            "broker": broker,
        },
    )

    agent = BootstrapAgent(
        backend=backend,
        cluster_name=cluster,
        coordinator_queue=coordinator_queue,
        worker_queue=worker_queue,
        group_names=groups,
        budget=budget,
        poll_interval_s=poll_s,
        storage_mount=os.environ.get("DLCFN_STORAGE_MOUNT", "/mnt/dlcfn"),
        group_signal_resources={g: f"group:{g}" for g in groups},
        min_groups=min_slices,
    )
    try:
        if role == "coordinator":
            contract = agent.run_coordinator(os.environ.get("DLCFN_MY_IP"))
        else:
            contract = agent.run_worker()
            # Positive acknowledgment: the controller counts these so a
            # worker that silently died cannot be declared part of a ready
            # cluster.  (The reference never verified workers — only the
            # master signaled; StackSetup.md:107-108 documents the
            # resulting stale-metadata trap.  This closes it.)
            backend.get_queue(f"{cluster}-ready-queue").send(
                {
                    "event": "worker-ready",
                    "index": index,
                    "group": my_group,
                    "cluster": cluster,
                }
            )
    except (BootstrapError, BudgetExhausted) as e:
        log.error("bootstrap failed: %s", e)
        try:
            blackbox.capture(f"bootstrap-failed: {e}")
        except OSError:
            log.error("blackbox capture failed (disk?)")
        if role == "coordinator":
            # Fail the WaitCondition NOW so the controller rolls back within
            # one poll tick instead of burning the full cluster_ready budget
            # — the exit-1-drives-rollback semantics of the reference's
            # master (dl_cfn_setup_v2.py:426-428, deeplearning.template:769-780).
            try:
                backend.signal_resource(
                    cluster_ready_resource(cluster), ResourceSignal.FAILURE
                )
            except (OSError, BrokerError):
                log.error("could not signal FAILURE to broker")
        return 1
    finally:
        heartbeater.stop()
        backend.close()
    get_recorder().record(
        "bootstrap_complete",
        cluster=cluster,
        group=my_group,
        index=index,
        role=role,
        workers=contract.workers_count,
    )
    log.info(
        "bootstrap complete: %d workers, I am process %d (%s)",
        contract.workers_count,
        index,
        role,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
