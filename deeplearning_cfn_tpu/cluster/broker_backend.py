"""Broker-backed backend views: cross-process cluster state for real VMs.

Round-1's gap: the on-VM agent (`agent_main`) was handed a fresh in-memory
``LocalBackend``, so the coordinator role couldn't see group state and its
ready-signal landed in VM-local memory the controller never read.  The
reference never had this problem because both sides spoke to AWS: the master
polled ASG/EC2 APIs for instance state (dl_cfn_setup_v2.py:210-281) and
CloudFormation saw the cfn-signal (:286-298).

Here the native broker (native/broker/broker.cpp) plays the role of that
shared cloud state for everything the agents need at bootstrap time:

- **Signals** (WaitCondition / signal_resource analog): stored in the
  broker's KV under ``signal:{resource}``.  The coordinator's SUCCESS is
  visible to the controller process and vice versa.
- **Group-state snapshots** (describe-ASG / describe-instances analog): the
  controller — the only party with cloud-API credentials — polls its real
  backend and publishes each group as JSON under ``group-state:{name}``.
  Agents read the snapshot; they never need cloud credentials, exactly like
  TPU-VM workers that enumerate peers from metadata instead of calling GCE.

Two classes:

- :class:`BrokerAgentBackend` — what ``agent_main`` runs against on a VM:
  signals + group snapshots + queues, all via the broker.  Cloud mutation
  methods are unavailable by design (agents must not need credentials).
- :class:`BrokerRendezvousBackend` — the controller-side wrapper around a
  real backend (local or GCP): queues become broker queues, signals are
  written through to the broker AND the inner backend, and
  :meth:`publish_group_state` exports the inner backend's group view for
  agents to consume.
"""

from __future__ import annotations

import json
from typing import Any

from deeplearning_cfn_tpu.cluster.broker_client import BrokerConnection, BrokerQueue
from deeplearning_cfn_tpu.cluster.queue import RendezvousQueue
from deeplearning_cfn_tpu.provision.backend import (
    Backend,
    Instance,
    InstanceState,
    ResourceSignal,
    StorageHandle,
    WorkerGroup,
)
from deeplearning_cfn_tpu.utils.logging import get_logger

log = get_logger("dlcfn.broker_backend")

SIGNAL_KEY_FMT = "signal:{resource}"
GROUP_STATE_KEY_FMT = "group-state:{name}"


def serialize_group(group: WorkerGroup) -> bytes:
    return json.dumps(
        {
            "name": group.name,
            "desired": group.desired,
            "minimum": group.minimum,
            "chips_per_worker": group.chips_per_worker,
            "replace_unhealthy_suspended": group.replace_unhealthy_suspended,
            "instances": [
                {
                    "instance_id": i.instance_id,
                    "index": i.index,
                    "state": i.state.value,
                    "private_ip": i.private_ip,
                    "healthy": i.healthy,
                    "chips": i.chips,
                }
                for i in group.instances
            ],
        }
    ).encode()


def deserialize_group(raw: bytes) -> WorkerGroup:
    d = json.loads(raw.decode())
    return WorkerGroup(
        name=d["name"],
        desired=int(d["desired"]),
        minimum=int(d["minimum"]),
        chips_per_worker=int(d["chips_per_worker"]),
        replace_unhealthy_suspended=bool(d["replace_unhealthy_suspended"]),
        instances=[
            Instance(
                instance_id=i["instance_id"],
                group=d["name"],
                index=int(i["index"]),
                state=InstanceState(i["state"]),
                private_ip=i["private_ip"],
                healthy=bool(i["healthy"]),
                chips=int(i["chips"]),
            )
            for i in d["instances"]
        ],
    )


class BrokerAgentBackend(Backend):
    """The Backend view an on-VM bootstrap agent has: broker-only.

    No cloud credentials, no mutation of cloud resources — only the three
    capabilities the choreography needs on the VM side: read group
    snapshots, read/write signals, and speak to the rendezvous queues.
    """

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._conn = BrokerConnection(host, port)
        self._queues: dict[str, BrokerQueue] = {}

    # --- queues ---------------------------------------------------------
    def create_queue(self, name: str) -> RendezvousQueue:
        # Broker queues materialize on first use; create == get.
        return self.get_queue(name)

    def get_queue(self, name: str) -> RendezvousQueue:
        if name not in self._queues:
            self._queues[name] = BrokerQueue(name, self.host, self.port)
        return self._queues[name]

    # --- group state (read-only snapshots) ------------------------------
    def describe_group(self, name: str) -> WorkerGroup:
        raw = self._conn.get(GROUP_STATE_KEY_FMT.format(name=name))
        if raw is None:
            # Snapshot not published yet: return a placeholder that can
            # never satisfy the instances-active check, so the agent's
            # poll loop keeps waiting instead of crashing (the reference's
            # master likewise loops until describe succeeds,
            # dl_cfn_setup_v2.py:210-281).
            return WorkerGroup(name=name, desired=1, minimum=1, chips_per_worker=0)
        return deserialize_group(raw)

    def describe_instances(self, instance_ids: list[str]) -> list[Instance]:
        raise NotImplementedError(
            "agents read group snapshots, not instance APIs"
        )

    # --- signaling ------------------------------------------------------
    def signal_resource(self, resource: str, signal: ResourceSignal) -> None:
        self._conn.set(SIGNAL_KEY_FMT.format(resource=resource), signal.value.encode())

    def get_resource_signal(self, resource: str) -> ResourceSignal | None:
        raw = self._conn.get(SIGNAL_KEY_FMT.format(resource=resource))
        return ResourceSignal(raw.decode()) if raw is not None else None

    def close(self) -> None:
        self._conn.close()
        for q in self._queues.values():
            q.close()


class BrokerRendezvousBackend(Backend):
    """Controller-side wrapper: a real backend + broker-visible rendezvous.

    Delegates all cloud operations to ``inner`` while routing queues and
    signals through the broker so remote agents participate in the same
    choreography.  Signals are written through to BOTH stores: the inner
    backend remains the source of record for same-process reads (and, for
    the GCP backend, durable GCS markers), the broker makes them visible to
    VMs.  Reads prefer the broker (agents only ever write there).
    """

    def __init__(self, inner: Backend, host: str, port: int):
        self.inner = inner
        self.host = host
        self.port = port
        self._conn = BrokerConnection(host, port)
        self._queues: dict[str, BrokerQueue] = {}

    @property
    def events(self):  # type: ignore[override]
        return self.inner.events

    @property
    def clock(self):
        return getattr(self.inner, "clock", None)

    # --- queues: broker-hosted ------------------------------------------
    def create_queue(self, name: str) -> RendezvousQueue:
        return self.get_queue(name)

    def get_queue(self, name: str) -> RendezvousQueue:
        if name not in self._queues:
            self._queues[name] = BrokerQueue(name, self.host, self.port)
        return self._queues[name]

    # --- re-provision hygiene -------------------------------------------
    def reset_cluster_state(
        self, cluster_name: str, group_names: list[str], queue_names: list[str]
    ) -> None:
        """Clear every broker artifact a previous provision of this cluster
        name may have left behind: ready/failure signals, group signals and
        snapshots, and queued messages.  Without this, a recover() against
        a live broker would read the PREVIOUS cluster's SUCCESS signal and
        worker-setup broadcast and return a contract full of dead IPs —
        the broker, unlike CloudFormation's per-stack WaitCondition handle,
        is shared across cluster generations."""
        from deeplearning_cfn_tpu.cluster.bootstrap import cluster_ready_resource

        ready = cluster_ready_resource(cluster_name)
        self._conn.unset(SIGNAL_KEY_FMT.format(resource=ready))
        self.inner.clear_resource_signal(ready)
        for g in group_names:
            self._conn.unset(SIGNAL_KEY_FMT.format(resource=f"group:{g}"))
            self.inner.clear_resource_signal(f"group:{g}")
            self._conn.unset(GROUP_STATE_KEY_FMT.format(name=g))
        for q in queue_names:
            self.get_queue(q).purge()

    # --- group state: delegate + publish --------------------------------
    def publish_group_state(self, name: str) -> WorkerGroup:
        """Export the inner backend's current group view to the broker —
        the controller's describe-loop makes cloud state visible to
        credential-less agents (run on every poll tick).  Returns the
        group so callers can reuse the describe instead of re-issuing the
        cloud API read."""
        group = self.inner.describe_group(name)
        self._conn.set(GROUP_STATE_KEY_FMT.format(name=name), serialize_group(group))
        return group

    def create_group(self, name: str, desired: int, minimum: int, chips_per_worker: int) -> WorkerGroup:
        group = self.inner.create_group(name, desired, minimum, chips_per_worker)
        self.publish_group_state(name)
        return group

    def describe_group(self, name: str) -> WorkerGroup:
        return self.inner.describe_group(name)

    def describe_instances(self, instance_ids: list[str]) -> list[Instance]:
        return self.inner.describe_instances(instance_ids)

    def set_desired_capacity(self, group: str, desired: int) -> None:
        self.inner.set_desired_capacity(group, desired)
        self.publish_group_state(group)

    def suspend_replace_unhealthy(self, group: str) -> None:
        self.inner.suspend_replace_unhealthy(group)
        self.publish_group_state(group)

    def delete_group(self, name: str) -> None:
        self.inner.delete_group(name)

    # --- storage: delegate ----------------------------------------------
    def create_or_reuse_storage(
        self, kind: str, existing_id: str | None, mount_point: str, retain: bool
    ) -> StorageHandle:
        return self.inner.create_or_reuse_storage(kind, existing_id, mount_point, retain)

    def delete_storage(self, storage_id: str, force: bool = False) -> bool:
        return self.inner.delete_storage(storage_id, force=force)

    def storage_exists(self, storage_id: str, kind: str = "filestore") -> bool:
        return self.inner.storage_exists(storage_id, kind)

    # --- signaling: write-through, broker-preferred reads ----------------
    def signal_resource(self, resource: str, signal: ResourceSignal) -> None:
        self._conn.set(SIGNAL_KEY_FMT.format(resource=resource), signal.value.encode())
        self.inner.signal_resource(resource, signal)

    def get_resource_signal(self, resource: str) -> ResourceSignal | None:
        raw = self._conn.get(SIGNAL_KEY_FMT.format(resource=resource))
        if raw is not None:
            return ResourceSignal(raw.decode())
        return self.inner.get_resource_signal(resource)

    def clear_resource_signal(self, resource: str) -> None:
        self._conn.unset(SIGNAL_KEY_FMT.format(resource=resource))
        self.inner.clear_resource_signal(resource)

    # --- passthrough for backend extras (kill_instance etc.) -------------
    def __getattr__(self, item: str) -> Any:
        return getattr(self.inner, item)
