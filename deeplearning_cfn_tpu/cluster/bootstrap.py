"""Bootstrap / discovery agent — runs on every worker at startup.

TPU-native rebuild of cfn-bootstrap/dl_cfn_setup_v2.py.  Every worker VM in
a slice runs this same agent; role is decided by worker index (worker 0 is
the coordinator — the "master is also worker #1" rule, StackSetup.md:110-111)
rather than an AWS_DL_NODE_TYPE env var.

Coordinator phases (dl_cfn_setup_v2.py:389-436):

1. ``wait_for_credentials`` — poll until the platform identity is usable
   (check_instance_role_availability, :359-386).
2. ``wait_for_group_success`` — poll the coordinator queue until a
   ``group-setup`` success message is seen for EVERY registered group,
   deduping at-least-once redelivery by group name (:123-168, dedup
   :142-149); consumed messages are deleted (:150).
3. ``wait_until_instances_active`` — poll the backend until every healthy
   instance is RUNNING and has an IP (:210-281).
4. Build + publish the contract, broadcast ``worker-setup`` on the worker
   queue (:346-357), and signal the cluster WaitCondition (:286-298).

Worker phases: wait for the broadcast with ``visibility_timeout=0`` and
never delete it so one message reaches all workers (:170-208, trick
:180-190), then publish the same contract locally.

All waits draw from one :class:`TimeoutBudget`
(setup_timeout = cluster_ready - controller_launch, :411-415), and each
phase raises a typed error naming itself on exhaustion — the analog of the
per-phase error exits (:309-311, 327-329, 426-428).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

from deeplearning_cfn_tpu.cluster.contract import ClusterContract
from deeplearning_cfn_tpu.cluster.elasticity import GROUP_SETUP_EVENT
from deeplearning_cfn_tpu.cluster.queue import RendezvousQueue
from deeplearning_cfn_tpu.provision.backend import Backend, InstanceState, ResourceSignal
from deeplearning_cfn_tpu.utils.logging import get_logger
from deeplearning_cfn_tpu.utils.timeouts import TimeoutBudget

log = get_logger("dlcfn.bootstrap")

def cluster_ready_resource(cluster_name: str) -> str:
    """Per-cluster WaitCondition resource name — namespaced so clusters
    sharing a backend cannot read each other's ready/failure signals."""
    return f"cluster-ready:{cluster_name}"


class BootstrapError(RuntimeError):
    def __init__(self, phase: str, message: str):
        super().__init__(f"[{phase}] {message}")
        self.phase = phase


@dataclass
class GroupSetupResult:
    group: str
    launched: int
    degraded: bool


@dataclass
class BootstrapAgent:
    backend: Backend
    cluster_name: str
    coordinator_queue: RendezvousQueue
    worker_queue: RendezvousQueue
    group_names: list[str]
    budget: TimeoutBudget
    poll_interval_s: float = 30.0
    storage_mount: str = "/mnt/dlcfn"
    contract_root: Path | None = None
    # group -> signal-resource name; must match GroupPolicy.signal_resource
    # registered with the controller (provisioner wires both sides).
    group_signal_resources: dict[str, str] | None = None
    credential_probe: Callable[[], bool] = lambda: True
    # SQS batch size from the reference (dl_cfn_setup_v2.py:36-37,139-141)
    receive_batch: int = 10
    visibility_timeout_s: float = 60.0
    # Multi-slice degrade policy: None = every group must succeed (a group
    # FAILURE aborts bootstrap); an int = the cluster proceeds as long as
    # at least this many groups (slices) succeed, DROPPING failed ones from
    # the contract — the slice-granularity shape of degrade-and-continue
    # (a TPU slice fails whole, unlike an ASG that shrinks;
    # lambda_function.py:142-169 is the per-instance original).
    min_groups: int | None = None
    failed_groups: set[str] = field(default_factory=set)

    # --- phase 1: credentials -------------------------------------------
    def wait_for_credentials(self) -> None:
        phase = "credentials"
        while not self.credential_probe():
            log.info("platform credentials not yet available; retrying")
            self.budget.sleep(self.poll_interval_s, phase)
        self.budget.check(phase)

    # --- phase 2: group success messages (coordinator) -------------------
    def wait_for_group_success(self) -> dict[str, GroupSetupResult]:
        phase = "group-success"
        pending = set(self.group_names)
        results: dict[str, GroupSetupResult] = {}
        while pending:
            self.budget.check(phase)
            # Fail fast if the controller already rendered a FAILURE verdict
            # (below-minimum capacity) — the definitive signal is on the
            # group resource; waiting out the whole budget would burn ~45
            # real minutes for an answer that is already known.
            signal_names = self.group_signal_resources or {}
            for name in list(pending):
                if (
                    self.backend.get_resource_signal(
                        signal_names.get(name, f"group:{name}")
                    )
                    is ResourceSignal.FAILURE
                ):
                    self._record_group_failure(phase, name)
                    pending.discard(name)
            messages = self.coordinator_queue.receive(
                max_messages=self.receive_batch,
                visibility_timeout_s=self.visibility_timeout_s,
            )
            for msg in messages:
                body = msg.body
                if body.get("event") != GROUP_SETUP_EVENT:
                    log.info("ignoring non-setup message: %s", body.get("event"))
                    self.coordinator_queue.delete(msg.receipt)
                    continue
                group = body.get("group")
                if group in results:
                    # At-least-once redelivery: dedup by group name
                    # (dl_cfn_setup_v2.py:142-149).
                    log.info("duplicate group-setup for %s deduped", group)
                elif group in pending:
                    if body.get("status") != "success":
                        self._record_group_failure(
                            phase,
                            str(group),
                            f"reported {body.get('status')!r}",
                        )
                        pending.discard(str(group))
                        self.coordinator_queue.delete(msg.receipt)
                        continue
                    results[group] = GroupSetupResult(
                        group=str(group),
                        launched=int(body.get("launched", 0)),
                        degraded=bool(body.get("degraded", False)),
                    )
                    pending.discard(str(group))
                    log.info(
                        "group %s ready (launched=%d degraded=%s); %d group(s) pending",
                        group,
                        results[str(group)].launched,
                        results[str(group)].degraded,
                        len(pending),
                    )
                else:
                    log.info("group-setup for unknown group %s ignored", group)
                self.coordinator_queue.delete(msg.receipt)
            if pending:
                self.budget.sleep(self.poll_interval_s, phase)
        return results

    def _record_group_failure(
        self, phase: str, name: str, cause: str = "failed to reach minimum capacity"
    ) -> None:
        """A group (slice) failed: abort unless the min_groups policy says
        the cluster can proceed without it.

        The coordinator slice (group_names[0]) is always required — it
        hosts the agent running this very choreography, so it cannot be
        dropped (the reference has the same asymmetry: the master ASG's
        CreationPolicy fails the stack if the master doesn't launch,
        deeplearning.template:669-674, while worker capacity degrades)."""
        self.failed_groups.add(name)
        surviving = len(self.group_names) - len(self.failed_groups)
        if (
            name == self.group_names[0]
            or self.min_groups is None
            or surviving < self.min_groups
        ):
            raise BootstrapError(phase, f"group {name} {cause}")
        log.warning(
            "dropping failed slice %s (%s); %d/%d slices remain (min %d)",
            name, cause, surviving, len(self.group_names), self.min_groups,
        )

    @property
    def surviving_groups(self) -> list[str]:
        return [g for g in self.group_names if g not in self.failed_groups]

    # --- phase 3: instances active ---------------------------------------
    def wait_until_instances_active(self) -> dict[str, list[str]]:
        """Poll until every healthy instance of every group is RUNNING with
        an IP; returns {group: [ips]} (dl_cfn_setup_v2.py:210-281)."""
        phase = "instances-active"
        ips: dict[str, list[str]] = {}
        while True:
            self.budget.check(phase)
            ips.clear()
            all_running = True
            for name in self.surviving_groups:
                group = self.backend.describe_group(name)
                healthy = group.healthy_instances
                running = [
                    i
                    for i in healthy
                    if i.state is InstanceState.RUNNING and i.private_ip
                ]
                if len(running) < group.desired:
                    all_running = False
                    log.info(
                        "group %s: %d/%d running", name, len(running), group.desired
                    )
                    break
                ips[name] = [i.private_ip for i in running if i.private_ip]
            if all_running:
                return ips
            self.budget.sleep(self.poll_interval_s, phase)

    # --- phase 4: contract + broadcast + signal ---------------------------
    def _publish_contract(self, contract: ClusterContract) -> None:
        contract.write(self.contract_root)

    def run_coordinator(self, my_ip: str | None = None) -> ClusterContract:
        """Run the master role.  ``my_ip=None`` resolves the coordinator's
        address from the harvested group state: worker 0 = the lowest-index
        instance of the first group (the master-is-also-worker-#1 rule,
        dl_cfn_setup_v2.py:330-342) — on a real slice that IS this VM, and
        it is the address every peer will dial, which matters more than
        what a local socket probe reports."""
        self.wait_for_credentials()
        results = self.wait_for_group_success()
        ips_by_group = self.wait_until_instances_active()
        surviving = self.surviving_groups
        if my_ip is None:
            group0 = self.backend.describe_group(surviving[0])
            me = min(
                (
                    i
                    for i in group0.healthy_instances
                    if i.state is InstanceState.RUNNING and i.private_ip
                ),
                key=lambda i: i.index,
                default=None,
            )
            if me is None or me.private_ip is None:
                raise BootstrapError(
                    "contract", "cannot resolve coordinator IP from group state"
                )
            my_ip = me.private_ip
        all_ips = [ip for name in surviving for ip in ips_by_group[name]]
        degraded = any(r.degraded for r in results.values()) or bool(
            self.failed_groups
        )
        chips = max(
            self.backend.describe_group(name).chips_per_worker
            for name in surviving
        )
        contract = ClusterContract.build(
            cluster_name=self.cluster_name,
            coordinator_ip=my_ip,
            other_worker_ips=all_ips,
            chips_per_worker=chips,
            storage_mount=self.storage_mount,
            degraded=degraded,
            # Slice topology (multi-slice only): lets compute derive the
            # hybrid ICI x DCN mesh from the contract alone.
            slices=(
                {name: ips_by_group[name] for name in surviving}
                if len(self.group_names) > 1
                else None
            ),
        )
        self._publish_contract(contract)
        self.worker_queue.send(contract.to_message())
        self.backend.signal_resource(
            cluster_ready_resource(self.cluster_name), ResourceSignal.SUCCESS
        )
        log.info(
            "cluster %s ready: %d workers x %d chips%s",
            self.cluster_name,
            contract.workers_count,
            contract.chips_per_worker,
            " (DEGRADED)" if degraded else "",
        )
        return contract

    def run_worker(self) -> ClusterContract:
        self.wait_for_credentials()
        phase = "worker-setup"
        while True:
            self.budget.check(phase)
            # visibility_timeout=0 + no delete of the broadcast: the trick
            # that lets one worker-setup message reach every worker
            # (dl_cfn_setup_v2.py:180-190).  Scan a full batch so a stray
            # message at the head of the queue cannot shadow the broadcast
            # forever; strays are deleted (worker-setup is the only message
            # type ever broadcast on this queue, so junk is junk for every
            # consumer).
            messages = self.worker_queue.receive(
                max_messages=self.receive_batch, visibility_timeout_s=0.0
            )
            for msg in messages:
                if msg.body.get("event") == "worker-setup":
                    contract = ClusterContract.from_message(msg.body)
                    self._publish_contract(contract)
                    return contract
                log.info("deleting stray message %s on worker queue", msg.body.get("event"))
                self.worker_queue.delete(msg.receipt)
            self.budget.sleep(self.poll_interval_s, phase)
