"""Client for the native rendezvous broker (native/broker/broker.cpp).

``BrokerQueue`` implements the same :class:`RendezvousQueue` interface as
the in-memory queue, over the broker's TCP line protocol — so the
provisioner, bootstrap agents, and elasticity controller run unchanged
against the production transport.  ``BrokerProcess`` builds (via make) and
supervises a local broker instance; on a TPU deployment the broker runs on
the coordinator VM and workers connect to
``$DEEPLEARNING_COORDINATOR_HOST:<port>``.
"""

from __future__ import annotations

import json
import shutil
import socket
import subprocess
import uuid
import zlib
from pathlib import Path
from typing import Any

from deeplearning_cfn_tpu.cluster.queue import Message, RendezvousQueue
from deeplearning_cfn_tpu.obs.tracing import span
from deeplearning_cfn_tpu.utils.logging import get_logger
from deeplearning_cfn_tpu.utils.resilience import (
    CircuitBreaker,
    RetryExhausted,
    RetryPolicy,
)
from deeplearning_cfn_tpu.utils.timeouts import (
    BudgetExhausted,
    Clock,
    MonotonicClock,
    TimeoutBudget,
)

log = get_logger("dlcfn.broker")


def _traced(method):
    """Wrap an RPC method in a ``rpc.<name>`` span (obs flight journal)."""
    import functools

    span_name = f"rpc.{method.__name__}"

    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        with span(span_name):
            return method(self, *args, **kwargs)

    return wrapper


BROKER_DIR = Path(__file__).resolve().parents[2] / "native" / "broker"
BROKER_BIN = BROKER_DIR / "dlcfn-broker"


def shard_for_key(key: str, n_shards: int) -> int:
    """The broker keyspace hash ring: which shard owns ``key``.

    CRC32 rather than Python's ``hash()`` — the ring must be stable
    across processes, restarts, and languages (PYTHONHASHSEED randomizes
    ``hash()`` per interpreter), because the router, the sim fleet, and
    any future C++ client must all agree on placement.  Queues, KV keys,
    and heartbeat worker ids all route through this one function."""
    if n_shards < 1:
        raise ValueError(f"need at least one shard, got {n_shards}")
    return zlib.crc32(key.encode("utf-8")) % n_shards


class BrokerError(RuntimeError):
    pass


class BrokerFenced(BrokerError):
    """A replication write was rejected by epoch fencing: the sender is a
    deposed primary and must stop streaming (docs/RESILIENCE.md)."""

    def __init__(self, epoch: int, seq: int):
        super().__init__(
            f"replication fenced: epoch {epoch} is stale (entry seq {seq})"
        )
        self.epoch = epoch
        self.seq = seq


class BrokerTimeout(BrokerError, TimeoutError):
    """The broker did not become reachable within the readiness budget."""

    def __init__(self, timeout_s: float, last: BaseException | None = None):
        super().__init__(
            f"broker did not become reachable within {timeout_s:.1f}s"
            + (f" (last error: {last})" if last is not None else "")
        )
        self.timeout_s = timeout_s
        self.last = last


def await_broker_ready(
    probe,
    timeout_s: float = 5.0,
    clock: Clock | None = None,
    poll_interval_s: float = 0.05,
) -> None:
    """Poll ``probe()`` until it stops raising OSError, bounded by a
    monotonic deadline.

    The unified-policy port of the old bare ``time.sleep(0.05)`` loop:
    attempts draw from one :class:`TimeoutBudget` on an injectable clock,
    and exhaustion raises the typed :class:`BrokerTimeout` instead of a
    generic error (callers can distinguish "never came up" from protocol
    failures).
    """
    clock = clock or MonotonicClock()
    policy = RetryPolicy(
        # The budget is the real bound; size the attempt ceiling so the
        # policy can never give up before the deadline does.
        max_attempts=max(2, int(timeout_s / max(poll_interval_s, 1e-6)) + 1),
        base_s=poll_interval_s,
        cap_s=max(poll_interval_s * 5, poll_interval_s),
        clock=clock,
        seed=0,
        retryable=(OSError,),
    )
    budget = TimeoutBudget(timeout_s, clock=clock)
    try:
        policy.call(probe, budget=budget, phase="broker-ready")
    except (BudgetExhausted, RetryExhausted) as err:
        last = getattr(err, "last", None) or err
        raise BrokerTimeout(timeout_s, last) from err


class BrokerConnection:
    """One TCP connection speaking the broker line protocol.

    ``token``: shared-secret for the AUTH handshake (the IAM-gating
    analog of the reference's SQS control plane,
    deeplearning.template:193-197).  Defaults to $DLCFN_BROKER_TOKEN —
    the ambient channel the cluster contract stamps on VMs — so every
    existing construction site authenticates without plumbing changes.
    Pass an explicit token to override (controller-side callers read it
    from the broker record)."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = 10.0,
        token: str | None = None,
    ):
        import os

        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b""
        if token is None:
            token = os.environ.get("DLCFN_BROKER_TOKEN") or None
        if token:
            # A failed handshake must not leak the connected socket: an
            # agent's bootstrap retry loop would otherwise accumulate one
            # fd per attempt until EMFILE masks the real auth failure.
            try:
                if any(c.isspace() for c in token):
                    raise BrokerError(
                        "broker token must not contain whitespace"
                    )
                self.sock.sendall(f"AUTH {token}\n".encode())
                resp = self._read_line()
                if resp != "OK":
                    raise BrokerError(f"broker AUTH rejected: {resp}")
            except BaseException:
                self.close()
                raise

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _read_line(self) -> str:
        while b"\n" not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise BrokerError("broker closed connection")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\n", 1)
        return line.decode()

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise BrokerError("broker closed connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    @_traced
    def ping(self) -> bool:
        self.sock.sendall(b"PING\n")
        return self._read_line() == "PONG"

    @_traced
    def send(self, queue: str, body: bytes) -> str:
        self.sock.sendall(f"SEND {queue} {len(body)}\n".encode() + body)
        resp = self._read_line()
        if not resp.startswith("OK "):
            raise BrokerError(f"SEND failed: {resp}")
        return resp[3:]

    @_traced
    def receive(self, queue: str, max_messages: int, visibility_ms: int) -> list[tuple[str, str, int, bytes]]:
        self.sock.sendall(f"RECV {queue} {max_messages} {visibility_ms}\n".encode())
        header = self._read_line()
        if not header.startswith("N "):
            raise BrokerError(f"RECV failed: {header}")
        out = []
        for _ in range(int(header[2:])):
            mline = self._read_line().split(" ")
            if mline[0] != "MSG":
                raise BrokerError(f"bad MSG frame: {mline}")
            _, mid, receipt, count, length = mline
            out.append((mid, receipt, int(count), self._read_exact(int(length))))
        return out

    @_traced
    def delete(self, queue: str, receipt: str) -> bool:
        self.sock.sendall(f"DEL {queue} {receipt}\n".encode())
        resp = self._read_line()
        if resp == "OK":
            return True
        if resp == "MISS":
            return False
        # A standby's "ERR not primary" must surface as an error the
        # failover wrapper can classify, not as a silent MISS.
        raise BrokerError(f"DEL failed: {resp}")

    @_traced
    def depth(self, queue: str) -> int:
        self.sock.sendall(f"DEPTH {queue}\n".encode())
        resp = self._read_line()
        if not resp.startswith("OK "):
            raise BrokerError(f"DEPTH failed: {resp}")
        return int(resp[3:])

    @_traced
    def purge(self, queue: str) -> None:
        self.sock.sendall(f"PURGE {queue}\n".encode())
        resp = self._read_line()
        if resp != "OK":
            raise BrokerError(f"PURGE failed: {resp}")

    # --- shared KV (signals + group-state snapshots) ---------------------
    @_traced
    def set(self, key: str, value: bytes) -> None:
        self.sock.sendall(f"SET {key} {len(value)}\n".encode() + value)
        resp = self._read_line()
        if resp != "OK":
            raise BrokerError(f"SET failed: {resp}")

    @_traced
    def get(self, key: str) -> bytes | None:
        self.sock.sendall(f"GET {key}\n".encode())
        resp = self._read_line()
        if resp == "NONE":
            return None
        if not resp.startswith("VAL "):
            raise BrokerError(f"GET failed: {resp}")
        return self._read_exact(int(resp[4:]))

    @_traced
    def unset(self, key: str) -> bool:
        self.sock.sendall(f"UNSET {key}\n".encode())
        resp = self._read_line()
        if resp == "OK":
            return True
        if resp == "MISS":
            return False
        raise BrokerError(f"UNSET failed: {resp}")

    # --- liveness (obs plane) --------------------------------------------
    @_traced
    def heartbeat(self, worker_id: str) -> int:
        """Record a beat for ``worker_id``; returns its beat count."""
        if not worker_id or any(c.isspace() for c in worker_id):
            raise BrokerError(f"bad heartbeat worker id: {worker_id!r}")
        self.sock.sendall(f"HEARTBEAT {worker_id}\n".encode())
        resp = self._read_line()
        if not resp.startswith("OK "):
            raise BrokerError(f"HEARTBEAT failed: {resp}")
        return int(resp[3:])

    @_traced
    def heartbeats(self) -> dict[str, tuple[float, int]]:
        """Dump the broker's beat table: worker -> (age_s, beat count)."""
        self.sock.sendall(b"HEARTBEAT\n")
        header = self._read_line()
        if not header.startswith("N "):
            raise BrokerError(f"HEARTBEAT dump failed: {header}")
        out: dict[str, tuple[float, int]] = {}
        for _ in range(int(header[2:])):
            hline = self._read_line().split(" ")
            if hline[0] != "HB" or len(hline) != 4:
                raise BrokerError(f"bad HB frame: {hline}")
            _, worker, age_ms, count = hline
            out[worker] = (int(age_ms) / 1000.0, int(count))
        return out

    # --- fleet telemetry (obs plane) --------------------------------------
    @_traced
    def telem(self, worker_id: str, snapshot: bytes) -> int:
        """Record ``worker_id``'s latest telemetry snapshot (last-write-
        wins, like a beat with a payload); returns its snapshot count."""
        if not worker_id or any(c.isspace() for c in worker_id):
            raise BrokerError(f"bad telemetry worker id: {worker_id!r}")
        self.sock.sendall(
            f"TELEM {worker_id} {len(snapshot)}\n".encode() + snapshot
        )
        resp = self._read_line()
        if not resp.startswith("OK "):
            raise BrokerError(f"TELEM failed: {resp}")
        return int(resp[3:])

    @_traced
    def telemetry(self) -> dict[str, tuple[float, int, bytes]]:
        """Dump the broker's telemetry table: worker ->
        (age_s, snapshot count, latest snapshot bytes)."""
        self.sock.sendall(b"TELEM\n")
        header = self._read_line()
        if not header.startswith("N "):
            raise BrokerError(f"TELEM dump failed: {header}")
        out: dict[str, tuple[float, int, bytes]] = {}
        for _ in range(int(header[2:])):
            tline = self._read_line().split(" ")
            if tline[0] != "TM" or len(tline) != 5:
                raise BrokerError(f"bad TM frame: {tline}")
            _, worker, age_ms, count, length = tline
            payload = self._read_exact(int(length))
            out[worker] = (int(age_ms) / 1000.0, int(count), payload)
        return out

    # --- replication / leader handover (docs/RESILIENCE.md) --------------
    @_traced
    def send_idempotent(self, queue: str, body: bytes, rid: str) -> str:
        """Enqueue with an idempotency key: re-sending the same ``rid``
        (the at-least-once re-send after a failover) enqueues at most
        once — the rid doubles as the message id."""
        if not rid or any(c.isspace() for c in rid):
            raise BrokerError(f"bad idempotency key: {rid!r}")
        self.sock.sendall(f"SENDID {queue} {rid} {len(body)}\n".encode() + body)
        resp = self._read_line()
        if not resp.startswith("OK "):
            raise BrokerError(f"SENDID failed: {resp}")
        return resp[3:]

    @_traced
    def role(self) -> tuple[str, int, int]:
        """The peer's (role, epoch, replication position).  Position is
        entries journaled for a primary, entries applied for a standby —
        primary minus standby is the replication lag in entries."""
        self.sock.sendall(b"ROLE\n")
        rline = self._read_line().split(" ")
        if rline[0] != "ROLE" or len(rline) != 4:
            raise BrokerError(f"bad ROLE frame: {rline}")
        _, role_name, epoch, seq = rline
        return role_name, int(epoch), int(seq)

    @_traced
    def promote(self, epoch: int) -> int:
        """Fence the peer to ``epoch`` and make it primary.  The epoch
        must exceed the peer's current one (the promotion ladder)."""
        self.sock.sendall(f"PROMOTE {epoch}\n".encode())
        resp = self._read_line()
        if not resp.startswith("OK "):
            raise BrokerError(f"PROMOTE failed: {resp}")
        return int(resp[3:])

    @_traced
    def sync_entry(self, epoch: int, seq: int, frame: bytes) -> int:
        """Replicate one journal frame to a standby.  Raises
        :class:`BrokerFenced` when the receiver's epoch is newer — this
        sender has been deposed and must stop streaming."""
        self.sock.sendall(f"SYNC {epoch} {seq} {len(frame)}\n".encode() + frame)
        resp = self._read_line()
        if resp.startswith("ERR fenced"):
            raise BrokerFenced(epoch, seq)
        if not resp.startswith("OK "):
            raise BrokerError(f"SYNC failed: {resp}")
        return int(resp[3:])

    @_traced
    def shard(self) -> tuple[int, int]:
        """The peer's (shard index, total shards) on the keyspace ring;
        (0, 1) for an unsharded broker.  Lets a router verify it dialed
        the owner of the keys it is about to route."""
        self.sock.sendall(b"SHARD\n")
        sline = self._read_line().split(" ")
        if sline[0] != "SHARD" or len(sline) != 3:
            raise BrokerError(f"bad SHARD frame: {sline}")
        _, shard, n_shards = sline
        return int(shard), int(n_shards)


def endpoints_from_record(record: dict) -> list[tuple[str, int]]:
    """The failover endpoint list a broker record file publishes.

    Replicated records carry ``endpoints`` (primary first, standby
    after); legacy single-process records only have host/port."""
    eps: list[tuple[str, int]] = []
    for ep in record.get("endpoints") or []:
        host, port = ep
        eps.append((str(host), int(port)))
    primary = (str(record["host"]), int(record["port"]))
    if primary not in eps:
        eps.insert(0, primary)
    return eps


class FailoverBrokerConnection:
    """Broker client that fails over across replica endpoints.

    Holds one live connection to the current leader.  A connection-level
    failure (dial refused, peer died mid-RPC, a standby's ``ERR not
    primary``) records a failure on THAT endpoint's breaker and moves to
    the next endpoint whose breaker admits a call; endpoints whose
    breaker is open are skipped (breaker-open is a failover trigger, not
    a dead end).  The first successful RPC after a switch journals
    ``broker_failover`` and resets the new endpoint's breaker — outage
    classification stays endpoint-local, so a clean failover never counts
    against a shared outage budget (docs/RESILIENCE.md "Broker
    failover").

    At-least-once safety: ``send`` goes through SENDID with a request id
    generated once per logical send, so the re-send after a primary dies
    mid-RPC (applied but unacked) cannot double-enqueue.  Every other
    verb is idempotent (reads, last-write-wins KV, receipt-keyed acks) or
    at-least-once by design (RECV leases).

    ``dial(host, port)`` is the connection seam: tests and the
    virtual-clock soak inject simulated connections; the default dials a
    real :class:`BrokerConnection` with this instance's token.

    ``endpoints_source`` (optional, ``() -> [(host, port), ...]``) is
    re-read once per RPC after every construction-time endpoint has been
    refused: after a failover the adoption ladder REWRITES the broker
    record (promoted primary first, auto-re-provisioned standby after),
    so a client started before the failover finds the fresh pair without
    a restart instead of walking dead endpoints forever.
    """

    _ENDPOINT_ERROR_HINTS = ("closed connection", "not primary")

    def __init__(
        self,
        endpoints,
        token: str | None = None,
        dial=None,
        breaker_factory=None,
        clock: Clock | None = None,
        max_cycles: int = 2,
        timeout_s: float = 10.0,
        endpoints_source=None,
    ):
        if not endpoints:
            raise BrokerError("failover connection needs at least one endpoint")
        self._endpoints = [(str(h), int(p)) for h, p in endpoints]
        self._token = token
        self._timeout_s = timeout_s
        self._clock = clock or MonotonicClock()
        if dial is None:

            def dial(host: str, port: int):
                return BrokerConnection(
                    host, port, timeout_s=self._timeout_s, token=self._token
                )

        self._dial = dial
        if breaker_factory is None:

            def breaker_factory(host: str, port: int) -> CircuitBreaker:
                return CircuitBreaker(
                    name=f"broker-endpoint:{host}:{port}",
                    failure_threshold=3,
                    reset_after_s=5.0,
                    clock=self._clock,
                )

        self._breaker_factory = breaker_factory
        self._breakers = {ep: breaker_factory(*ep) for ep in self._endpoints}
        self._endpoints_source = endpoints_source
        self._conn = None
        self._active = 0
        self._established: tuple[str, int] | None = None
        self._max_cycles = max_cycles
        self.failovers = 0
        self.endpoint_refreshes = 0

    @property
    def active_endpoint(self) -> tuple[str, int]:
        return self._endpoints[self._active]

    def breaker(self, endpoint) -> CircuitBreaker:
        host, port = endpoint
        return self._breakers[(str(host), int(port))]

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def _is_endpoint_failure(self, exc: BaseException) -> bool:
        if isinstance(exc, (ConnectionError, OSError)):
            return True
        if isinstance(exc, BrokerError):
            text = str(exc)
            return any(hint in text for hint in self._ENDPOINT_ERROR_HINTS)
        return False

    def _next_allowed(self) -> int | None:
        n = len(self._endpoints)
        for step in range(n):
            idx = (self._active + step) % n
            if self._breakers[self._endpoints[idx]].allow():
                return idx
        return None

    def _refresh_endpoints(self) -> bool:
        """Re-read the endpoint list from ``endpoints_source`` (the
        rewritten broker record after adoption/re-provisioning).  Returns
        whether the list actually changed; breakers for surviving
        endpoints keep their failure history, new endpoints start
        closed."""
        if self._endpoints_source is None:
            return False
        try:
            fresh = [
                (str(h), int(p)) for h, p in (self._endpoints_source() or [])
            ]
        except Exception as exc:
            log.warning("broker endpoint refresh failed: %s", exc)
            return False
        if not fresh or fresh == self._endpoints:
            return False
        self.close()
        self._breakers = {
            ep: self._breakers.get(ep) or self._breaker_factory(*ep)
            for ep in fresh
        }
        self._endpoints = fresh
        self._active = 0
        self.endpoint_refreshes += 1
        return True

    def _call(self, rpc: str, op):
        last: BaseException | None = None
        # Second pass only after a refresh actually changed the endpoint
        # list: every known endpoint was refused, so re-read the record —
        # adoption may have replaced the pair since this client started.
        for attempt_pass in range(2):
            if attempt_pass and not self._refresh_endpoints():
                break
            attempts = len(self._endpoints) * self._max_cycles
            for _ in range(attempts):
                idx = self._next_allowed()
                if idx is None:
                    break
                endpoint = self._endpoints[idx]
                try:
                    if self._conn is None or idx != self._active:
                        self.close()
                        self._conn = self._dial(*endpoint)
                        self._active = idx
                    result = op(self._conn)
                except BaseException as exc:
                    if not self._is_endpoint_failure(exc):
                        raise
                    last = exc
                    self._breakers[endpoint].record_failure()
                    self.close()
                    self._active = (idx + 1) % len(self._endpoints)
                    continue
                if (
                    self._established is not None
                    and endpoint != self._established
                ):
                    # A successful switch is a failover, not an outage:
                    # reset the adopted endpoint's breaker and journal the
                    # event instead of feeding any shared failure budget.
                    self.failovers += 1
                    from deeplearning_cfn_tpu.obs.recorder import get_recorder

                    get_recorder().record(
                        "broker_failover",
                        rpc=rpc,
                        from_host=self._established[0],
                        from_port=self._established[1],
                        to_host=endpoint[0],
                        to_port=endpoint[1],
                    )
                self._breakers[endpoint].record_success()
                self._established = endpoint
                return result
        raise BrokerError(
            f"{rpc}: no broker endpoint available (endpoints "
            f"{self._endpoints}, last error: {last})"
        ) from last

    # -- the BrokerConnection surface, failover-wrapped -------------------
    def ping(self) -> bool:
        return self._call("ping", lambda c: c.ping())

    def send(self, queue: str, body: bytes, rid: str | None = None) -> str:
        rid = rid or uuid.uuid4().hex  # dlcfn: noqa[DLC601] idempotency key for a real client: must be unique across processes, so entropy is the point; sims pass explicit rids
        return self._call("send", lambda c: c.send_idempotent(queue, body, rid))

    def send_idempotent(self, queue: str, body: bytes, rid: str) -> str:
        return self._call(
            "send_idempotent", lambda c: c.send_idempotent(queue, body, rid)
        )

    def receive(self, queue: str, max_messages: int, visibility_ms: int):
        return self._call(
            "receive", lambda c: c.receive(queue, max_messages, visibility_ms)
        )

    def delete(self, queue: str, receipt: str) -> bool:
        return self._call("delete", lambda c: c.delete(queue, receipt))

    def depth(self, queue: str) -> int:
        return self._call("depth", lambda c: c.depth(queue))

    def purge(self, queue: str) -> None:
        return self._call("purge", lambda c: c.purge(queue))

    def set(self, key: str, value: bytes) -> None:
        return self._call("set", lambda c: c.set(key, value))

    def get(self, key: str) -> bytes | None:
        return self._call("get", lambda c: c.get(key))

    def unset(self, key: str) -> bool:
        return self._call("unset", lambda c: c.unset(key))

    def heartbeat(self, worker_id: str) -> int:
        return self._call("heartbeat", lambda c: c.heartbeat(worker_id))

    def heartbeats(self) -> dict[str, tuple[float, int]]:
        return self._call("heartbeats", lambda c: c.heartbeats())

    def telem(self, worker_id: str, snapshot: bytes) -> int:
        return self._call("telem", lambda c: c.telem(worker_id, snapshot))

    def telemetry(self) -> dict[str, tuple[float, int, bytes]]:
        return self._call("telemetry", lambda c: c.telemetry())

    def role(self) -> tuple[str, int, int]:
        return self._call("role", lambda c: c.role())

    def shard(self) -> tuple[int, int]:
        return self._call("shard", lambda c: c.shard())


class ShardedBrokerRouter:
    """Shard-aware broker client over N independent primary/standby pairs.

    Hashes every queue/key/worker id on the production ring
    (:func:`shard_for_key`) and drives THAT shard's
    :class:`FailoverBrokerConnection` — per-endpoint CircuitBreakers,
    idempotent SENDID re-sends, and record-refresh failover all stay
    endpoint-local, so a single shard's failover stalls only the keys
    that hash there while the other shards' traffic flows untouched.

    ``shard_endpoints`` is a list (index = shard) of endpoint lists;
    ``shard_endpoint_sources`` optionally supplies a per-shard
    ``endpoints_source`` callable (normally a closure over that shard's
    record file) so long-lived routers survive adoption rewrites.
    Table-dump reads (``heartbeats``/``telemetry``) merge every
    reachable shard and skip shards mid-failover — the merged-view
    contract the liveness watcher expects."""

    def __init__(
        self,
        shard_endpoints,
        token: str | None = None,
        dial=None,
        breaker_factory=None,
        clock: Clock | None = None,
        timeout_s: float = 10.0,
        shard_endpoint_sources=None,
    ):
        if not shard_endpoints:
            raise BrokerError("sharded router needs at least one shard")
        if shard_endpoint_sources is not None and len(
            shard_endpoint_sources
        ) != len(shard_endpoints):
            raise BrokerError(
                "shard_endpoint_sources must match shard_endpoints"
            )
        self.n_shards = len(shard_endpoints)
        self._conns = [
            FailoverBrokerConnection(
                endpoints,
                token=token,
                dial=dial,
                breaker_factory=breaker_factory,
                clock=clock,
                timeout_s=timeout_s,
                endpoints_source=(
                    shard_endpoint_sources[k]
                    if shard_endpoint_sources is not None
                    else None
                ),
            )
            for k, endpoints in enumerate(shard_endpoints)
        ]

    @classmethod
    def for_cluster(
        cls, cluster_name: str, root=None, **kwargs
    ) -> "ShardedBrokerRouter":
        """Build a router from a recorded sharded deployment: per-shard
        endpoints come from each shard's record file, and each shard's
        ``endpoints_source`` re-reads that record so adoption rewrites
        are picked up live."""
        from deeplearning_cfn_tpu.cluster import broker_service

        shard_map = broker_service.sharded_broker_records(cluster_name, root)
        if shard_map is None:
            raise BrokerError(
                f"no sharded broker recorded for {cluster_name}"
            )
        endpoints: list[list[tuple[str, int]]] = []
        sources = []
        token = None
        for entry in shard_map:
            record = entry.get("record")
            if record is None:
                raise BrokerError(
                    f"shard {entry.get('shard')} of {cluster_name} has no "
                    "live record"
                )
            token = token or record.get("token")
            endpoints.append(endpoints_from_record(record))

            def source(name=entry["cluster"]):
                rec = broker_service.broker_status(name, root)
                return endpoints_from_record(rec) if rec else []

            sources.append(source)
        kwargs.setdefault("token", token)
        return cls(endpoints, shard_endpoint_sources=sources, **kwargs)

    @property
    def failovers(self) -> int:
        return sum(conn.failovers for conn in self._conns)

    def shard_index(self, key: str) -> int:
        return shard_for_key(key, self.n_shards)

    def connection(self, key: str) -> FailoverBrokerConnection:
        """The failover connection owning ``key``'s shard."""
        return self._conns[self.shard_index(key)]

    def shard_connections(self) -> list[FailoverBrokerConnection]:
        return list(self._conns)

    def close(self) -> None:
        for conn in self._conns:
            conn.close()

    # -- key-routed verbs -------------------------------------------------
    def ping(self) -> bool:
        return all(conn.ping() for conn in self._conns)

    def send(self, queue: str, body: bytes, rid: str | None = None) -> str:
        return self.connection(queue).send(queue, body, rid)

    def send_idempotent(self, queue: str, body: bytes, rid: str) -> str:
        return self.connection(queue).send_idempotent(queue, body, rid)

    def receive(self, queue: str, max_messages: int, visibility_ms: int):
        return self.connection(queue).receive(
            queue, max_messages, visibility_ms
        )

    def delete(self, queue: str, receipt: str) -> bool:
        return self.connection(queue).delete(queue, receipt)

    def depth(self, queue: str) -> int:
        return self.connection(queue).depth(queue)

    def purge(self, queue: str) -> None:
        return self.connection(queue).purge(queue)

    def set(self, key: str, value: bytes) -> None:
        return self.connection(key).set(key, value)

    def get(self, key: str) -> bytes | None:
        return self.connection(key).get(key)

    def unset(self, key: str) -> bool:
        return self.connection(key).unset(key)

    def heartbeat(self, worker_id: str) -> int:
        return self.connection(worker_id).heartbeat(worker_id)

    def telem(self, worker_id: str, snapshot: bytes) -> int:
        return self.connection(worker_id).telem(worker_id, snapshot)

    # -- merged table dumps ----------------------------------------------
    def heartbeats(self) -> dict[str, tuple[float, int]]:
        merged: dict[str, tuple[float, int]] = {}
        for conn in self._conns:
            try:
                merged.update(conn.heartbeats())
            except BrokerError:
                continue  # shard mid-failover: only ITS slice goes dark
        return merged

    def telemetry(self) -> dict[str, tuple[float, int, bytes]]:
        merged: dict[str, tuple[float, int, bytes]] = {}
        for conn in self._conns:
            try:
                merged.update(conn.telemetry())
            except BrokerError:
                continue
        return merged


class BrokerQueue(RendezvousQueue):
    """RendezvousQueue over the native broker."""

    def __init__(
        self,
        name: str,
        host: str = "127.0.0.1",
        port: int = 8477,
        token: str | None = None,
    ):
        self.name = name
        self._conn = BrokerConnection(host, port, token=token)

    def send(self, body: dict[str, Any]) -> str:
        return self._conn.send(self.name, json.dumps(body).encode())

    def receive(
        self, max_messages: int = 10, visibility_timeout_s: float = 60.0
    ) -> list[Message]:
        raw = self._conn.receive(
            self.name, max_messages, int(visibility_timeout_s * 1000)
        )
        return [
            Message(
                message_id=mid,
                body=json.loads(payload.decode()),
                receipt=receipt,
                receive_count=count,
            )
            for mid, receipt, count, payload in raw
        ]

    def delete(self, receipt: str) -> None:
        self._conn.delete(self.name, receipt)

    def purge(self) -> None:
        self._conn.purge(self.name)

    def approximate_depth(self) -> int:
        return self._conn.depth(self.name)

    def close(self) -> None:
        self._conn.close()


def build_broker(force: bool = False) -> Path:
    """Compile the broker with make (idempotent)."""
    if BROKER_BIN.exists() and not force:
        return BROKER_BIN
    if shutil.which("make") is None or shutil.which("g++") is None:
        raise BrokerError("make/g++ not available to build the broker")
    # Bounded: a wedged compiler must fail the provision step, not hang it.
    subprocess.run(
        ["make", "-C", str(BROKER_DIR)],
        check=True,
        capture_output=True,
        timeout=600,
    )
    return BROKER_BIN


class BrokerProcess:
    """Build + spawn + supervise a local broker (ephemeral port by default).

    ``token``: spawn the broker with AUTH required (via env, never argv —
    /proc cmdline is world-readable)."""

    def __init__(
        self,
        port: int = 0,
        token: str | None = None,
        ready_timeout_s: float = 5.0,
        clock: Clock | None = None,
    ):
        import os

        build_broker()
        self.token = token
        env = dict(os.environ)
        env.pop("DLCFN_BROKER_TOKEN", None)
        if token:
            env["DLCFN_BROKER_TOKEN"] = token
        self.proc = subprocess.Popen(
            [str(BROKER_BIN), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        assert self.proc.stdout is not None
        line = self.proc.stdout.readline()
        if "listening on" not in line:
            raise BrokerError(f"broker failed to start: {line!r}")
        self.port = int(line.strip().rsplit(" ", 1)[-1])

        # Wait until accepting, on a monotonic budget with a typed
        # timeout (BrokerTimeout) instead of the old unbounded-feeling
        # bare-sleep spin.
        def _probe() -> None:
            conn = BrokerConnection("127.0.0.1", self.port, timeout_s=1.0)
            try:
                conn.ping()
            finally:
                conn.close()

        await_broker_ready(_probe, timeout_s=ready_timeout_s, clock=clock)

    def queue(self, name: str) -> BrokerQueue:
        return BrokerQueue(name, "127.0.0.1", self.port, token=self.token)

    def stop(self) -> None:
        self.proc.terminate()
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.proc.kill()

    def __enter__(self) -> "BrokerProcess":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
