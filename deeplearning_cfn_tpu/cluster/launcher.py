"""Job launcher — from a ready cluster to a running SPMD training job.

Replaces the reference's two launch paths (SURVEY §3.5): the Horovod/mpirun
path (run.sh builds a hostfile, SSH-warms every node, computes
NUM_PARALLEL = workers x gpus, then execs ``mpirun -np`` with transport
tuning, run.sh:46-95) and the TF-PS path (generate_trainer.py writing
per-host scripts with ps/worker topology, generate_trainer.py:19-76).

TPU-native, both collapse into one shape: **every worker runs the same
program**.  The launcher's job is therefore (a) enforcing invariants up
front exactly where run.sh:43-44 did, (b) rendering the per-worker launch
plan (command + env derived from the cluster contract — no SSH fan-out,
workers pick it up from their metadata/startup script), and (c) for the
local backend, executing the program in-process over a virtual mesh.
"""

from __future__ import annotations

import shlex
from dataclasses import dataclass, field

from deeplearning_cfn_tpu.cluster.contract import ClusterContract
from deeplearning_cfn_tpu.config.schema import JobSpec
from deeplearning_cfn_tpu.utils.logging import get_logger

log = get_logger("dlcfn.launch")


class LaunchError(RuntimeError):
    pass


@dataclass
class WorkerLaunch:
    process_id: int
    host: str
    command: str
    env: dict[str, str]


@dataclass
class LaunchPlan:
    job_name: str
    workers: list[WorkerLaunch]
    num_parallel: int  # workers x chips — NUM_PARALLEL (run.sh:56)
    steps_per_epoch: int | None

    def render_script(self, process_id: int) -> str:
        """A per-worker launch script — the {host}.sh analog
        (generate_trainer.py:64-76), env-driven instead of SSH-pushed."""
        w = self.workers[process_id]
        lines = ["#!/bin/bash", "set -euo pipefail"]
        lines += [f"export {k}={shlex.quote(v)}" for k, v in sorted(w.env.items())]
        lines.append(w.command)
        return "\n".join(lines) + "\n"


def build_launch_plan(
    contract: ClusterContract,
    job: JobSpec,
    job_violation: str | None = None,
) -> LaunchPlan:
    """Validate invariants and render the all-workers launch plan."""
    # Invariants checked just before launch, as run.sh:43-44 checked the
    # worker count right before mpirun.
    if job_violation:
        raise LaunchError(
            f"job invalid on the realized cluster: {job_violation}. "
            "Adjust global_batch_size or recreate the cluster at full size."
        )
    n = contract.workers_count
    if job.require_even_workers and n != 1 and n % 2:
        raise LaunchError(f"worker count must be 1 or even, got {n}")
    if job.global_batch_size % contract.total_chips:
        raise LaunchError(
            f"global_batch_size {job.global_batch_size} not divisible by "
            f"{contract.total_chips} chips"
        )

    num_parallel = contract.total_chips
    steps = (
        max(1, job.steps_per_epoch_numerator // num_parallel)
        if job.steps_per_epoch_numerator
        else None
    )

    args = " ".join(
        f"--{k} {shlex.quote(str(v))}" for k, v in sorted(job.args.items())
    )
    workers = []
    for pid, host in enumerate(contract.hostnames()):
        env = dict(contract.env())
        env["DLCFN_PROCESS_ID"] = str(pid)
        env["DLCFN_JOB_NAME"] = job.name
        workers.append(
            WorkerLaunch(
                process_id=pid,
                host=host,
                command=f"python -m {job.module} {args}".strip(),
                env=env,
            )
        )
    plan = LaunchPlan(
        job_name=job.name,
        workers=workers,
        num_parallel=num_parallel,
        steps_per_epoch=steps,
    )
    log.info(
        "launch plan %s: %d workers, NUM_PARALLEL=%d, steps/epoch=%s",
        job.name,
        n,
        num_parallel,
        steps,
    )
    return plan


@dataclass
class LocalJobRunner:
    """Executes a launch plan in-process over the virtual device mesh —
    the local backend's stand-in for every TPU VM running its copy."""

    plan: LaunchPlan
    results: list = field(default_factory=list)

    def run(self, entrypoint, *args, **kwargs):
        """Run the job's entrypoint once (single-controller semantics:
        the virtual mesh spans all 'workers')."""
        return entrypoint(*args, **kwargs)
