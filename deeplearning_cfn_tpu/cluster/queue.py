"""Rendezvous queues — the control-plane transport.

The reference's control plane is two SQS queues: the *master queue* carries
Lambda -> master lifecycle events, and the *worker queue* carries the
master -> workers cluster-contract broadcast (SURVEY §2.4).  Three SQS
behaviors are load-bearing and are reproduced exactly here:

1. **At-least-once delivery** — consumers must dedup; the reference dedups
   asg-setup messages by ASG name (dl_cfn_setup_v2.py:142-149).
2. **Visibility timeout** — a received message becomes invisible for N
   seconds, then reappears unless deleted (receive args at
   dl_cfn_setup_v2.py:139-141: batch of 10, visibility 60 s).
3. **The broadcast trick** — receiving with ``visibility_timeout=0`` and
   never deleting lets one message fan out to every worker
   (dl_cfn_setup_v2.py:180-190).

On TPU deployments the same interface is served by the native C++ broker
(native/broker) over TCP, or by a GCS-object mailbox; the in-memory
implementation backs unit tests and the local backend.
"""

from __future__ import annotations

import itertools
import json
import threading
from dataclasses import dataclass, field
from typing import Any

from deeplearning_cfn_tpu.utils.timeouts import Clock, MonotonicClock


@dataclass
class Message:
    message_id: str
    body: dict[str, Any]
    receipt: str
    receive_count: int = 1


class RendezvousQueue:
    """Abstract queue with SQS-compatible semantics."""

    name: str

    def send(self, body: dict[str, Any]) -> str:
        raise NotImplementedError

    def receive(
        self,
        max_messages: int = 10,
        visibility_timeout_s: float = 60.0,
    ) -> list[Message]:
        raise NotImplementedError

    def delete(self, receipt: str) -> None:
        raise NotImplementedError

    def purge(self) -> None:
        raise NotImplementedError


@dataclass
class _Stored:
    message_id: str
    body: dict[str, Any]
    enqueued_seq: int
    invisible_until: float = 0.0
    receive_count: int = 0
    receipts: set[str] = field(default_factory=set)


class InMemoryQueue(RendezvousQueue):
    """Thread-safe in-memory queue with visibility-timeout semantics.

    ``duplicate_next_send`` simulates SQS at-least-once duplication so tests
    can prove consumers dedup correctly.
    """

    def __init__(self, name: str, clock: Clock | None = None):
        self.name = name
        self._clock = clock or MonotonicClock()
        self._lock = threading.Lock()
        self._messages: dict[str, _Stored] = {}
        self._duplicate_next_send = False
        # Counter-derived ids, not uuid4: chaos scenarios replay this
        # queue twice per seed and diff report bytes, so every id a
        # fresh instance mints must be identical run over run.  In-queue
        # uniqueness is all SQS semantics need (delete-by-receipt and
        # visibility are per queue; consumers dedup by body content).
        self._seq = itertools.count()
        self._mids = itertools.count(1)

    @property
    def duplicate_next_send(self) -> bool:
        with self._lock:
            return self._duplicate_next_send

    @duplicate_next_send.setter
    def duplicate_next_send(self, value: bool) -> None:
        # Tests arm this from the main thread while worker threads are
        # mid-send; route the write through the queue lock so the flag
        # cannot be torn between send()'s read and clear.
        with self._lock:
            self._duplicate_next_send = bool(value)

    def send(self, body: dict[str, Any]) -> str:
        # Bodies must be JSON-serializable: the wire protocol is JSON, as in
        # the reference (lambda_function.py:51-62, dl_cfn_setup_v2.py:346-357).
        json.dumps(body)
        with self._lock:
            # The backing field, not the property: the lock is already
            # held and threading.Lock does not re-enter.
            copies = 2 if self._duplicate_next_send else 1
            self._duplicate_next_send = False
            mid = ""
            for _ in range(copies):
                mid = f"{self.name}-m{next(self._mids):06d}"
                self._messages[mid] = _Stored(
                    message_id=mid,
                    body=json.loads(json.dumps(body)),
                    enqueued_seq=next(self._seq),
                )
            return mid

    def receive(
        self,
        max_messages: int = 10,
        visibility_timeout_s: float = 60.0,
    ) -> list[Message]:
        now = self._clock.now()
        out: list[Message] = []
        with self._lock:
            visible = sorted(
                (m for m in self._messages.values() if m.invisible_until <= now),
                key=lambda m: m.enqueued_seq,
            )
            for stored in visible[:max_messages]:
                stored.receive_count += 1
                stored.invisible_until = now + max(visibility_timeout_s, 0.0)
                # Unique per (message, receive): receive_count was just
                # incremented under the lock, and the mid prefix keeps
                # receipts distinct across messages.
                receipt = f"{stored.message_id}-r{stored.receive_count}"
                stored.receipts.add(receipt)
                out.append(
                    Message(
                        message_id=stored.message_id,
                        body=json.loads(json.dumps(stored.body)),
                        receipt=receipt,
                        receive_count=stored.receive_count,
                    )
                )
        return out

    def delete(self, receipt: str) -> None:
        with self._lock:
            for mid, stored in list(self._messages.items()):
                if receipt in stored.receipts:
                    del self._messages[mid]
                    return
        # Deleting an unknown receipt is a no-op, as in SQS.

    def purge(self) -> None:
        with self._lock:
            self._messages.clear()

    def approximate_depth(self) -> int:
        with self._lock:
            return len(self._messages)
