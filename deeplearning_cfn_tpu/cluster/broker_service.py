"""The rendezvous broker as a STACK RESOURCE.

The reference's control-plane queues are CloudFormation resources — created
with the stack, deleted with it, never a manual pre-step
(deeplearning.template:743-754).  Round 2 shipped the broker binary and the
agents that dial it, but ``create`` still assumed an operator had started a
broker somewhere routable.  This module closes that gap: ``dlcfn create
--broker auto`` (and run/recover) calls :func:`ensure_broker`, which

- reuses a live broker previously recorded for this cluster (idempotent,
  like CloudFormation's no-op update for an unchanged resource),
- otherwise builds + spawns ``native/broker/dlcfn-broker`` as a DETACHED
  process that outlives the CLI (the stack outlives ``create``),
- health-checks it (PING) before any queued-resource creation happens, and
- records ``{host, port, pid}`` under the contract root so ``dlcfn
  delete`` can tear it down with the cluster (:func:`teardown_broker`).

Topology: the broker runs on the operator/controller host — the GCE-VM
analog of the reference's regional SQS endpoint — and its address is
stamped into TPU VM metadata exactly as an explicit ``--broker HOST:PORT``
would be (provision/gcp.py broker_host).  ``advertise`` selects the address
written to the record: loopback for the local/dev backend, this host's
routable IP (or an explicit override) for real clusters.

Exposure: the broker is bound to loopback plus the advertise interface
only (and this host's outbound interface when the advertise address is a
non-local NAT/public IP, since that is where forwarded traffic actually
arrives) — never all interfaces.  An unauthenticated rendezvous plane
must not listen on interfaces no cluster VM dials.
"""

from __future__ import annotations

import json
import os
import re
import signal
import socket
import subprocess
import time
from pathlib import Path
from typing import Sequence

from deeplearning_cfn_tpu.cluster.broker_client import (
    BROKER_BIN,
    BrokerConnection,
    BrokerError,
    build_broker,
)
from deeplearning_cfn_tpu.cluster.contract import ClusterContract
from deeplearning_cfn_tpu.obs.liveness import (
    LivenessConfig,
    LivenessTable,
    WorkerState,
)
from deeplearning_cfn_tpu.obs.recorder import get_recorder, read_journal
from deeplearning_cfn_tpu.utils.logging import get_logger

log = get_logger("dlcfn.broker")

_LISTENING = re.compile(r"listening on (\d+)")


def _record_path(cluster_name: str, root: Path | None = None) -> Path:
    root = root or ClusterContract.root_dir()
    return root / "broker" / f"{cluster_name}.json"


def _standby_record_path(cluster_name: str, root: Path | None = None) -> Path:
    root = root or ClusterContract.root_dir()
    return root / "broker" / f"{cluster_name}.standby.json"


def _repl_log_path(cluster_name: str, root: Path | None = None) -> Path:
    """The primary's replication journal: flight-recorder JSONL
    (``kind: broker_apply``) appended by the broker binary for every
    state mutation it applies, tailed by :class:`ReplicationStreamer`."""
    root = root or ClusterContract.root_dir()
    return root / "broker" / f"{cluster_name}.repl.jsonl"


def _standby_repl_log_path(
    cluster_name: str, root: Path | None = None
) -> Path:
    """The STANDBY's copy of the journal: every SYNC entry it applies is
    re-journaled at the entry's own seq/epoch, so after a promotion the
    adopter renames this file over :func:`_repl_log_path` and replication
    resumes from the promoted node's journal (self-healing pair)."""
    root = root or ClusterContract.root_dir()
    return root / "broker" / f"{cluster_name}.standby.repl.jsonl"


def shard_cluster_name(cluster_name: str, shard: int) -> str:
    """The per-shard internal cluster name: shard ``k`` of ``cluster`` is
    recorded, locked, logged, and journaled as ``cluster.shard<k>`` —
    every single-pair code path (spawn/adopt/teardown/status) applies to
    a shard unchanged."""
    return f"{cluster_name}.shard{shard}"


def _shard_map_path(cluster_name: str, root: Path | None = None) -> Path:
    """The shard-map record: which per-shard cluster names make up a
    sharded deployment, in ring order."""
    root = root or ClusterContract.root_dir()
    return root / "broker" / f"{cluster_name}.shards.json"


def detect_host_ip() -> str:
    """This host's outbound IP — the address a TPU VM would dial.  The
    UDP-connect trick never sends a packet; the fallback is loopback
    (dev boxes with no route)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def _alive(host: str, port: int, timeout_s: float = 2.0) -> bool:
    # token="" suppresses the AUTH handshake (PING is deliberately
    # unauthenticated): liveness must be checkable before the record —
    # and therefore the token — exists, and must not fail on a stale
    # ambient DLCFN_BROKER_TOKEN.
    try:
        conn = BrokerConnection(host, port, timeout_s=timeout_s, token="")
        try:
            return conn.ping()
        finally:
            conn.close()
    except (OSError, BrokerError):
        return False


def broker_token(cluster_name: str, root: Path | None = None) -> str | None:
    """The shared secret of the cluster's recorded broker, or None (open
    broker from an older record).  The record file is operator-only
    (0600); VMs receive the token through instance metadata, the channel
    the reference used for IAM-scoped credentials."""
    rec = _record_path(cluster_name, root)
    try:
        return json.loads(rec.read_text()).get("token") or None
    except (OSError, ValueError):
        return None


def _write_record(rec: Path, payload: dict) -> None:
    """Write the broker record operator-only: it carries the AUTH token,
    which must never be world-readable on a shared host — not even for
    the umask window between create and chmod.  A fresh 0600 inode is
    created and atomically renamed over the record, so readers see
    either the old record or the new one, never a partial write or a
    permissive mode."""
    tmp = rec.with_suffix(".tmp")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(json.dumps(payload))
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise
    os.replace(tmp, rec)


def _bind_addresses(advertise: str | None) -> str:
    """The comma-separated bind list handed to the broker binary: loopback
    (liveness probes + the local/dev backend) plus the advertise interface.
    A non-local advertise address (operator NAT/public IP) cannot be bound
    — the binary skips it — so the host's outbound interface is included
    too, which is where NAT-forwarded traffic actually arrives."""
    addrs = ["127.0.0.1"]
    if advertise and advertise not in addrs:
        addrs.append(advertise)
        host_ip = detect_host_ip()
        if host_ip not in addrs:
            addrs.append(host_ip)
    return ",".join(addrs)


def broker_status(cluster_name: str, root: Path | None = None) -> dict | None:
    """The recorded broker for a cluster, plus liveness — or None.

    Liveness is probed on LOOPBACK: the broker always runs on this host
    (loopback is always in its bind list); the recorded ``host`` is only
    the address VMs dial, which may be a NAT/public IP not locally
    routable — probing it would misread a live broker as dead and spawn a
    leaked duplicate."""
    rec = _record_path(cluster_name, root)
    try:
        data = json.loads(rec.read_text())
    except (FileNotFoundError, json.JSONDecodeError):
        return None
    data["alive"] = _alive("127.0.0.1", int(data["port"]))
    return data


def standby_broker_status(
    cluster_name: str, root: Path | None = None
) -> dict | None:
    """The recorded warm-standby replica for a cluster, plus liveness —
    or None.  Loopback probe, same rationale as :func:`broker_status`."""
    srec = _standby_record_path(cluster_name, root)
    try:
        data = json.loads(srec.read_text())
    except (FileNotFoundError, json.JSONDecodeError):
        return None
    data["alive"] = _alive("127.0.0.1", int(data["port"]))
    return data


def _adopt_standby(
    cluster_name: str,
    root: Path | None,
    dead_record: dict,
    rec: Path,
) -> tuple[str, int, bool] | None:
    """Promote a live warm standby over a dead primary's record.

    The promotion ladder (docs/RESILIENCE.md "Broker failover"): fence the
    standby to ``max(dead primary's epoch, standby's epoch) + 1`` with
    PROMOTE — strictly above any term the deposed primary could still be
    streaming under — then rewrite the PRIMARY record file to point at it
    and unlink the standby record (it is a standby no more).  Returns
    ``(host, port, False)`` like a reuse, or None when no LIVE standby
    exists; a stale standby record is unlinked here so it cannot shadow
    the dead primary (the single-process-singleton bug this replaces).

    Self-healing (docs/RESILIENCE.md "Sharded broker"): the promoted
    node's own journal copy is renamed over the primary journal path
    (its repl fd follows the inode, so post-promotion appends continue
    in place), a FRESH standby is re-provisioned, and the journal is
    replayed into it — a failover never leaves a degraded pair as steady
    state.  Re-provisioning is best-effort: a failure degrades to the
    pre-heal behavior (promoted primary, no standby) rather than failing
    the adoption.
    """
    srec = _standby_record_path(cluster_name, root)
    try:
        standby = json.loads(srec.read_text())
    except (OSError, ValueError):
        return None
    if not _alive("127.0.0.1", int(standby["port"])):
        log.warning(
            "standby broker record for %s (pid %s) is stale; removing it",
            cluster_name, standby.get("pid"),
        )
        srec.unlink(missing_ok=True)
        return None
    token = standby.get("token") or dead_record.get("token") or ""
    conn = BrokerConnection(
        "127.0.0.1", int(standby["port"]), timeout_s=5.0, token=token
    )
    try:
        _, standby_epoch, repl_seq = conn.role()
        new_epoch = max(
            int(dead_record.get("epoch", 0) or 0), standby_epoch
        ) + 1
        conn.promote(new_epoch)
    finally:
        conn.close()
    host = standby.get("host") or dead_record.get("host") or "127.0.0.1"
    port = int(standby["port"])
    record_payload = {
        "cluster": cluster_name,
        "host": host,
        "port": port,
        "pid": int(standby["pid"]),
        "binds": standby.get("binds", dead_record.get("binds", "")),
        "binds_requested": standby.get(
            "binds_requested", dead_record.get("binds_requested", "")
        ),
        "token": token or None,
        "role": "primary",
        "epoch": new_epoch,
        "endpoints": [[host, port]],
        "started_ts": standby.get("started_ts", time.time()),
    }
    for key in ("shard", "n_shards"):
        if key in dead_record:
            record_payload[key] = dead_record[key]
    _write_record(rec, record_payload)
    srec.unlink(missing_ok=True)
    # The promoted node journaled every entry it acked into its own copy;
    # rename it over the primary journal path so its repl fd (which
    # follows the inode) keeps appending there and the streamer resumes
    # from the promoted node's journal.  The dead primary's journal — and
    # with it any unshipped tail that died with the process — is replaced.
    standby_repl = _standby_repl_log_path(cluster_name, root)
    repl_log = _repl_log_path(cluster_name, root)
    if standby_repl.exists():
        os.replace(standby_repl, repl_log)
    else:
        repl_log.unlink(missing_ok=True)
    log.warning(
        "promoted standby broker for %s at %s:%d (pid %s, epoch %d, "
        "replayed seq %d)",
        cluster_name, host, port, standby.get("pid"), new_epoch, repl_seq,
    )
    get_recorder().record(
        "broker_promoted",
        cluster=cluster_name,
        broker_host=host,
        broker_port=port,
        epoch=new_epoch,
        repl_seq=repl_seq,
    )
    # Self-heal: re-provision a FRESH standby and replay the journal into
    # it, so broker_replication_status never reports a degraded pair as
    # steady state.  Best-effort — the promoted primary is already
    # serving; a heal failure is logged and retried by the next ensure.
    try:
        sb_host, sb_port, _ = ensure_standby_broker(cluster_name, root=root)
        streamer = ReplicationStreamer(cluster_name, root=root)
        replayed = streamer.step()
        get_recorder().record(
            "standby_reprovisioned",
            cluster=cluster_name,
            broker_host=sb_host,
            broker_port=sb_port,
            epoch=new_epoch,
            replayed=replayed,
        )
        log.warning(
            "re-provisioned standby broker for %s at %s:%d (%d journal "
            "entries replayed)",
            cluster_name, sb_host, sb_port, replayed,
        )
    except (OSError, BrokerError) as exc:
        log.warning(
            "standby re-provision for %s failed (pair stays degraded "
            "until the next ensure): %s",
            cluster_name, exc,
        )
    return host, port, False


def ensure_broker(
    cluster_name: str,
    root: Path | None = None,
    advertise: str | None = None,
    port: int = 0,
    timeout_s: float = 30.0,
    extra_binds: Sequence[str] | None = None,
    reuse_token: str | None = None,
    reuse_epoch: int | None = None,
    shard: int | None = None,
    n_shards: int | None = None,
) -> tuple[str, int, bool]:
    """Return ``(host, port, started)`` for a live broker serving this
    cluster, starting one (detached) if none is recorded and reachable.

    ``extra_binds``: additional interfaces to bind beyond what
    ``advertise`` implies — the restart path passes the PREVIOUS broker's
    requested binds here so the replacement serves the union.  Without
    the union, two concurrent CLIs passing different advertise addresses
    would ping-pong: each restart binds only its own advertise, which
    re-fails the other CLI's reuse check, which restarts again.

    ``shard``/``n_shards``: the keyspace-ring stamp for a per-shard pair
    spawned by :func:`ensure_sharded_broker` — written to the record and
    the binary's SHARD identity; None for an unsharded broker."""
    rec = _record_path(cluster_name, root)

    def reuse_live(record: dict) -> tuple[str, int, bool] | None:
        """Return a live recorded broker, rewriting the advertised host
        when the caller passes a different one — the record's host is only
        the address VMs dial; an operator re-running with a (corrected)
        advertise address must not be silently held to the old one.  Used
        by BOTH reuse paths (uncontended and lock-contention wait), so a
        ``create --broker-advertise X`` racing a concurrent ``run`` cannot
        come back with the other process's advertise address.

        Returns None when the rewrite needs interfaces the running broker
        never bound (its bind set is fixed at spawn): handing VMs an
        address nothing listens on would hang bootstrap with connection
        refusals.  The caller restarts the broker with the right binds."""
        host = record["host"]
        if advertise is not None and advertise != host:
            # Records from before binds were narrowed carry no bind list;
            # those brokers bound all interfaces, so any rewrite is safe.
            # The comparison is against the REQUESTED set (what the old
            # broker attempted), not the actual binds: an address the old
            # broker already tried and found unbindable (a NAT advertise)
            # would fail again after a restart — comparing against actual
            # binds would restart on every reuse, forever.
            attempted = set(
                str(record.get("binds_requested", record.get("binds", "*"))).split(",")
            )
            needed = set(_bind_addresses(advertise).split(","))
            if "*" not in attempted and not needed <= attempted:
                log.warning(
                    "advertise %s needs interfaces the live broker never "
                    "attempted to bind (%s); restarting it with the wider "
                    "bind set",
                    advertise, ",".join(sorted(attempted)),
                )
                return None
            log.warning(
                "rewriting broker advertise address for %s: %s -> %s",
                cluster_name, host, advertise,
            )
            record["host"] = host = advertise
            # The failover dial list leads with the primary's advertised
            # address; keep it in step with the rewrite.
            if record.get("endpoints"):
                record["endpoints"][0] = [host, int(record["port"])]
            _write_record(
                rec, {k: v for k, v in record.items() if k != "alive"}
            )
        log.info(
            "reusing broker for %s at %s:%s (pid %s)",
            cluster_name, host, record["port"], record["pid"],
        )
        return host, int(record["port"]), False

    def restart_with_wider_binds(old_record: dict) -> tuple[str, int, bool]:
        # The replacement binds the UNION of the old broker's requested
        # interfaces and this caller's: concurrent CLIs with different
        # advertise addresses converge on one broker serving both instead
        # of killing each other's in turn.  (The teardown itself still
        # discards the old broker's in-memory rendezvous state — which is
        # exactly why converging after ONE restart matters.)
        prior = [
            a
            for a in str(
                old_record.get("binds_requested", old_record.get("binds", ""))
            ).split(",")
            if a and a != "*"
        ]
        merged = sorted(set(prior) | set(extra_binds or []))
        teardown_broker(cluster_name, root)
        return ensure_broker(
            cluster_name, root=root, advertise=advertise, port=port,
            timeout_s=timeout_s, extra_binds=merged,
            shard=shard, n_shards=n_shards,
            # Carry the old broker's AUTH token into the replacement:
            # agents provisioned by the OTHER CLI hold it in VM metadata,
            # and that CLI's process holds it ambiently — regenerating
            # would permanently lock them all out.
            reuse_token=old_record.get("token") or reuse_token,
            # Bump the epoch: the replacement is a NEW leadership term (its
            # in-memory state starts empty), so any stale replication
            # stream from the torn-down broker must be fenced.
            reuse_epoch=int(old_record.get("epoch", 0) or 0) + 1,
        )

    existing = broker_status(cluster_name, root)
    if existing is not None:
        if existing["alive"]:
            reused = reuse_live(existing)
            if reused is None:
                return restart_with_wider_binds(existing)
            return reused
        # Dead primary: adopt (promote) a live warm standby before falling
        # back to a cold start — a promotion keeps the replicated KV /
        # queue / heartbeat state; a fresh spawn starts empty.  A STALE
        # standby record is unlinked inside _adopt_standby so it can never
        # shadow the dead primary on later calls.
        adopted = _adopt_standby(cluster_name, root, existing, rec)
        if adopted is not None:
            return adopted
        log.warning(
            "recorded broker for %s at %s:%s is dead; starting a new one",
            cluster_name, existing["host"], existing["port"],
        )
        # Preserve the dead broker's AUTH token: VMs provisioned against
        # it hold that token in instance metadata, and a crash-restart of
        # the operator host must let them re-converge, not lock them out.
        if reuse_token is None:
            reuse_token = existing.get("token") or None
        # Fence the dead broker's term even on a cold restart: if its
        # process is merely partitioned (not dead) and later streams SYNC
        # frames, the bumped epoch rejects them.
        if reuse_epoch is None:
            reuse_epoch = int(existing.get("epoch", 0) or 0) + 1
        rec.unlink(missing_ok=True)

    build_broker()
    rec.parent.mkdir(parents=True, exist_ok=True)
    log_path = rec.with_suffix(".log")
    # Exclusive-create lock: two concurrent ensure calls (parallel create +
    # run) must not each spawn a detached broker — the loser's process
    # would be leaked with no record pointing at it.
    lock = rec.with_suffix(".lock")
    try:
        fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.write(fd, str(os.getpid()).encode())
        os.close(fd)
    except FileExistsError:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            st = broker_status(cluster_name, root)
            if st is not None and st["alive"]:
                reused = reuse_live(st)
                if reused is None:
                    # The race winner's broker lacks interfaces this
                    # caller's advertise needs; replace it.
                    return restart_with_wider_binds(st)
                return reused
            # Stale-lock reclaim: the holder wrote its pid for exactly
            # this check — a crash between lock and unlink must not brick
            # --broker auto until manual cleanup.
            try:
                holder = int(lock.read_text().strip() or 0)
            except (FileNotFoundError, ValueError):
                holder = 0
            holder_alive = False
            if holder:
                try:
                    os.kill(holder, 0)
                    holder_alive = True
                except ProcessLookupError:
                    holder_alive = False
                except PermissionError:
                    # EPERM = the pid EXISTS under another user — alive.
                    holder_alive = True
            if holder and not holder_alive:
                # Atomic reclaim: rename wins exactly once, so two waiters
                # observing the same dead holder cannot both proceed (the
                # loser's rename fails and it keeps waiting for the
                # winner's record).  The rename alone is not enough — a
                # slow waiter could rename the WINNER's fresh lock — so
                # verify the renamed file still names the dead holder and
                # restore it if not.
                stale = lock.with_suffix(".stale")
                try:
                    os.rename(lock, stale)
                except FileNotFoundError:
                    time.sleep(0.1)
                    continue
                try:
                    renamed_holder = int(stale.read_text().strip() or 0)
                except (FileNotFoundError, ValueError):
                    renamed_holder = 0
                if renamed_holder != holder:
                    # We grabbed a lock newer than the one we observed
                    # dead: put it back and keep waiting on its owner.
                    try:
                        os.rename(stale, lock)
                    except OSError:
                        pass
                    time.sleep(0.1)
                    continue
                stale.unlink(missing_ok=True)
                log.warning(
                    "reclaimed stale broker lock %s (holder pid %d is dead)",
                    lock, holder,
                )
                return ensure_broker(
                    cluster_name, root=root, advertise=advertise, port=port,
                    timeout_s=max(deadline - time.monotonic(), 5.0),
                    extra_binds=extra_binds, reuse_token=reuse_token,
                    reuse_epoch=reuse_epoch, shard=shard, n_shards=n_shards,
                )
            time.sleep(0.1)
        raise BrokerError(
            f"another process holds {lock} but never published a live "
            "broker; remove the lock if it is stale"
        )
    try:
        # "wb": a crashed broker's log would otherwise leave a stale
        # "listening on <port>" line that the parser below would match
        # first, pointing every restart at the dead port.
        log_fh = open(log_path, "wb")
        try:
            # start_new_session: the broker is a stack resource that must
            # survive this CLI process (and its process group / terminal).
            # The explicit bind list keeps the unauthenticated rendezvous
            # plane off interfaces no cluster VM dials (see module doc).
            bind_list = _bind_addresses(advertise).split(",")
            for a in extra_binds or []:
                if a and a != "*" and a not in bind_list:
                    bind_list.append(a)
            # Shared-secret AUTH (the reference's control plane was
            # IAM-gated, deeplearning.template:193-197; an open rendezvous
            # on the advertise interface is below that bar).  Via env so
            # the token never appears in /proc/<pid>/cmdline.
            import secrets

            token = reuse_token or secrets.token_hex(16)  # dlcfn: noqa[DLC601] auth token for a real broker process: unpredictability is the requirement, not replayability
            epoch = int(reuse_epoch or 0)
            # Fresh leadership term, fresh journal: a new primary's seq
            # counter restarts at 1, so stale entries from the previous
            # term would make a standby's skip-by-seq dedup swallow the
            # new term's stream.
            repl_log = _repl_log_path(cluster_name, root)
            repl_log.unlink(missing_ok=True)
            spawn_env = {
                **os.environ,
                "DLCFN_BROKER_TOKEN": token,
                "DLCFN_BROKER_ROLE": "primary",
                "DLCFN_BROKER_EPOCH": str(epoch),
                "DLCFN_BROKER_REPL_LOG": str(repl_log),
            }
            if n_shards is not None:
                spawn_env["DLCFN_BROKER_SHARD"] = str(shard or 0)
                spawn_env["DLCFN_BROKER_NSHARDS"] = str(n_shards)
            proc = subprocess.Popen(
                [str(BROKER_BIN), str(port), ",".join(bind_list)],
                stdout=log_fh,
                stderr=subprocess.STDOUT,
                start_new_session=True,
                env=spawn_env,
            )
        finally:
            log_fh.close()

        # The broker prints "dlcfn-broker listening on <port>" first; poll
        # the log for it (stdout is detached), then health-check with PING.
        deadline = time.monotonic() + timeout_s
        bound_port: int | None = None
        while time.monotonic() < deadline and bound_port is None:
            if proc.poll() is not None:
                raise BrokerError(
                    f"broker exited with {proc.returncode} at startup; "
                    f"see {log_path}"
                )
            m = _LISTENING.search(log_path.read_text(errors="replace"))
            if m:
                bound_port = int(m.group(1))
                break
            time.sleep(0.05)
        if bound_port is None:
            proc.terminate()
            raise BrokerError(f"broker did not report a port; see {log_path}")
        while time.monotonic() < deadline:
            if _alive("127.0.0.1", bound_port):
                break
            time.sleep(0.05)
        else:
            proc.terminate()
            raise BrokerError("broker did not become reachable")

        host = advertise or "127.0.0.1"
        # Record what the broker ACTUALLY listens on, not what was
        # requested: the binary skips unbindable addresses (NAT IPs,
        # port conflicts on one interface) non-fatally and logs each.
        # Recording the requested list would let a later advertise
        # rewrite pass the needed<=bound safety check against addresses
        # nothing serves.
        requested = list(bind_list)
        skipped = set(
            re.findall(
                r"skipping unbindable address (\S+)",
                log_path.read_text(errors="replace"),
            )
        )
        actual_binds = [a for a in requested if a not in skipped]
        if advertise and advertise in skipped:
            # Expected for a NAT/public advertise address (traffic arrives
            # at the host's own interface, which is bound); surfaced so a
            # port conflict on a LOCAL advertise interface is not silent.
            log.warning(
                "advertise address %s is not locally bindable; VMs must "
                "reach the broker via forwarding to one of: %s",
                advertise, ",".join(actual_binds),
            )
        record_payload = {
            "cluster": cluster_name,
            "host": host,
            "port": bound_port,
            "pid": proc.pid,
            # What the broker actually listens on (skips removed)
            # vs what was attempted: reuse compares advertise needs
            # against ATTEMPTED (retrying a known-unbindable NAT
            # address is pointless), while the actual list is the
            # honest record of what serves.
            "binds": ",".join(actual_binds),
            "binds_requested": ",".join(requested),
            # The AUTH shared secret; the record is chmod 0600.
            "token": token,
            # Replication metadata (docs/RESILIENCE.md "Broker
            # failover"): the leadership term this process was fenced
            # to at spawn, and the ordered dial list handed to
            # failover clients (endpoints_from_record).  A standby
            # attach (ensure_standby_broker) appends its address here.
            "role": "primary",
            "epoch": epoch,
            "endpoints": [[host, bound_port]],
            "started_ts": time.time(),
        }
        if n_shards is not None:
            record_payload["shard"] = int(shard or 0)
            record_payload["n_shards"] = int(n_shards)
        _write_record(rec, record_payload)
    finally:
        lock.unlink(missing_ok=True)
    log.info(
        "started broker for %s at %s:%d (pid %d, log %s)",
        cluster_name, host, bound_port, proc.pid, log_path,
    )
    get_recorder().record(
        "broker_started",
        cluster=cluster_name,
        broker_host=host,
        broker_port=bound_port,
        broker_pid=proc.pid,
    )
    return host, bound_port, True


def ensure_standby_broker(
    cluster_name: str,
    root: Path | None = None,
    port: int = 0,
    timeout_s: float = 30.0,
) -> tuple[str, int, bool]:
    """Return ``(host, port, started)`` for a warm-standby replica of the
    cluster's recorded primary, spawning one (detached) if none is live.

    The standby runs on the same host as the primary (the operator /
    controller host), shares its AUTH token, starts at the primary's
    epoch with ``DLCFN_BROKER_ROLE=standby`` — rejecting client writes
    until promoted — and is recorded in ``<cluster>.standby.json``.  The
    PRIMARY record's ``endpoints`` list is extended so failover clients
    (``FailoverBrokerConnection``) learn both addresses from one record.
    State flows to it through :class:`ReplicationStreamer`, not at spawn:
    a standby attached mid-life converges as the journal is replayed.
    """
    primary = broker_status(cluster_name, root)
    if primary is None or not primary["alive"]:
        raise BrokerError(
            f"no live primary broker recorded for {cluster_name}; "
            "run ensure_broker first"
        )
    srec = _standby_record_path(cluster_name, root)
    existing = standby_broker_status(cluster_name, root)
    if existing is not None:
        if existing["alive"]:
            log.info(
                "reusing standby broker for %s at %s:%s (pid %s)",
                cluster_name, existing["host"], existing["port"],
                existing["pid"],
            )
            return existing["host"], int(existing["port"]), False
        srec.unlink(missing_ok=True)

    build_broker()
    srec.parent.mkdir(parents=True, exist_ok=True)
    log_path = srec.with_suffix(".log")
    binds = str(
        primary.get("binds_requested") or primary.get("binds") or "127.0.0.1"
    )
    token = primary.get("token") or ""
    epoch = int(primary.get("epoch", 0) or 0)
    # The standby journals every SYNC entry it applies into its OWN copy
    # of the journal, seq/epoch-faithful (not a local counter, so replay
    # after ITS promotion dedups exactly).  Fresh standby, fresh copy.
    standby_repl = _standby_repl_log_path(cluster_name, root)
    standby_repl.unlink(missing_ok=True)
    env = {
        **os.environ,
        # Token via env (never argv).
        "DLCFN_BROKER_TOKEN": token,
        "DLCFN_BROKER_ROLE": "standby",
        "DLCFN_BROKER_EPOCH": str(epoch),
        "DLCFN_BROKER_REPL_LOG": str(standby_repl),
    }
    # A shard-stamped primary gets a matching standby (SHARD identity).
    if primary.get("n_shards"):
        env["DLCFN_BROKER_SHARD"] = str(primary.get("shard", 0))
        env["DLCFN_BROKER_NSHARDS"] = str(primary["n_shards"])
    # "wb" for the same stale-"listening on" reason as ensure_broker.
    log_fh = open(log_path, "wb")
    try:
        proc = subprocess.Popen(
            [str(BROKER_BIN), str(port), binds],
            stdout=log_fh,
            stderr=subprocess.STDOUT,
            start_new_session=True,
            env=env,
        )
    finally:
        log_fh.close()

    deadline = time.monotonic() + timeout_s
    bound_port: int | None = None
    while time.monotonic() < deadline and bound_port is None:
        if proc.poll() is not None:
            raise BrokerError(
                f"standby broker exited with {proc.returncode} at startup; "
                f"see {log_path}"
            )
        m = _LISTENING.search(log_path.read_text(errors="replace"))
        if m:
            bound_port = int(m.group(1))
            break
        time.sleep(0.05)
    if bound_port is None:
        proc.terminate()
        raise BrokerError(
            f"standby broker did not report a port; see {log_path}"
        )
    while time.monotonic() < deadline:
        if _alive("127.0.0.1", bound_port):
            break
        time.sleep(0.05)
    else:
        proc.terminate()
        raise BrokerError("standby broker did not become reachable")

    host = primary["host"]
    standby_payload = {
        "cluster": cluster_name,
        "host": host,
        "port": bound_port,
        "pid": proc.pid,
        "binds": binds,
        "binds_requested": binds,
        "token": token or None,
        "role": "standby",
        "epoch": epoch,
        "started_ts": time.time(),
    }
    for key in ("shard", "n_shards"):
        if key in primary:
            standby_payload[key] = primary[key]
    _write_record(srec, standby_payload)
    prec = {k: v for k, v in primary.items() if k != "alive"}
    prec["endpoints"] = [
        [primary["host"], int(primary["port"])],
        [host, bound_port],
    ]
    _write_record(_record_path(cluster_name, root), prec)
    log.info(
        "started standby broker for %s at %s:%d (pid %d, epoch %d, log %s)",
        cluster_name, host, bound_port, proc.pid, epoch, log_path,
    )
    get_recorder().record(
        "broker_standby_started",
        cluster=cluster_name,
        broker_host=host,
        broker_port=bound_port,
        broker_pid=proc.pid,
        epoch=epoch,
    )
    return host, bound_port, True


class ReplicationStreamer:
    """Ships the primary's replication journal to the warm standby.

    The primary appends every mutation it applies to a flight-recorder
    JSONL journal (``kind: broker_apply``); this streamer tails the file
    and replays each frame into the standby with SYNC.  Pull-based and
    resumable: the streamer resumes from its last shipped seq, the
    standby skips any entry at-or-below the seq it already applied
    (crash-safe at-least-once shipping composes with idempotent replay),
    and epoch fencing at the receiver raises ``BrokerFenced`` when this
    stream belongs to a deposed primary — the split-brain guard.
    """

    def __init__(
        self,
        cluster_name: str,
        root: Path | None = None,
        connect=None,
        clock=time.time,
    ):
        self.cluster_name = cluster_name
        self._root = root
        self._connect = connect  # injectable: () -> standby BrokerConnection
        self._clock = clock
        self.shipped_seq = 0
        self.shipped_total = 0

    def _dial_standby(self):
        if self._connect is not None:
            return self._connect()
        standby = standby_broker_status(self.cluster_name, self._root)
        if standby is None or not standby["alive"]:
            raise BrokerError(
                f"no live standby broker recorded for {self.cluster_name}"
            )
        return BrokerConnection(
            "127.0.0.1",
            int(standby["port"]),
            timeout_s=5.0,
            token=standby.get("token") or "",
        )

    def pending(self) -> list[dict]:
        """Journal entries not yet shipped, oldest first."""
        entries = read_journal(_repl_log_path(self.cluster_name, self._root))
        return [
            e
            for e in entries
            if e.get("kind") == "broker_apply"
            and int(e.get("seq", 0)) > self.shipped_seq
        ]

    def lag_seconds(self) -> float:
        """Age of the oldest journal entry not yet shipped; 0.0 when
        caught up."""
        todo = self.pending()
        if not todo:
            return 0.0
        return max(0.0, self._clock() - float(todo[0].get("ts", 0.0)))

    def _sender_epoch(self) -> int:
        """The recorded primary's current term: entries ship under
        ``max(entry epoch, sender epoch)``.  A promoted primary re-plays
        pre-promotion history to a fresh standby under ITS term (the
        entries' old epochs would be fenced), while a deposed primary's
        process cannot launder its stream — adoption atomically rotates
        the journal file this streamer tails, so the path always names
        the acting primary's history.  0 (entry epochs verbatim) when no
        record exists — the injected-connect test seam."""
        try:
            record = json.loads(
                _record_path(self.cluster_name, self._root).read_text()
            )
            return int(record.get("epoch", 0) or 0)
        except (OSError, ValueError):
            return 0

    def step(self) -> int:
        """Ship every unshipped journal entry to the standby; returns how
        many were shipped.  Raises ``BrokerFenced`` (via sync_entry) when
        the standby has seen a higher epoch — stop streaming, this
        primary is deposed."""
        todo = self.pending()
        if not todo:
            return 0
        sender_epoch = self._sender_epoch()
        conn = self._dial_standby()
        try:
            for e in todo:
                conn.sync_entry(
                    max(int(e["epoch"]), sender_epoch),
                    int(e["seq"]),
                    str(e["frame"]).encode("utf-8"),
                )
                self.shipped_seq = int(e["seq"])
                self.shipped_total += 1
        finally:
            conn.close()
        get_recorder().record(
            "broker_replicate",
            cluster=self.cluster_name,
            shipped=len(todo),
            seq=self.shipped_seq,
            lag_s=round(
                max(0.0, self._clock() - float(todo[-1].get("ts", 0.0))), 6
            ),
        )
        return len(todo)


def broker_replication_status(
    cluster_name: str, root: Path | None = None, clock=time.time
) -> dict | None:
    """Role / epoch / applied-seq for the recorded primary and standby,
    plus replication lag — the ``dlcfn status --broker`` and exporter
    view.  None when no broker is recorded.  Lag is measured from the
    journal: entries the standby has not applied, in count
    (``lag_entries``) and age of the oldest such entry
    (``lag_seconds``).  ``clock`` must match the journal's ``ts`` domain
    (wall clock for the binary's log; a VirtualClock in sims) — lag is
    an age metric against recorded stamps, not a deadline."""
    primary = broker_status(cluster_name, root)
    if primary is None:
        return None

    def probe(record: dict) -> dict:
        out = {
            "host": record["host"],
            "port": int(record["port"]),
            "pid": int(record["pid"]),
            "alive": bool(record.get("alive")),
            "role": record.get("role"),
            "epoch": record.get("epoch"),
            "seq": None,
        }
        if not out["alive"]:
            return out
        try:
            conn = BrokerConnection(
                "127.0.0.1",
                out["port"],
                timeout_s=2.0,
                token=record.get("token") or "",
            )
            try:
                role_name, epoch, seq = conn.role()
            finally:
                conn.close()
            out.update(role=role_name, epoch=epoch, seq=seq)
        except (OSError, BrokerError):
            out["alive"] = False
        return out

    standby = standby_broker_status(cluster_name, root)
    result = {
        "primary": probe(primary),
        "standby": probe(standby) if standby is not None else None,
    }
    pseq = result["primary"]["seq"]
    sseq = (result["standby"] or {}).get("seq")
    if pseq is None or sseq is None:
        result["lag_entries"] = None
        result["lag_seconds"] = None
        return result
    result["lag_entries"] = max(0, pseq - sseq)
    lag_s = 0.0
    if result["lag_entries"]:
        entries = [
            e
            for e in read_journal(_repl_log_path(cluster_name, root))
            if e.get("kind") == "broker_apply"
            and int(e.get("seq", 0)) > sseq
        ]
        if entries:
            lag_s = max(0.0, clock() - float(entries[0].get("ts", 0.0)))
    result["lag_seconds"] = round(lag_s, 6)
    return result


class BrokerLivenessWatcher:
    """Polls the broker's heartbeat table and drives the liveness machine.

    The supervisor-side half of the HEARTBEAT loop: agents beat at the
    broker (obs/heartbeat.py Heartbeater); this watcher dumps the table,
    feeds the ALIVE/SUSPECT/DEAD classifier, and publishes
    ``INSTANCE_TERMINATE`` on the provisioner event bus for each DEAD
    transition — silent death then takes exactly the recovery path a
    backend-reported termination does (elasticity -> RecoveryManager).

    A worker that resumes beating after DEAD is resurrected to ALIVE;
    idempotent controllers (the bus contract) make the duplicate
    terminate harmless if recovery already replaced it.
    """

    def __init__(
        self,
        cluster_name: str,
        group: str,
        bus=None,
        root: Path | None = None,
        config: LivenessConfig | None = None,
        clock=time.monotonic,
        fetch=None,
    ):
        self.cluster_name = cluster_name
        self.group = group
        self.bus = bus
        self._root = root
        self._fetch = fetch  # injectable: () -> {worker: (age_s, count)}
        self._last_counts: dict[str, int] = {}
        self.table = LivenessTable(
            config=config or LivenessConfig(),
            clock=clock,
            on_transition=self._on_transition,
        )

    def _on_transition(self, transition) -> None:
        worker, old, new = transition
        log.warning(
            "worker %s liveness: %s -> %s", worker, old.value, new.value
        )
        if new is WorkerState.DEAD and self.bus is not None:
            from deeplearning_cfn_tpu.provision.events import (
                EventKind,
                LifecycleEvent,
            )

            self.bus.publish(
                LifecycleEvent(
                    kind=EventKind.INSTANCE_TERMINATE,
                    group=self.group,
                    instance_id=worker,
                    detail={"reason": "heartbeat-dead", "source": "liveness"},
                )
            )

    def _dump_heartbeats(self) -> dict[str, tuple[float, int]]:
        if self._fetch is not None:
            return self._fetch()
        status = broker_status(self.cluster_name, self._root)
        if status is None or not status["alive"]:
            return {}
        conn = BrokerConnection(
            "127.0.0.1",
            int(status["port"]),
            timeout_s=5.0,
            token=broker_token(self.cluster_name, self._root) or "",
        )
        try:
            return conn.heartbeats()
        finally:
            conn.close()

    def poll(self) -> list:
        """One fetch + sweep; returns the liveness transitions."""
        for worker, (age_s, count) in self._dump_heartbeats().items():
            self.table.observe(worker, age_s=age_s, count=count)
            # Journal each NEW beat with the observer's clock: paired
            # with the worker's heartbeat_sent event of the same seq,
            # obs/trace_export.py derives sender->observer clock offsets
            # (observed ts - age_s names the send instant on THIS clock).
            if count != self._last_counts.get(worker):
                self._last_counts[worker] = count
                get_recorder().record(
                    "heartbeat_observed",
                    worker=worker,
                    seq=count,
                    age_s=round(float(age_s), 6),
                )
        return self.table.sweep()

    def snapshot(self) -> dict:
        return self.table.snapshot()


def cluster_liveness(
    cluster_name: str,
    root: Path | None = None,
    config: LivenessConfig | None = None,
) -> dict:
    """One-shot per-worker liveness for a recorded cluster broker.

    The ``dlcfn status`` view: dump the broker's heartbeat table, classify
    each worker's silence against ``config``, return the snapshot.  Empty
    when no broker is recorded/alive or nothing has ever beaten.
    """
    watcher = BrokerLivenessWatcher(
        cluster_name, group="", bus=None, root=root, config=config
    )
    watcher.poll()
    return watcher.snapshot()


def _unlink_lock_if_stale(lock: Path) -> None:
    """Remove the ensure_broker spawn lock only when its holder is this
    process or dead.  A teardown racing a live ensure_broker (two CLIs,
    one restarting the broker while the other is mid-spawn) must not
    yank the winner's exclusive-create lock out from under it — that
    would let a THIRD caller spawn a second broker concurrently."""
    try:
        holder = int(lock.read_text().strip() or 0)
    except FileNotFoundError:
        return  # no lock to reap
    except (ValueError, OSError):
        holder = 0  # unreadable/corrupt: reap (verified below)
    if holder and holder != os.getpid():
        try:
            os.kill(holder, 0)
            return  # live holder: the lock is theirs, leave it
        except ProcessLookupError:
            pass
        except PermissionError:
            return  # exists under another user: alive
    # Check-then-unlink is a TOCTOU window: between the dead-holder check
    # and the unlink, a concurrent teardown may reap the same stale lock
    # AND a fresh ensure_broker may exclusive-create a new one — a plain
    # unlink here would delete the new winner's lock.  Same discipline as
    # ensure_broker's reclaim: rename (atomic, wins exactly once; losing
    # the race is fine), verify the renamed file still names the holder
    # we judged stale, restore it if we grabbed a newer lock.  The rename
    # target is pid-unique so two reapers cannot collide on it either.
    stale = lock.with_suffix(f".stale-{os.getpid()}")
    try:
        os.rename(lock, stale)
    except FileNotFoundError:
        return  # a concurrent reaper won; done either way
    except OSError:
        return  # cannot rename (exotic fs): leave the lock for the operator
    try:
        renamed_holder = int(stale.read_text().strip() or 0)
    except (FileNotFoundError, ValueError, OSError):
        renamed_holder = 0
    if renamed_holder != holder:
        # We grabbed a lock newer than the one we observed stale: it
        # belongs to a live ensure_broker — put it back.
        try:
            os.rename(stale, lock)
        except OSError:
            pass
        return
    stale.unlink(missing_ok=True)


def _reap_standby(cluster_name: str, root: Path | None) -> dict | None:
    """Stop and forget the cluster's recorded STANDBY broker, with the
    same pid-identity discipline as the primary teardown (cmdline verify
    on procfs; never signal an unverifiable pid).  None when no standby
    record exists."""
    srec = _standby_record_path(cluster_name, root)
    status = standby_broker_status(cluster_name, root)
    if status is None:
        return None
    pid = int(status["pid"])
    verdict = "stopped"
    if Path("/proc").exists():
        try:
            cmdline = (
                Path(f"/proc/{pid}/cmdline").read_bytes().decode(errors="replace")
            )
        except OSError:
            cmdline = ""
        if "dlcfn-broker" not in cmdline:
            verdict = "stale-record"
    else:
        verdict = "left-running"
    if verdict == "stopped":

        def gone() -> bool:
            try:
                if os.waitpid(pid, os.WNOHANG)[0] == pid:
                    return True
            except ChildProcessError:
                pass
            try:
                os.kill(pid, 0)
                return False
            except ProcessLookupError:
                return True

        try:
            os.kill(pid, signal.SIGTERM)
            for _ in range(50):
                if gone():
                    break
                time.sleep(0.1)
            else:
                os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        except PermissionError:
            verdict = "left-running"
    srec.unlink(missing_ok=True)
    srec.with_suffix(".log").unlink(missing_ok=True)
    return {
        "broker": verdict,
        "host": status["host"],
        "port": status["port"],
        "pid": pid,
    }


def teardown_broker(cluster_name: str, root: Path | None = None) -> dict:
    """Stop and forget the cluster's recorded broker — primary, warm
    standby, and replication journal (``delete``'s side of the
    stack-resource contract).  Safe when none exists."""
    rec = _record_path(cluster_name, root)
    standby_result = _reap_standby(cluster_name, root)
    _repl_log_path(cluster_name, root).unlink(missing_ok=True)
    _standby_repl_log_path(cluster_name, root).unlink(missing_ok=True)
    status = broker_status(cluster_name, root)
    if status is None:
        result = {"broker": "none"}
        if standby_result is not None:
            result["standby"] = standby_result
        return result
    pid = int(status["pid"])

    # Never SIGTERM a recycled pid: after a reboot the record survives but
    # the OS may have reassigned the pid to an unrelated same-user
    # process.  On procfs systems (every deployment target: TPU VMs /
    # GCE / the dev containers), verify the pid's cmdline is actually the
    # broker.  Without /proc there is NO safe way to verify a pid's
    # identity — a live port answering PING does not prove the recorded
    # pid is the broker — so never signal: clean the records and report
    # the pid for the operator.
    if Path("/proc").exists():
        try:
            cmdline = (
                Path(f"/proc/{pid}/cmdline").read_bytes().decode(errors="replace")
            )
        except OSError:
            cmdline = ""  # pid gone entirely: nothing to kill
        verdict = "stale-record" if "dlcfn-broker" not in cmdline else None
    else:
        verdict = "left-running"
    if verdict is not None:
        rec.unlink(missing_ok=True)
        rec.with_suffix(".log").unlink(missing_ok=True)
        _unlink_lock_if_stale(rec.with_suffix(".lock"))
        result = {
            "broker": verdict,
            "host": status["host"],
            "port": status["port"],
            "pid": pid,
        }
        if standby_result is not None:
            result["standby"] = standby_result
        return result

    def gone() -> bool:
        # Reap first if the broker is OUR child (ensure_broker ran in this
        # process): a terminated-but-unreaped child still answers kill(0).
        # Cross-process (create in one CLI, delete in another) the broker
        # was adopted and reaped by init, so kill(0) alone is accurate.
        try:
            if os.waitpid(pid, os.WNOHANG)[0] == pid:
                return True
        except ChildProcessError:
            pass  # not our child
        try:
            os.kill(pid, 0)
            return False
        except ProcessLookupError:
            return True

    stopped = False
    try:
        os.kill(pid, signal.SIGTERM)
        for _ in range(50):
            if gone():
                stopped = True
                break
            time.sleep(0.1)
        if not stopped:
            os.kill(pid, signal.SIGKILL)
            for _ in range(50):
                if gone():
                    break
                time.sleep(0.1)
            stopped = True
    except ProcessLookupError:
        stopped = True  # already gone
    except PermissionError:
        # Someone else's pid (stale record reused by the OS): do not kill.
        stopped = False
    rec.unlink(missing_ok=True)
    rec.with_suffix(".log").unlink(missing_ok=True)
    _unlink_lock_if_stale(rec.with_suffix(".lock"))
    result = {
        "broker": "stopped" if stopped else "left-running",
        "host": status["host"],
        "port": status["port"],
        "pid": pid,
    }
    if standby_result is not None:
        result["standby"] = standby_result
    get_recorder().record("broker_teardown", cluster=cluster_name, **result)
    return result


def ensure_sharded_broker(
    cluster_name: str,
    n_shards: int,
    root: Path | None = None,
    advertise: str | None = None,
    timeout_s: float = 30.0,
    standby: bool = True,
) -> dict:
    """Bring up (or adopt) a sharded broker deployment: ``n_shards``
    independent primary/standby pairs, each owning one consistent-hash
    shard of the queue/KV/heartbeat keyspace (broker_client.shard_for_key).

    Each shard is a full ``ensure_broker`` cluster named
    ``<cluster>.shard<k>`` — its own record, lock, log, replication
    journal, epoch fence — so every single-pair mechanism (promotion,
    fencing, journal rename, auto-re-provision) applies per shard
    unchanged.  All shards share shard 0's AUTH token so a router holds
    one credential.  The shard map is written to ``<cluster>.shards.json``
    and consumed by :func:`sharded_broker_records` /
    ``ShardedBrokerRouter.for_cluster``.  Idempotent: live shards are
    reused, dead ones restarted.
    """
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    shards = []
    token: str | None = None
    for k in range(n_shards):
        shard_name = shard_cluster_name(cluster_name, k)
        host, port, started = ensure_broker(
            shard_name,
            root=root,
            advertise=advertise,
            timeout_s=timeout_s,
            reuse_token=token,
            shard=k,
            n_shards=n_shards,
        )
        if token is None:
            token = broker_token(shard_name, root)
        if standby:
            ensure_standby_broker(shard_name, root=root, timeout_s=timeout_s)
        shards.append(
            {"shard": k, "cluster": shard_name, "host": host, "port": port,
             "started": started}
        )
    _write_record(
        _shard_map_path(cluster_name, root),
        {"cluster": cluster_name, "n_shards": n_shards,
         "shards": [s["cluster"] for s in shards]},
    )
    get_recorder().record(
        "broker_sharded_ensure", cluster=cluster_name, n_shards=n_shards,
        started=sum(1 for s in shards if s["started"]),
    )
    return {"cluster": cluster_name, "n_shards": n_shards, "shards": shards}


def sharded_broker_records(
    cluster_name: str, root: Path | None = None
) -> list[dict] | None:
    """Per-shard broker records for a sharded deployment, in ring order —
    the routing table ``ShardedBrokerRouter.for_cluster`` builds its
    per-shard endpoint lists from.  None when no shard map is recorded
    (the cluster is unsharded or torn down).  A shard whose record is
    missing (mid-teardown, crashed before re-ensure) yields
    ``record: None`` — the router refuses to run with a hole in the ring
    rather than silently misrouting its keyspace slice."""
    try:
        shard_map = json.loads(_shard_map_path(cluster_name, root).read_text())
    except (OSError, ValueError):
        return None
    return [
        {"shard": k, "cluster": name, "record": broker_status(name, root)}
        for k, name in enumerate(shard_map.get("shards", []))
    ]


def broker_shard_replication_status(
    cluster_name: str, root: Path | None = None, clock=time.time
) -> dict | None:
    """Replication health for every shard of a sharded deployment — the
    ``dlcfn status --broker`` / exporter view.  None when no shard map is
    recorded.  Each entry is :func:`broker_replication_status` for that
    shard plus a ``degraded`` flag: True when the pair is not a healthy
    replicating primary+standby (missing/dead standby, or nonzero lag) —
    the state the self-healing adoption path exists to make transient,
    never steady-state."""
    try:
        shard_map = json.loads(_shard_map_path(cluster_name, root).read_text())
    except (OSError, ValueError):
        return None
    shards = []
    for k, name in enumerate(shard_map.get("shards", [])):
        status = broker_replication_status(name, root, clock=clock)
        degraded = True
        if status is not None:
            standby = status.get("standby")
            degraded = not (
                status["primary"]["alive"]
                and standby is not None
                and standby.get("alive")
                and not status.get("lag_entries")
            )
        shards.append(
            {"shard": k, "cluster": name, "status": status, "degraded": degraded}
        )
    return {
        "cluster": cluster_name,
        "n_shards": len(shards),
        "shards": shards,
        "degraded_shards": sum(1 for s in shards if s["degraded"]),
    }


def teardown_sharded_broker(
    cluster_name: str, root: Path | None = None
) -> dict:
    """Tear down every shard of a sharded deployment and forget the shard
    map.  Safe when none exists (mirrors :func:`teardown_broker`)."""
    try:
        shard_map = json.loads(_shard_map_path(cluster_name, root).read_text())
    except (OSError, ValueError):
        return {"broker": "none", "shards": []}
    results = [
        {"shard": k, "cluster": name, "result": teardown_broker(name, root)}
        for k, name in enumerate(shard_map.get("shards", []))
    ]
    _shard_map_path(cluster_name, root).unlink(missing_ok=True)
    get_recorder().record(
        "broker_sharded_teardown", cluster=cluster_name, n_shards=len(results)
    )
    return {"cluster": cluster_name, "shards": results}
