"""Automated instance-loss recovery: detect → recreate → resume.

The reference documents this loop but leaves every step to the operator:
the master self-heals only via its ASG (StackSetup.md:113-114), worker
replacement never updates cluster metadata (StackSetup.md:107-108), and the
prescribed remedy is "delete the stack, recreate reusing the EFS, restart
from checkpoint" (examples/distributed-tensorflow/README.md:85-87).  Round
1 automated the middle step (``Provisioner.recover()``); this module closes
the loop: the elasticity controller's terminate events *trigger* recovery,
and training resumes from the checkpoints that survived on retained
storage.

On TPU the whole-slice recreate is the right granularity for any loss — a
slice is one logical machine, so a lost coordinator and a lost worker leave
the same stale contract (unlike the reference's asymmetric master/worker
story).  ``RecoveryManager`` therefore arms on every post-freeze loss in a
managed group.

Deliberate split between *detection* (event-driven, may fire mid-step) and
*recovery* (pulled at a safe point): lifecycle events arrive inside
describe/poll calls, where tearing down the very backend state being
described would re-enter the event bus.  Callers check ``needs_recovery``
between training episodes — or just use :func:`run_with_recovery`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from deeplearning_cfn_tpu.cluster.contract import ClusterContract
from deeplearning_cfn_tpu.cluster.elasticity import ElasticityController, GroupPolicy
from deeplearning_cfn_tpu.obs.recorder import get_recorder
from deeplearning_cfn_tpu.obs.tracing import span
from deeplearning_cfn_tpu.provision.events import LifecycleEvent
from deeplearning_cfn_tpu.provision.provisioner import ProvisionResult, Provisioner
from deeplearning_cfn_tpu.utils.logging import get_logger
from deeplearning_cfn_tpu.utils.resilience import RetryPolicy

log = get_logger("dlcfn.recovery")


@dataclass
class RecoveryManager:
    """Arms on instance-loss events; performs recover-and-rearm on demand."""

    provisioner: Provisioner
    losses: list[LifecycleEvent] = field(default_factory=list)

    def attach(self, result: ProvisionResult) -> None:
        """Subscribe to the live controller (re-call after every recover —
        each provisioning generation has a fresh controller)."""
        result.controller.on_instance_loss = self._on_loss

    def _on_loss(self, policy: GroupPolicy, event: LifecycleEvent) -> None:
        self.losses.append(event)
        log.warning(
            "armed for recovery: lost %s in group %s (%d losses pending)",
            event.instance_id,
            policy.name,
            len(self.losses),
        )

    @property
    def needs_recovery(self) -> bool:
        return bool(self.losses)

    def recover(self) -> ProvisionResult:
        """Recreate the cluster (reusing retained storage), re-arm on the
        new controller, and return the fresh result.  Checkpoints on the
        reused storage make the subsequent training episode resume via
        ``Checkpointer.restore_latest``."""
        lost = [e.instance_id for e in self.losses]
        self.losses.clear()
        log.warning("recovering cluster after instance loss: %s", lost)
        get_recorder().record("recovery_start", lost=lost)
        with span("recover"):
            result = self.provisioner.recover()
        self.attach(result)
        get_recorder().record("recovery_done", lost=lost)
        return result


@dataclass
class LiveReshardManager:
    """Arms on *coalesced slice losses*; derives the surviving topology.

    The in-place analog of :class:`RecoveryManager`: where that one
    recreates the cluster and restarts the training episode, this one
    feeds the live-reshard coordinator (train/reshard.py), which re-forms
    the mesh from ``surviving_contract()`` and migrates state
    device-to-device with no restart at all.  Same detection/recovery
    split as above — ``on_slice_loss`` fires from the controller's
    debounce flush (itself pulled at a step boundary), and the trainer
    consumes ``needs_reshard`` at that safe point.

    ``commit(contract)`` advances the manager to the post-reshard
    topology; a late duplicate flush for an already-removed group is then
    ignored by the ``group in slices`` guard, keeping the whole path
    idempotent under at-least-once event delivery.
    """

    contract: ClusterContract
    lost_groups: set[str] = field(default_factory=set)
    events: list[LifecycleEvent] = field(default_factory=list)
    # Grow direction (the scheduler's restore path): slices armed to
    # RETURN to the contract at the next step boundary.
    pending_restores: dict[str, list[str]] = field(default_factory=dict)

    def attach(self, controller: ElasticityController) -> None:
        controller.on_slice_loss = self.on_slice_loss

    def on_slice_loss(self, group: str, burst: list[LifecycleEvent]) -> None:
        slices = self.contract.slices or {}
        if group not in slices:
            log.info("slice-loss for unknown/already-removed group %s ignored", group)
            return
        self.lost_groups.add(group)
        self.events.extend(burst)
        get_recorder().record(
            "slice_lost",
            group=group,
            instances=sorted(e.instance_id or "?" for e in burst),
        )
        log.warning(
            "armed for live reshard: slice %s lost (%d slices pending)",
            group,
            len(self.lost_groups),
        )

    def arm_restore(self, group: str, ips: list[str]) -> None:
        """Arm the grow direction: slice ``group`` (with ``ips``) returns
        to the contract at the next step boundary.  The inverse of
        ``on_slice_loss``, same safe-point discipline — arming is cheap
        and idempotent (a slice already in the contract is ignored), the
        reshard itself happens when the trainer polls.  This is the
        scheduler's off-peak restore seam (sched/preempt.py)."""
        slices = self.contract.slices or {}
        if group in slices:
            log.info("restore for already-present group %s ignored", group)
            return
        self.pending_restores[group] = list(ips)
        get_recorder().record(
            "slice_restore_armed", group=group, instances=sorted(ips)
        )
        log.warning(
            "armed for live re-grow: slice %s returning (%d restore(s) pending)",
            group,
            len(self.pending_restores),
        )

    @property
    def needs_reshard(self) -> bool:
        return bool(self.lost_groups or self.pending_restores)

    def surviving_contract(self) -> ClusterContract:
        """The target topology: survivors of any lost slices, plus any
        armed restores (``ClusterContract.restored``).  Raises ValueError
        when live reshard is structurally impossible (e.g. the
        coordinator's slice died) — see ClusterContract.surviving."""
        contract = self.contract
        if self.lost_groups:
            contract = contract.surviving(self.lost_groups)
        if self.pending_restores:
            contract = contract.restored(self.pending_restores)
        return contract

    def commit(self, contract: ClusterContract) -> None:
        self.contract = contract
        self.lost_groups.clear()
        self.events.clear()
        self.pending_restores.clear()


def run_with_recovery(
    provisioner: Provisioner,
    train_once: Callable[[ProvisionResult], dict],
    max_recoveries: int = 1,
    policy: RetryPolicy | None = None,
) -> tuple[dict, ProvisionResult, int]:
    """provision → train → (on loss: recover → resume) loop.

    ``train_once(result)`` runs one training episode against a live
    cluster and returns its metrics; it is responsible for checkpointing
    (and for restoring, which makes resumption automatic).  Returns the
    last episode's metrics, the final provision result, and how many
    recoveries happened.

    ``policy`` (a :class:`~..utils.resilience.RetryPolicy`) adds jittered
    backoff between recovery attempts on the policy's injected clock —
    back-to-back recreates against a struggling control plane are the
    same thundering-herd mistake as unjittered RPC retries.  The give-up
    bound stays ``max_recoveries``; the default (no policy) recovers
    immediately, as before.
    """
    result = provisioner.provision()
    manager = RecoveryManager(provisioner)
    manager.attach(result)
    recoveries = 0
    delays = policy.delays() if policy is not None else None
    while True:
        out = train_once(result)
        if not manager.needs_recovery:
            return out, result, recoveries
        if recoveries >= max_recoveries:
            raise RuntimeError(
                f"instance loss after {max_recoveries} recoveries; giving up "
                f"(pending: {[e.instance_id for e in manager.losses]})"
            )
        recoveries += 1
        if delays is not None and policy is not None:
            backoff = next(delays)
            get_recorder().record("recovery_backoff", delay_s=backoff)
            policy.clock.sleep(backoff)
        result = manager.recover()
