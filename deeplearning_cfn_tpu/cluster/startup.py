"""Worker startup-script rendering — the cfn-init configSet analog.

The reference boots every node through UserData -> cfn-init running an
ordered configSet (``Setup = [efs-config, download-setup,
deeplearning-config]``, deeplearning.template:490-567); the Mask R-CNN
stack extends it to 9 worker / 12 master steps adding S3 data+code staging
with an EFS-vs-EBS placement condition guarded by a marker file
(mask-rcnn-cfn.yaml:774-827,1039-1172) and conda-env auto-activation
(:199-221).

Here the same choreography renders to ONE bash script from the typed spec
(no per-step cloud metadata), because a TPU slice's workers all boot the
same image and the script is delivered via VM metadata.  Step order is
preserved from the reference:

1. storage-config   — mount shared storage (efs-config analog)
2. staging-download — fetch dataset/code artifacts from the object store,
                      marker-guarded shared-vs-local placement
3. env-setup        — pinned pip deps + commands + login-shell activation
4. agent            — exec the bootstrap/discovery agent (the
                      deeplearning-config step running dl_cfn_setup_v2.py)
"""

from __future__ import annotations

import shlex

from deeplearning_cfn_tpu.config.schema import ClusterSpec
from deeplearning_cfn_tpu.provision.provisioner import (
    worker_group_name,
    worker_group_names,
)

# Marker file guarding one-time shared-storage data placement — the
# data.txt trick of mask-rcnn-cfn.yaml:784-789 (cfn-init `test:` guards).
DATA_MARKER = ".dlcfn-data-staged"


def render_startup_script(spec: ClusterSpec) -> str:
    """Render the full worker boot script for a cluster spec."""
    lines: list[str] = [
        "#!/bin/bash",
        "set -euo pipefail",
        # Log like cloud-init: everything teed to a well-known path
        # (deeplearning.template:549,645).
        "exec > >(tee -a /var/log/dlcfn-startup.log) 2>&1",
        f"export DLCFN_CLUSTER={shlex.quote(spec.name)}",
    ]
    lines += _storage_steps(spec)
    lines += _staging_steps(spec)
    lines += _setup_steps(spec)
    lines += _agent_step(spec)
    return "\n".join(lines) + "\n"


def _storage_steps(spec: ClusterSpec) -> list[str]:
    mount = shlex.quote(spec.storage.mount_point)
    steps = [f"mkdir -p {mount}"]
    if spec.storage.kind == "filestore":
        # efs-config analog: install client, mount, chown
        # (deeplearning.template:524-538).  The address is delivered via VM
        # metadata after storage creation; guard so a missing value degrades
        # to a warning instead of aborting the boot under `set -u`.
        steps += [
            'DLCFN_FILESTORE_ADDR="${DLCFN_FILESTORE_ADDR:-'
            "$(curl -sf -H 'Metadata-Flavor: Google' "
            "http://metadata.google.internal/computeMetadata/v1/instance/attributes/dlcfn-filestore-addr "
            '|| true)}"',
            "if [ -n \"$DLCFN_FILESTORE_ADDR\" ]; then "
            "command -v mount.nfs >/dev/null || "
            "(apt-get update -qq && apt-get install -y -qq nfs-common); "
            f'mount -t nfs -o rw,async "$DLCFN_FILESTORE_ADDR":/share {mount} '
            f"&& chown -R \"$(id -un)\" {mount} "
            "|| echo 'WARN: filestore mount failed'; "
            "else echo 'WARN: no filestore address in metadata'; fi",
        ]
    elif spec.storage.kind == "gcs":
        steps += [
            'DLCFN_GCS_BUCKET="${DLCFN_GCS_BUCKET:-'
            "$(curl -sf -H 'Metadata-Flavor: Google' "
            "http://metadata.google.internal/computeMetadata/v1/instance/attributes/dlcfn-gcs-bucket "
            '|| true)}"',
            "if [ -n \"$DLCFN_GCS_BUCKET\" ] && command -v gcsfuse >/dev/null; then "
            f'gcsfuse --implicit-dirs "$DLCFN_GCS_BUCKET" {mount} '
            "|| echo 'WARN: gcs mount failed'; "
            "else echo 'WARN: gcs bucket unset or gcsfuse missing'; fi",
        ]
    return steps


def _staging_steps(spec: ClusterSpec) -> list[str]:
    st = spec.staging
    if not st.bucket:
        return []
    base = f"gs://{st.bucket}/{st.prefix}"
    steps: list[str] = []
    if st.datasets:
        if st.data_on_shared_storage:
            # One worker stages for everyone (EFSServesData=True path,
            # mask-rcnn-cfn.yaml:1039-1068).  `mkdir` of the lock dir is the
            # atomic election on shared NFS; losers wait for the completion
            # marker so no one execs the agent against half-extracted data.
            data_dir = f"{spec.storage.mount_point}/data"
            marker = f"{data_dir}/{DATA_MARKER}"
            lock = f"{data_dir}/.dlcfn-stage-lock"
            steps.append(f"mkdir -p {shlex.quote(data_dir)}")
            fetches = " && ".join(
                f"gsutil -m cp {shlex.quote(f'{base}/{art}')} - | tar -x -C {shlex.quote(data_dir)}"
                for art in st.datasets
            )
            steps.append(
                f"if mkdir {shlex.quote(lock)} 2>/dev/null; then "
                f"{fetches} && touch {shlex.quote(marker)}; "
                f"else for i in $(seq 1 360); do "
                f"[ -f {shlex.quote(marker)} ] && break; sleep 10; done; "
                f"[ -f {shlex.quote(marker)} ] || echo 'WARN: staging wait timed out'; fi"
            )
        else:
            # Every worker stages to local disk (EFSServesData=False /
            # EBS path, mask-rcnn-cfn.yaml:774-789).
            data_dir = "/mnt/disks/data"
            steps.append(f"mkdir -p {data_dir}")
            for art in st.datasets:
                steps.append(
                    f"gsutil -m cp {shlex.quote(f'{base}/{art}')} - | tar -x -C {data_dir}"
                )
    for art in st.code:
        # Code lands in the home dir on every worker, like the tensorpack
        # tar (mask-rcnn-cfn.yaml:1107-1130).
        steps.append(
            f"gsutil -m cp {shlex.quote(f'{base}/{art}')} - | tar -x -C \"$HOME\""
        )
    return steps


def _setup_steps(spec: ClusterSpec) -> list[str]:
    setup = spec.setup
    steps: list[str] = []
    if setup.pip_packages:
        # Pinned dependency set on each worker (setup.sh:1-19 analog).
        pkgs = " ".join(shlex.quote(p) for p in setup.pip_packages)
        steps.append(f"python3 -m pip install --no-input -q {pkgs}")
    steps.extend(setup.commands)
    if setup.activate_env:
        # ActivateCondaEnv analog: auto-activate in login shells
        # (mask-rcnn-cfn.yaml:199-221 writes .bash_login).
        act = shlex.quote(f"source {setup.activate_env}/bin/activate")
        steps.append(f"echo {act} >> \"$HOME/.bash_login\"")
    return steps


def _agent_step(spec: ClusterSpec) -> list[str]:
    # deeplearning-config analog: run the discovery agent with the full
    # cluster identity in env — the AWS_DL_* injection of
    # deeplearning.template:546-564.  Worker index comes from TPU VM
    # metadata (every worker of a slice learns its rank from
    # `agent-worker-number`); the broker address is stamped into instance
    # attributes by the controller at create time.  Env vars already set
    # (e.g. by a test harness or a custom image) win over metadata.
    md = (
        "curl -sf -H 'Metadata-Flavor: Google' "
        "http://metadata.google.internal/computeMetadata/v1/instance/"
    )
    return [
        # Retry the metadata fetch, then REFUSE to boot rather than guess:
        # a worker that defaulted to index 0 would run a second coordinator
        # and consume the single group-setup message the real coordinator
        # is waiting for (wait_for_group_success deletes what it reads).
        'for _i in 1 2 3 4 5; do '
        f'DLCFN_WORKER_INDEX="${{DLCFN_WORKER_INDEX:-$({md}attributes/agent-worker-number || true)}}"; '
        '[ -n "$DLCFN_WORKER_INDEX" ] && break; sleep 2; done',
        'if [ -z "$DLCFN_WORKER_INDEX" ]; then '
        "echo 'ERROR: worker index unavailable (metadata + env)'; exit 1; fi",
        'for _i in 1 2 3 4 5; do '
        f'DLCFN_BROKER="${{DLCFN_BROKER:-$({md}attributes/dlcfn-broker || true)}}"; '
        '[ -n "$DLCFN_BROKER" ] && break; sleep 2; done',
        'if [ -z "$DLCFN_BROKER" ]; then '
        "echo 'ERROR: broker address unavailable (metadata + env)'; exit 1; fi",
        # AUTH token rides the same metadata channel, with the same
        # retry discipline as the address fetch (transient metadata-server
        # unavailability at boot must not strand an auth-required
        # cluster).  curl exit 22 = an HTTP error (404: the attribute is
        # legitimately absent — open broker, older stack): stop
        # immediately instead of burning 10 s of retries on a value that
        # will never appear; any other failure is transient and retries.
        # `set -u` safety: the variable is usually unset here, so every
        # reference defaults it; `set -e` safety: the curl assignment runs
        # under `|| _rc=$?` so a failure reaches the retry logic instead
        # of aborting the boot script at the assignment.
        'if [ -z "${DLCFN_BROKER_TOKEN:-}" ]; then for _i in 1 2 3 4 5; do '
        f'_rc=0; _tok="$({md}attributes/dlcfn-broker-token)" || _rc=$?; '
        'if [ "$_rc" = "0" ]; then DLCFN_BROKER_TOKEN="$_tok"; break; fi; '
        '[ "$_rc" = "22" ] && break; sleep 2; done; fi',
        'DLCFN_BROKER_TOKEN="${DLCFN_BROKER_TOKEN:-}"',
        # Slice ordinal (multi-slice: one queued resource per slice, each
        # with its own worker 0) — only slice 0's worker 0 coordinates.
        f'DLCFN_SLICE="${{DLCFN_SLICE:-$({md}attributes/dlcfn-slice || true)}}"',
        'if [ "$DLCFN_WORKER_INDEX" = "0" ] && [ "${DLCFN_SLICE:-0}" = "0" ]; '
        'then DLCFN_ROLE="${DLCFN_ROLE:-coordinator}"; '
        'else DLCFN_ROLE="${DLCFN_ROLE:-worker}"; fi',
        f'DLCFN_GROUPS="${{DLCFN_GROUPS:-{shlex.quote(",".join(worker_group_names(spec.name, spec.pool.slices)))}}}"',
        f'DLCFN_MIN_SLICES="${{DLCFN_MIN_SLICES:-{spec.pool.min_slices or ""}}}"',
        f'DLCFN_STORAGE_MOUNT="${{DLCFN_STORAGE_MOUNT:-{shlex.quote(spec.storage.mount_point)}}}"',
        f'DLCFN_BOOTSTRAP_BUDGET_S="${{DLCFN_BOOTSTRAP_BUDGET_S:-{spec.timeouts.bootstrap_budget_s:.0f}}}"',
        f'DLCFN_POLL_INTERVAL_S="${{DLCFN_POLL_INTERVAL_S:-{spec.timeouts.poll_interval_s:g}}}"',
        "export DLCFN_WORKER_INDEX DLCFN_BROKER DLCFN_BROKER_TOKEN "
        "DLCFN_ROLE DLCFN_SLICE "
        "DLCFN_GROUPS DLCFN_MIN_SLICES DLCFN_STORAGE_MOUNT "
        "DLCFN_BOOTSTRAP_BUDGET_S DLCFN_POLL_INTERVAL_S",
        "exec python3 -m deeplearning_cfn_tpu.cluster.agent_main",
    ]
