"""The cluster contract: what discovery publishes for training jobs.

The reference's bootstrap ends by writing three artifacts every trainer
consumes (dl_cfn_setup_v2.py:92-116, documented README.md:89-97):

1. ``/etc/hosts`` names ``deeplearning-master`` / ``deeplearning-workerN``
   (dl_cfn_setup_v2.py:95-101) — consumed by run.sh's hostfile (run.sh:46-53).
2. ``/opt/deeplearning/workers`` — one hostname per line.
3. ``/etc/profile.d/deeplearning.sh`` exporting DEEPLEARNING_WORKERS_COUNT,
   DEEPLEARNING_WORKERS_PATH, DEEPLEARNING_WORKER_GPU_COUNT, EFS_MOUNT.

This module reproduces that contract (chips instead of GPUs) and extends it
with the field JAX actually needs that MPI got from mpirun: the coordinator
address + process count + process id for ``jax.distributed.initialize``.
The master-is-also-worker-0 rule and deterministic IP ordering are kept:
the coordinator's IP is prepended and the remainder sorted
(dl_cfn_setup_v2.py:330-342), so every node derives the identical worker
list independently.
"""

from __future__ import annotations

import json
import os
import shlex
from dataclasses import dataclass, field, asdict
from pathlib import Path

from deeplearning_cfn_tpu.utils.atomicio import atomic_write_text

COORDINATOR_HOSTNAME = "deeplearning-master"
WORKER_HOSTNAME_FMT = "deeplearning-worker{index}"
DEFAULT_COORDINATOR_PORT = 8476

# The broker wire protocol's canonical verb set — the single source of
# truth the cross-language contract checker (analysis/contract_check.py,
# DLC100) enforces against broker_client.py, broker_service.py, and the
# C++ dispatch chain in native/broker/broker.cpp.  Adding a verb to any
# one layer without the others fails `dlcfn lint`.
BROKER_PROTOCOL_VERBS = (
    "AUTH",   # AUTH <token>                     authenticate the connection
    "PING",   # PING                             liveness probe
    "SEND",   # SEND <queue> <nbytes>\n<body>    enqueue a message
    "RECV",   # RECV <queue> <max> <vis_ms>      lease up to max messages
    "DEL",    # DEL <queue> <receipt>            ack a leased message
    "DEPTH",  # DEPTH <queue>                    visible + in-flight counts
    "PURGE",  # PURGE <queue>                    drop all messages
    "SET",    # SET <key> <nbytes>\n<value>      kv store write
    "GET",    # GET <key>                        kv store read
    "UNSET",  # UNSET <key>                      kv store delete
    # HEARTBEAT <worker>                         record a liveness beat
    # HEARTBEAT                                  dump table: N <n> then HB lines
    "HEARTBEAT",
    # TELEM <worker> <nbytes>\n<snapshot>        record a telemetry snapshot
    # TELEM                                      dump snapshots: N <n> then TM frames
    "TELEM",
    # -- replication / leader handover (docs/RESILIENCE.md "Broker
    #    failover"): a warm standby replays the primary's journal and is
    #    promoted with a higher epoch; epoch fencing rejects the deposed
    #    primary's stale stream.
    "SENDID",   # SENDID <queue> <rid> <nbytes>\n<body>  enqueue, idempotent by rid
    "ROLE",     # ROLE                             report role, epoch, repl seq
    "PROMOTE",  # PROMOTE <epoch>                  fence to epoch, become primary
    "SYNC",     # SYNC <epoch> <seq> <nbytes>\n<entry>   replicate one journal frame
    # -- keyspace sharding (docs/RESILIENCE.md "Sharded broker"): each
    #    primary/standby pair owns one consistent-hash shard of the
    #    queue/KV/heartbeat keyspace; SHARD lets a router verify it
    #    dialed the owner of the keys it routes there.
    "SHARD",    # SHARD                            report shard index, total shards
)


@dataclass
class ClusterContract:
    cluster_name: str
    coordinator_ip: str
    worker_ips: list[str]  # coordinator first, rest sorted
    chips_per_worker: int
    storage_mount: str
    coordinator_port: int = DEFAULT_COORDINATOR_PORT
    degraded: bool = False
    tags: dict[str, str] = field(default_factory=dict)
    # Multi-slice topology: group name -> that slice's worker IPs, in
    # slice order (None/absent = single slice).  Lets compute build the
    # hybrid ICI x DCN mesh from the contract alone
    # (parallel/mesh.py:hybrid_mesh_for_slices).
    slices: dict[str, list[str]] | None = None

    @classmethod
    def build(
        cls,
        cluster_name: str,
        coordinator_ip: str,
        other_worker_ips: list[str],
        chips_per_worker: int,
        storage_mount: str,
        degraded: bool = False,
        slices: dict[str, list[str]] | None = None,
    ) -> "ClusterContract":
        # Coordinator doubles as worker 0 (StackSetup.md:110-111); its IP is
        # prepended and the rest sorted for a stable order (dl_cfn_setup_v2.py:330-342).
        #
        # Multi-slice: process ids follow worker_ips order, and
        # build_hybrid_mesh's process-granule fallback reshapes CONSECUTIVE
        # process blocks into the DCN axes (parallel/mesh.py) — so each
        # slice's IPs must stay contiguous (a global lexicographic sort
        # would interleave slices and silently put per-step ICI collectives
        # over DCN).  Coordinator's slice comes first (it holds process 0);
        # the stored ``slices`` is normalized so its concatenation IS
        # worker_ips.
        if slices:
            coord_slices = [
                g for g, ips in slices.items() if coordinator_ip in ips
            ]
            if not coord_slices:
                # Prepending the coordinator outside the topology would
                # shift every process id by one relative to the slices —
                # the exact misalignment this ordering exists to prevent.
                raise ValueError(
                    f"coordinator {coordinator_ip} is not in any slice"
                )
            n_coord = sum(
                ips.count(coordinator_ip) for ips in slices.values()
            )
            if n_coord > 1:
                # Silently stripping the extra occurrences would publish a
                # slice smaller than discovery reported and shift the
                # process-id -> slice mapping.
                raise ValueError(
                    f"coordinator {coordinator_ip} appears {n_coord} times "
                    f"in the slice topology (slices {sorted(coord_slices)})"
                )
            coord_slice = coord_slices[0]
            names = sorted(slices, key=lambda g: (g != coord_slice, g))
            norm: dict[str, list[str]] = {}
            for g in names:
                members = sorted(ip for ip in slices[g] if ip != coordinator_ip)
                if g == coord_slice:
                    members = [coordinator_ip] + members
                norm[g] = members
            worker_ips = [ip for ips in norm.values() for ip in ips]
            covered = set(worker_ips)
            if len(worker_ips) != len(covered):
                dupes = sorted(
                    {ip for ip in worker_ips if worker_ips.count(ip) > 1}
                )
                raise ValueError(f"duplicate IPs in slice topology: {dupes}")
            known = set(other_worker_ips) | {coordinator_ip}
            leftover = sorted(known - covered)
            if leftover:
                raise ValueError(
                    f"worker IPs missing from slice topology: {leftover}"
                )
            phantom = sorted(covered - known)
            if phantom:
                raise ValueError(
                    f"slice topology names IPs discovery never reported: {phantom}"
                )
            slices = norm
        else:
            rest = sorted(ip for ip in other_worker_ips if ip != coordinator_ip)
            worker_ips = [coordinator_ip] + rest
        return cls(
            cluster_name=cluster_name,
            coordinator_ip=coordinator_ip,
            worker_ips=worker_ips,
            chips_per_worker=chips_per_worker,
            storage_mount=storage_mount,
            degraded=degraded,
            slices=slices,
        )

    def surviving(self, lost_groups) -> "ClusterContract":
        """The post-loss contract: the same cluster minus the dead slices.

        This is the topology half of live elastic resharding
        (docs/RESILIENCE.md): when the liveness plane declares a slice
        dead, the trainer re-forms its mesh from THIS derivation instead
        of waiting for a reprovision.  Raises ``ValueError`` when a live
        reshard is structurally impossible — no slice topology at all,
        none of the named groups are slices here (idempotence against
        duplicate/stale loss notifications is the caller's job), nothing
        survives, or the coordinator's own slice died (process 0 is gone;
        only the restart-provision path can help).  Goes through
        :meth:`build` so the survivor ordering invariants (coordinator's
        slice first, contiguous slices) are re-validated, and is marked
        ``degraded`` — the same flag the launch-error path sets.
        """
        if not self.slices:
            raise ValueError(
                "contract has no slice topology; cannot derive survivors"
            )
        lost = {g for g in lost_groups if g in self.slices}
        if not lost:
            raise ValueError(
                f"none of {sorted(set(lost_groups))} are slices of this "
                f"contract (slices: {sorted(self.slices)})"
            )
        keep = {g: list(ips) for g, ips in self.slices.items() if g not in lost}
        if not keep:
            raise ValueError("no surviving slices; full reprovision required")
        survivors = [ip for ips in keep.values() for ip in ips]
        if self.coordinator_ip not in survivors:
            raise ValueError(
                f"coordinator {self.coordinator_ip}'s slice was lost; live "
                "reshard impossible (process 0 is gone) — use the "
                "recreate-and-restore path"
            )
        contract = ClusterContract.build(
            cluster_name=self.cluster_name,
            coordinator_ip=self.coordinator_ip,
            other_worker_ips=[ip for ip in survivors if ip != self.coordinator_ip],
            chips_per_worker=self.chips_per_worker,
            storage_mount=self.storage_mount,
            degraded=True,
            slices=keep,
        )
        contract.coordinator_port = self.coordinator_port
        contract.tags = dict(self.tags)
        return contract

    def restored(
        self, regained: dict[str, list[str]], degraded: bool = False
    ) -> "ClusterContract":
        """The grow-back derivation — ``surviving()``'s inverse: the same
        cluster plus slices returning to it (a lent slice coming home
        after a scheduler preemption resolves, or a reprovisioned slice
        rejoining).  Goes through :meth:`build` so the ordering
        invariants (coordinator's slice first, contiguous slices) are
        re-validated on the grown topology; ``degraded`` defaults to
        False — a restore is the cluster returning to strength.  Raises
        ``ValueError`` when there is no slice topology, a regained group
        is already present, or a regained IP is already a worker.
        """
        if not self.slices:
            raise ValueError(
                "contract has no slice topology; cannot restore slices into it"
            )
        if not regained:
            raise ValueError("no slices to restore")
        already = sorted(set(regained) & set(self.slices))
        if already:
            raise ValueError(f"slices already present: {already}")
        merged = {g: list(ips) for g, ips in self.slices.items()}
        merged.update({g: list(ips) for g, ips in regained.items()})
        contract = ClusterContract.build(
            cluster_name=self.cluster_name,
            coordinator_ip=self.coordinator_ip,
            other_worker_ips=[
                ip
                for ips in merged.values()
                for ip in ips
                if ip != self.coordinator_ip
            ],
            chips_per_worker=self.chips_per_worker,
            storage_mount=self.storage_mount,
            degraded=degraded,
            slices=merged,
        )
        contract.coordinator_port = self.coordinator_port
        contract.tags = dict(self.tags)
        return contract

    # --- derived views ----------------------------------------------------
    @property
    def workers_count(self) -> int:
        return len(self.worker_ips)

    @property
    def slices_count(self) -> int:
        return len(self.slices) if self.slices else 1

    @property
    def total_chips(self) -> int:
        return self.workers_count * self.chips_per_worker

    def slice_inventory(self) -> dict[str, int]:
        """Slice name -> chips: the fleet scheduler's placement currency
        (sched/placer.py).  A single-slice contract exposes its whole
        capacity under the one name the arbiter can reason about."""
        if self.slices:
            return {
                g: len(ips) * self.chips_per_worker
                for g, ips in self.slices.items()
            }
        return {"all": self.total_chips}

    def hostnames(self) -> list[str]:
        # worker0 answers to both names, as in the reference where the master
        # appears in /etc/hosts as deeplearning-master AND heads the list.
        return [COORDINATOR_HOSTNAME] + [
            WORKER_HOSTNAME_FMT.format(index=i + 1) for i in range(self.workers_count - 1)
        ]

    def hosts_entries(self) -> list[tuple[str, str]]:
        return list(zip(self.worker_ips, self.hostnames()))

    def datastream_hosts(self) -> tuple[str, ...]:
        """The data plane's host ordering (train/datastream): shard
        assignment is positional over this tuple, so it must be the
        contract's canonical worker order — coordinator's slice first,
        slices contiguous (``build()`` normalizes exactly that).  A
        ``surviving()`` contract preserves relative order, which is what
        keeps reassignment deterministic across a live reshard."""
        return tuple(self.worker_ips)

    def env(self, root: Path | None = None) -> dict[str, str]:
        """The DEEPLEARNING_* contract (dl_cfn_setup_v2.py:104-109), chips
        instead of GPUs, plus the jax.distributed coordination triple.

        ``root`` must be the directory the contract was (or will be)
        published to, so DEEPLEARNING_WORKERS_PATH points at the workers
        file that actually exists."""
        root = root or self.root_dir()
        return {
            "DEEPLEARNING_WORKERS_COUNT": str(self.workers_count),
            "DEEPLEARNING_WORKERS_PATH": str(root / "workers"),
            "DEEPLEARNING_WORKER_CHIP_COUNT": str(self.chips_per_worker),
            "DEEPLEARNING_STORAGE_MOUNT": self.storage_mount,
            "DEEPLEARNING_COORDINATOR": f"{self.coordinator_ip}:{self.coordinator_port}",
            "DEEPLEARNING_CLUSTER_NAME": self.cluster_name,
            "DEEPLEARNING_DEGRADED": "1" if self.degraded else "0",
            "DEEPLEARNING_SLICES_COUNT": str(self.slices_count),
        }

    def jax_initialize_kwargs(self, process_id: int) -> dict[str, object]:
        """Arguments for jax.distributed.initialize — the rendezvous MPI's
        mpirun provided in the reference (run.sh:72-77), without SSH."""
        return {
            "coordinator_address": f"{self.coordinator_ip}:{self.coordinator_port}",
            "num_processes": self.workers_count,
            "process_id": process_id,
        }

    # --- filesystem publication ------------------------------------------
    @staticmethod
    def root_dir() -> Path:
        return Path(os.environ.get("DLCFN_ROOT", "/opt/deeplearning"))

    def workers_file_path(self) -> Path:
        return self.root_dir() / "workers"

    def write(self, root: Path | None = None) -> Path:
        # Atomic per file: on-VM agents read these while the coordinator
        # (re)publishes them — a torn contract.json must be impossible.
        root = root or self.root_dir()
        root.mkdir(parents=True, exist_ok=True)
        atomic_write_text(
            root / "workers", "".join(f"{h}\n" for h in self.hostnames())
        )
        atomic_write_text(
            root / "hosts",
            "".join(f"{ip} {host}\n" for ip, host in self.hosts_entries()),
        )
        atomic_write_text(
            root / "env.sh",
            "".join(
                f"export {k}={shlex.quote(v)}\n" for k, v in self.env(root).items()
            ),
        )
        atomic_write_text(
            root / "contract.json", json.dumps(asdict(self), indent=2)
        )
        return root

    @classmethod
    def read(cls, root: Path | None = None) -> "ClusterContract":
        root = root or cls.root_dir()
        return cls(**json.loads((root / "contract.json").read_text()))

    def to_message(self) -> dict[str, object]:
        """The worker-setup broadcast body (dl_cfn_setup_v2.py:346-357)."""
        return {
            "event": "worker-setup",
            "status": "success",
            "coordinator-ip": self.coordinator_ip,
            "worker-ips": self.worker_ips,
            "chips-per-worker": self.chips_per_worker,
            "storage-mount": self.storage_mount,
            "degraded": self.degraded,
            "cluster": self.cluster_name,
            "coordinator-port": self.coordinator_port,
            "tags": self.tags,
            "slices": self.slices,
        }

    @classmethod
    def from_message(cls, body: dict[str, object]) -> "ClusterContract":
        return cls(
            cluster_name=str(body["cluster"]),
            coordinator_ip=str(body["coordinator-ip"]),
            worker_ips=list(body["worker-ips"]),  # type: ignore[arg-type]
            chips_per_worker=int(body["chips-per-worker"]),  # type: ignore[arg-type]
            storage_mount=str(body["storage-mount"]),
            degraded=bool(body.get("degraded", False)),
            coordinator_port=int(body.get("coordinator-port", DEFAULT_COORDINATOR_PORT)),  # type: ignore[arg-type]
            tags=dict(body.get("tags", {})),  # type: ignore[arg-type]
            slices=body.get("slices"),  # type: ignore[arg-type]
        )
